"""The final 29 TPC-DS queries (completing 99/99), adapted like the rest of
``queries.py``: clause structure follows the public spec text
(reference ships these in ``benchmarking/tpcds/queries/*.sql``); literal
vocabularies (years 1999-2001, county/color/carrier names, d_month_seq base
1200) match the synthetic datagen so results are non-degenerate.

Families added here: cross-year customer-growth self-joins (4/11/74),
bucketed scalar-subquery CASE (9/28), EXISTS-disjunctions (10/35),
channel return-ratio windows (49), cumulative full-outer windows (51),
ROLLUP + GROUPING() with ranked hierarchies (36/70/86), county quarter
deltas (31), item-week pivots (58/83), inventory/promo supply chains
(64/66/72), frequent-item cohorts (14/23/24/54), channel-ratio reports
(44/45/57/75/78), and 12-shape revenue ratios (12).
"""

Q4 = """
WITH year_total AS
  (SELECT c_customer_id customer_id, c_first_name customer_first_name,
          c_last_name customer_last_name,
          c_preferred_cust_flag customer_preferred_cust_flag,
          c_birth_country customer_birth_country,
          c_login customer_login, c_email_address customer_email_address,
          d_year dyear,
          SUM(((ss_ext_list_price - ss_ext_wholesale_cost
                - ss_ext_discount_amt) + ss_ext_sales_price) / 2) year_total,
          's' sale_type
   FROM customer, store_sales, date_dim
   WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
            c_birth_country, c_login, c_email_address, d_year
   UNION ALL
   SELECT c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
          c_birth_country, c_login, c_email_address, d_year,
          SUM(((cs_ext_list_price - cs_ext_wholesale_cost
                - cs_ext_discount_amt) + cs_ext_sales_price) / 2),
          'c' sale_type
   FROM customer, catalog_sales, date_dim
   WHERE c_customer_sk = cs_bill_customer_sk AND cs_sold_date_sk = d_date_sk
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
            c_birth_country, c_login, c_email_address, d_year
   UNION ALL
   SELECT c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
          c_birth_country, c_login, c_email_address, d_year,
          SUM(((ws_ext_list_price - ws_ext_wholesale_cost
                - ws_ext_discount_amt) + ws_ext_sales_price) / 2),
          'w' sale_type
   FROM customer, web_sales, date_dim
   WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
            c_birth_country, c_login, c_email_address, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       t_s_secyear.customer_preferred_cust_flag
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_c_secyear.customer_id
  AND t_s_firstyear.customer_id = t_c_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_c_firstyear.sale_type = 'c'
  AND t_w_firstyear.sale_type = 'w' AND t_s_secyear.sale_type = 's'
  AND t_c_secyear.sale_type = 'c' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2000 AND t_s_secyear.dyear = 2000 + 1
  AND t_c_firstyear.dyear = 2000 AND t_c_secyear.dyear = 2000 + 1
  AND t_w_firstyear.dyear = 2000 AND t_w_secyear.dyear = 2000 + 1
  AND t_s_firstyear.year_total > 0 AND t_c_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN (t_c_secyear.year_total * 1.0000) / t_c_firstyear.year_total
           ELSE NULL END
    > CASE WHEN t_s_firstyear.year_total > 0
           THEN (t_s_secyear.year_total * 1.0000) / t_s_firstyear.year_total
           ELSE NULL END
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN (t_c_secyear.year_total * 1.0000) / t_c_firstyear.year_total
           ELSE NULL END
    > CASE WHEN t_w_firstyear.year_total > 0
           THEN (t_w_secyear.year_total * 1.0000) / t_w_firstyear.year_total
           ELSE NULL END
ORDER BY t_s_secyear.customer_id, t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name,
         t_s_secyear.customer_preferred_cust_flag
LIMIT 100
"""

Q9 = """
SELECT CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > 1000
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END bucket1,
       CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > 1000
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END bucket2,
       CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > 1000
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END bucket3,
       CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80) > 1000
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80) END bucket4,
       CASE WHEN (SELECT COUNT(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100) > 1000
            THEN (SELECT AVG(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100)
            ELSE (SELECT AVG(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100) END bucket5
FROM reason
WHERE r_reason_sk = 1
"""

Q10 = """
SELECT cd_gender, cd_marital_status, cd_education_status, COUNT(*) cnt1,
       cd_purchase_estimate, COUNT(*) cnt2, cd_credit_rating, COUNT(*) cnt3,
       cd_dep_count, COUNT(*) cnt4, cd_dep_employed_count, COUNT(*) cnt5,
       cd_dep_college_count, COUNT(*) cnt6
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('Ziebach County', 'Williamson County', 'Walker County')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2001
                AND d_moy BETWEEN 1 AND 1 + 3)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk AND d_year = 2001
                 AND d_moy BETWEEN 1 AND 1 + 3)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk AND d_year = 2001
                    AND d_moy BETWEEN 1 AND 1 + 3))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
LIMIT 100
"""

Q11 = """
WITH year_total AS
  (SELECT c_customer_id customer_id, c_first_name customer_first_name,
          c_last_name customer_last_name,
          c_preferred_cust_flag customer_preferred_cust_flag,
          c_birth_country customer_birth_country, c_login customer_login,
          c_email_address customer_email_address, d_year dyear,
          SUM(ss_ext_list_price - ss_ext_discount_amt) year_total,
          's' sale_type
   FROM customer, store_sales, date_dim
   WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
            c_birth_country, c_login, c_email_address, d_year
   UNION ALL
   SELECT c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
          c_birth_country, c_login, c_email_address, d_year,
          SUM(ws_ext_list_price - ws_ext_discount_amt), 'w' sale_type
   FROM customer, web_sales, date_dim
   WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
            c_birth_country, c_login, c_email_address, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       t_s_secyear.customer_preferred_cust_flag
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2000 AND t_s_secyear.dyear = 2000 + 1
  AND t_w_firstyear.dyear = 2000 AND t_w_secyear.dyear = 2000 + 1
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN (t_w_secyear.year_total * 1.0000) / t_w_firstyear.year_total
           ELSE 0.0 END
    > CASE WHEN t_s_firstyear.year_total > 0
           THEN (t_s_secyear.year_total * 1.0000) / t_s_firstyear.year_total
           ELSE 0.0 END
ORDER BY t_s_secyear.customer_id, t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name,
         t_s_secyear.customer_preferred_cust_flag
LIMIT 100
"""

Q12 = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       SUM(ws_ext_sales_price) AS itemrevenue,
       SUM(ws_ext_sales_price) * 100.0000
         / SUM(SUM(ws_ext_sales_price)) OVER (PARTITION BY i_class)
         AS revenueratio
FROM web_sales, item, date_dim
WHERE ws_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND ws_sold_date_sk = d_date_sk
  AND d_date BETWEEN CAST('1999-02-22' AS DATE)
                 AND CAST('1999-03-24' AS DATE)
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

Q14 = """
WITH cross_items AS
  (SELECT i_item_sk ss_item_sk
   FROM item,
     (SELECT iss.i_brand_id brand_id, iss.i_class_id class_id,
             iss.i_category_id category_id
      FROM store_sales, item iss, date_dim d1
      WHERE ss_item_sk = iss.i_item_sk AND ss_sold_date_sk = d1.d_date_sk
        AND d1.d_year BETWEEN 1999 AND 1999 + 2
      INTERSECT
      SELECT ics.i_brand_id, ics.i_class_id, ics.i_category_id
      FROM catalog_sales, item ics, date_dim d2
      WHERE cs_item_sk = ics.i_item_sk AND cs_sold_date_sk = d2.d_date_sk
        AND d2.d_year BETWEEN 1999 AND 1999 + 2
      INTERSECT
      SELECT iws.i_brand_id, iws.i_class_id, iws.i_category_id
      FROM web_sales, item iws, date_dim d3
      WHERE ws_item_sk = iws.i_item_sk AND ws_sold_date_sk = d3.d_date_sk
        AND d3.d_year BETWEEN 1999 AND 1999 + 2) sq1
   WHERE i_brand_id = brand_id AND i_class_id = class_id
     AND i_category_id = category_id),
     avg_sales AS
  (SELECT AVG(quantity * list_price) average_sales
   FROM (SELECT ss_quantity quantity, ss_list_price list_price
         FROM store_sales, date_dim
         WHERE ss_sold_date_sk = d_date_sk
           AND d_year BETWEEN 1999 AND 1999 + 2
         UNION ALL
         SELECT cs_quantity, cs_list_price
         FROM catalog_sales, date_dim
         WHERE cs_sold_date_sk = d_date_sk
           AND d_year BETWEEN 1999 AND 1999 + 2
         UNION ALL
         SELECT ws_quantity, ws_list_price
         FROM web_sales, date_dim
         WHERE ws_sold_date_sk = d_date_sk
           AND d_year BETWEEN 1999 AND 1999 + 2) sq2)
SELECT channel, i_brand_id, i_class_id, i_category_id,
       SUM(sales) AS sum_sales, SUM(number_sales) AS sum_number_sales
FROM
  (SELECT 'store' channel, i_brand_id, i_class_id, i_category_id,
          SUM(ss_quantity * ss_list_price) sales, COUNT(*) number_sales
   FROM store_sales, item, date_dim
   WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
     AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
     AND d_year = 1999 + 2 AND d_moy = 11
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING SUM(ss_quantity * ss_list_price)
          > (SELECT average_sales FROM avg_sales)
   UNION ALL
   SELECT 'catalog' channel, i_brand_id, i_class_id, i_category_id,
          SUM(cs_quantity * cs_list_price) sales, COUNT(*) number_sales
   FROM catalog_sales, item, date_dim
   WHERE cs_item_sk IN (SELECT ss_item_sk FROM cross_items)
     AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
     AND d_year = 1999 + 2 AND d_moy = 11
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING SUM(cs_quantity * cs_list_price)
          > (SELECT average_sales FROM avg_sales)
   UNION ALL
   SELECT 'web' channel, i_brand_id, i_class_id, i_category_id,
          SUM(ws_quantity * ws_list_price) sales, COUNT(*) number_sales
   FROM web_sales, item, date_dim
   WHERE ws_item_sk IN (SELECT ss_item_sk FROM cross_items)
     AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
     AND d_year = 1999 + 2 AND d_moy = 11
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING SUM(ws_quantity * ws_list_price)
          > (SELECT average_sales FROM avg_sales)) y
GROUP BY ROLLUP (channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel, i_brand_id, i_class_id, i_category_id
LIMIT 100
"""

Q17 = """
SELECT i_item_id, i_item_desc, s_state,
       COUNT(ss_quantity) AS store_sales_quantitycount,
       AVG(ss_quantity) AS store_sales_quantityave,
       STDDEV(ss_quantity) AS store_sales_quantitystdev,
       STDDEV(ss_quantity) / AVG(ss_quantity) AS store_sales_quantitycov,
       COUNT(sr_return_quantity) AS store_returns_quantitycount,
       AVG(sr_return_quantity) AS store_returns_quantityave,
       STDDEV(sr_return_quantity) AS store_returns_quantitystdev,
       STDDEV(sr_return_quantity) / AVG(sr_return_quantity)
         AS store_returns_quantitycov,
       COUNT(cs_quantity) AS catalog_sales_quantitycount,
       AVG(cs_quantity) AS catalog_sales_quantityave,
       STDDEV(cs_quantity) AS catalog_sales_quantitystdev,
       STDDEV(cs_quantity) / AVG(cs_quantity) AS catalog_sales_quantitycov
FROM store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
WHERE d1.d_quarter_name = '2000Q1'
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_quarter_name IN ('2000Q1', '2000Q2', '2000Q3')
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_quarter_name IN ('2000Q1', '2000Q2', '2000Q3')
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id, i_item_desc, s_state
LIMIT 100
"""

Q23 = """
WITH frequent_ss_items AS
  (SELECT itemdesc, i_item_sk item_sk, d_date solddate, COUNT(*) cnt
   FROM store_sales, date_dim,
        (SELECT SUBSTR(i_item_desc, 1, 30) itemdesc, * FROM item) sq1
   WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
     AND d_year IN (1999, 1999 + 1, 1999 + 2)
   GROUP BY itemdesc, i_item_sk, d_date
   HAVING COUNT(*) > 4),
     max_store_sales AS
  (SELECT MAX(csales) tpcds_cmax
   FROM (SELECT c_customer_sk, SUM(ss_quantity * ss_sales_price) csales
         FROM store_sales, customer, date_dim
         WHERE ss_customer_sk = c_customer_sk
           AND ss_sold_date_sk = d_date_sk
           AND d_year IN (1999, 1999 + 1, 1999 + 2)
         GROUP BY c_customer_sk) sq2),
     best_ss_customer AS
  (SELECT c_customer_sk, SUM(ss_quantity * ss_sales_price) ssales
   FROM store_sales, customer, max_store_sales
   WHERE ss_customer_sk = c_customer_sk
   GROUP BY c_customer_sk
   HAVING SUM(ss_quantity * ss_sales_price) > (50 / 100.0) * MAX(tpcds_cmax))
SELECT c_last_name, c_first_name, sales
FROM (SELECT c_last_name, c_first_name,
             SUM(cs_quantity * cs_list_price) sales
      FROM catalog_sales, customer, date_dim, frequent_ss_items,
           best_ss_customer
      WHERE d_year = 2000 AND d_moy = 2 AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk = item_sk
        AND cs_bill_customer_sk = best_ss_customer.c_customer_sk
        AND cs_bill_customer_sk = customer.c_customer_sk
      GROUP BY c_last_name, c_first_name
      UNION ALL
      SELECT c_last_name, c_first_name,
             SUM(ws_quantity * ws_list_price) sales
      FROM web_sales, customer, date_dim, frequent_ss_items,
           best_ss_customer
      WHERE d_year = 2000 AND d_moy = 2 AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk = item_sk
        AND ws_bill_customer_sk = best_ss_customer.c_customer_sk
        AND ws_bill_customer_sk = customer.c_customer_sk
      GROUP BY c_last_name, c_first_name) sq3
ORDER BY c_last_name, c_first_name, sales
LIMIT 100
"""

Q24 = """
WITH ssales AS
  (SELECT c_last_name, c_first_name, s_store_name, ca_state, s_state,
          i_color, i_current_price, i_manager_id, i_units, i_size,
          SUM(ss_net_paid) netpaid
   FROM store_sales, store_returns, store, item, customer,
        customer_address
   WHERE ss_ticket_number = sr_ticket_number AND ss_item_sk = sr_item_sk
     AND ss_customer_sk = c_customer_sk AND ss_item_sk = i_item_sk
     AND ss_store_sk = s_store_sk AND c_current_addr_sk = ca_address_sk
     AND c_birth_country <> UPPER(ca_country)
     AND s_market_id = 8
   GROUP BY c_last_name, c_first_name, s_store_name, ca_state, s_state,
            i_color, i_current_price, i_manager_id, i_units, i_size)
SELECT c_last_name, c_first_name, s_store_name, SUM(netpaid) paid
FROM ssales
WHERE i_color = 'peach'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING SUM(netpaid) > (SELECT 0.05 * AVG(netpaid) FROM ssales)
ORDER BY c_last_name, c_first_name, s_store_name
"""

Q28 = """
SELECT *
FROM (SELECT AVG(ss_list_price) b1_lp, COUNT(ss_list_price) b1_cnt,
             COUNT(DISTINCT ss_list_price) b1_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 0 AND 5
        AND (ss_list_price BETWEEN 8 AND 8 + 10
             OR ss_coupon_amt BETWEEN 459 AND 459 + 1000
             OR ss_wholesale_cost BETWEEN 57 AND 57 + 20)) b1,
     (SELECT AVG(ss_list_price) b2_lp, COUNT(ss_list_price) b2_cnt,
             COUNT(DISTINCT ss_list_price) b2_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 6 AND 10
        AND (ss_list_price BETWEEN 90 AND 90 + 10
             OR ss_coupon_amt BETWEEN 2323 AND 2323 + 1000
             OR ss_wholesale_cost BETWEEN 31 AND 31 + 20)) b2,
     (SELECT AVG(ss_list_price) b3_lp, COUNT(ss_list_price) b3_cnt,
             COUNT(DISTINCT ss_list_price) b3_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 11 AND 15
        AND (ss_list_price BETWEEN 142 AND 142 + 10
             OR ss_coupon_amt BETWEEN 12214 AND 12214 + 1000
             OR ss_wholesale_cost BETWEEN 79 AND 79 + 20)) b3,
     (SELECT AVG(ss_list_price) b4_lp, COUNT(ss_list_price) b4_cnt,
             COUNT(DISTINCT ss_list_price) b4_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 16 AND 20
        AND (ss_list_price BETWEEN 135 AND 135 + 10
             OR ss_coupon_amt BETWEEN 6071 AND 6071 + 1000
             OR ss_wholesale_cost BETWEEN 38 AND 38 + 20)) b4,
     (SELECT AVG(ss_list_price) b5_lp, COUNT(ss_list_price) b5_cnt,
             COUNT(DISTINCT ss_list_price) b5_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 21 AND 25
        AND (ss_list_price BETWEEN 122 AND 122 + 10
             OR ss_coupon_amt BETWEEN 836 AND 836 + 1000
             OR ss_wholesale_cost BETWEEN 17 AND 17 + 20)) b5,
     (SELECT AVG(ss_list_price) b6_lp, COUNT(ss_list_price) b6_cnt,
             COUNT(DISTINCT ss_list_price) b6_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 26 AND 30
        AND (ss_list_price BETWEEN 154 AND 154 + 10
             OR ss_coupon_amt BETWEEN 7326 AND 7326 + 1000
             OR ss_wholesale_cost BETWEEN 7 AND 7 + 20)) b6
LIMIT 100
"""

Q31 = """
WITH ss AS
  (SELECT ca_county, d_qoy, d_year,
          SUM(ss_ext_sales_price) AS store_sales
   FROM store_sales, date_dim, customer_address
   WHERE ss_sold_date_sk = d_date_sk AND ss_addr_sk = ca_address_sk
   GROUP BY ca_county, d_qoy, d_year),
     ws AS
  (SELECT ca_county, d_qoy, d_year,
          SUM(ws_ext_sales_price) AS web_sales
   FROM web_sales, date_dim, customer_address
   WHERE ws_sold_date_sk = d_date_sk AND ws_bill_addr_sk = ca_address_sk
   GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county, ss1.d_year,
       (ws2.web_sales * 1.0000) / ws1.web_sales web_q1_q2_increase,
       (ss2.store_sales * 1.0000) / ss1.store_sales store_q1_q2_increase,
       (ws3.web_sales * 1.0000) / ws2.web_sales web_q2_q3_increase,
       (ss3.store_sales * 1.0000) / ss2.store_sales store_q2_q3_increase
FROM ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
WHERE ss1.d_qoy = 1 AND ss1.d_year = 2000
  AND ss1.ca_county = ss2.ca_county AND ss2.d_qoy = 2
  AND ss2.d_year = 2000
  AND ss2.ca_county = ss3.ca_county AND ss3.d_qoy = 3
  AND ss3.d_year = 2000
  AND ss1.ca_county = ws1.ca_county AND ws1.d_qoy = 1
  AND ws1.d_year = 2000
  AND ws1.ca_county = ws2.ca_county AND ws2.d_qoy = 2
  AND ws2.d_year = 2000
  AND ws1.ca_county = ws3.ca_county AND ws3.d_qoy = 3
  AND ws3.d_year = 2000
  AND CASE WHEN ws1.web_sales > 0
           THEN (ws2.web_sales * 1.0000) / ws1.web_sales ELSE NULL END
    > CASE WHEN ss1.store_sales > 0
           THEN (ss2.store_sales * 1.0000) / ss1.store_sales
           ELSE NULL END
  AND CASE WHEN ws2.web_sales > 0
           THEN (ws3.web_sales * 1.0000) / ws2.web_sales ELSE NULL END
    > CASE WHEN ss2.store_sales > 0
           THEN (ss3.store_sales * 1.0000) / ss2.store_sales
           ELSE NULL END
ORDER BY ss1.ca_county
"""

Q35 = """
SELECT ca_state, cd_gender, cd_marital_status, cd_dep_count,
       COUNT(*) cnt1, MIN(cd_dep_count) min1, MAX(cd_dep_count) max1,
       AVG(cd_dep_count) avg1, cd_dep_employed_count, COUNT(*) cnt2,
       MIN(cd_dep_employed_count) min2, MAX(cd_dep_employed_count) max2,
       AVG(cd_dep_employed_count) avg2, cd_dep_college_count,
       COUNT(*) cnt3, MIN(cd_dep_college_count) min3,
       MAX(cd_dep_college_count) max3, AVG(cd_dep_college_count) avg3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2001
                AND d_qoy < 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk AND d_year = 2001
                 AND d_qoy < 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk AND d_year = 2001
                    AND d_qoy < 4))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
LIMIT 100
"""

Q36 = """
WITH results AS
  (SELECT SUM(ss_net_profit) AS ss_net_profit,
          SUM(ss_ext_sales_price) AS ss_ext_sales_price,
          (SUM(ss_net_profit) * 1.0000) / SUM(ss_ext_sales_price)
            AS gross_margin,
          i_category, i_class, 0 AS g_category, 0 AS g_class
   FROM store_sales, date_dim d1, item, store
   WHERE d1.d_year = 2000 AND d1.d_date_sk = ss_sold_date_sk
     AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
     AND s_state = 'TN'
   GROUP BY i_category, i_class),
     results_rollup AS
  (SELECT gross_margin, i_category, i_class, 0 AS t_category,
          0 AS t_class, 0 AS lochierarchy
   FROM results
   UNION
   SELECT (SUM(ss_net_profit) * 1.0000) / SUM(ss_ext_sales_price)
            AS gross_margin,
          i_category, NULL AS i_class, 0 AS t_category, 1 AS t_class,
          1 AS lochierarchy
   FROM results GROUP BY i_category
   UNION
   SELECT (SUM(ss_net_profit) * 1.0000) / SUM(ss_ext_sales_price)
            AS gross_margin,
          NULL AS i_category, NULL AS i_class, 1 AS t_category,
          1 AS t_class, 2 AS lochierarchy
   FROM results)
SELECT gross_margin, i_category, i_class, lochierarchy,
       RANK() OVER (PARTITION BY lochierarchy,
                                 CASE WHEN t_class = 0 THEN i_category END
                    ORDER BY gross_margin ASC) AS rank_within_parent
FROM results_rollup
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
"""

Q44 = """
SELECT asceding.rnk, i1.i_product_name best_performing,
       i2.i_product_name worst_performing
FROM (SELECT *
      FROM (SELECT item_sk, RANK() OVER (ORDER BY rank_col ASC) rnk
            FROM (SELECT ss_item_sk item_sk, AVG(ss_net_profit) rank_col
                  FROM store_sales ss1
                  WHERE ss_store_sk = 4
                  GROUP BY ss_item_sk
                  HAVING AVG(ss_net_profit) > 0.9 *
                    (SELECT AVG(ss_net_profit) rank_col
                     FROM store_sales
                     WHERE ss_store_sk = 4 AND ss_addr_sk IS NULL
                     GROUP BY ss_store_sk)) v1) v11
      WHERE rnk < 11) asceding,
     (SELECT *
      FROM (SELECT item_sk, RANK() OVER (ORDER BY rank_col DESC) rnk
            FROM (SELECT ss_item_sk item_sk, AVG(ss_net_profit) rank_col
                  FROM store_sales ss1
                  WHERE ss_store_sk = 4
                  GROUP BY ss_item_sk
                  HAVING AVG(ss_net_profit) > 0.9 *
                    (SELECT AVG(ss_net_profit) rank_col
                     FROM store_sales
                     WHERE ss_store_sk = 4 AND ss_addr_sk IS NULL
                     GROUP BY ss_store_sk)) v2) v21
      WHERE rnk < 11) descending,
     item i1, item i2
WHERE asceding.rnk = descending.rnk
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk
LIMIT 100
"""

Q45 = """
SELECT ca_zip, ca_city, SUM(ws_sales_price) AS total_sales
FROM web_sales, customer, customer_address, date_dim, item
WHERE ws_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ws_item_sk = i_item_sk
  AND (SUBSTR(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348',
                                '81792')
       OR i_item_id IN (SELECT i_item_id FROM item
                        WHERE i_item_sk IN (2, 3, 5, 7, 11, 13, 17, 19,
                                            23, 29)))
  AND ws_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2000
GROUP BY ca_zip, ca_city
ORDER BY ca_zip, ca_city
LIMIT 100
"""

Q49 = """
SELECT channel, item, return_ratio, return_rank, currency_rank
FROM
  (SELECT 'web' AS channel, web.item, web.return_ratio,
          web.return_rank, web.currency_rank
   FROM (SELECT item, return_ratio, currency_ratio,
                RANK() OVER (ORDER BY return_ratio) AS return_rank,
                RANK() OVER (ORDER BY currency_ratio) AS currency_rank
         FROM (SELECT ws.ws_item_sk AS item,
                      (SUM(COALESCE(wr.wr_return_quantity, 0)) * 1.0000)
                        / SUM(COALESCE(ws.ws_quantity, 0)) AS return_ratio,
                      (SUM(COALESCE(wr.wr_return_amt, 0)) * 1.0000)
                        / SUM(COALESCE(ws.ws_net_paid, 0))
                        AS currency_ratio
               FROM web_sales ws
               LEFT OUTER JOIN web_returns wr
                 ON (ws.ws_order_number = wr.wr_order_number
                     AND ws.ws_item_sk = wr.wr_item_sk), date_dim
               WHERE wr.wr_return_amt > 100
                 AND ws.ws_net_profit > 1 AND ws.ws_net_paid > 0
                 AND ws.ws_quantity > 0 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2000 AND d_moy = 12
               GROUP BY ws.ws_item_sk) in_web) web
   WHERE web.return_rank <= 10 OR web.currency_rank <= 10
   UNION
   SELECT 'catalog' AS channel, catalog.item, catalog.return_ratio,
          catalog.return_rank, catalog.currency_rank
   FROM (SELECT item, return_ratio, currency_ratio,
                RANK() OVER (ORDER BY return_ratio) AS return_rank,
                RANK() OVER (ORDER BY currency_ratio) AS currency_rank
         FROM (SELECT cs.cs_item_sk AS item,
                      (SUM(COALESCE(cr.cr_return_quantity, 0)) * 1.0000)
                        / SUM(COALESCE(cs.cs_quantity, 0)) AS return_ratio,
                      (SUM(COALESCE(cr.cr_return_amount, 0)) * 1.0000)
                        / SUM(COALESCE(cs.cs_net_paid, 0))
                        AS currency_ratio
               FROM catalog_sales cs
               LEFT OUTER JOIN catalog_returns cr
                 ON (cs.cs_order_number = cr.cr_order_number
                     AND cs.cs_item_sk = cr.cr_item_sk), date_dim
               WHERE cr.cr_return_amount > 100
                 AND cs.cs_net_profit > 1 AND cs.cs_net_paid > 0
                 AND cs.cs_quantity > 0 AND cs_sold_date_sk = d_date_sk
                 AND d_year = 2000 AND d_moy = 12
               GROUP BY cs.cs_item_sk) in_cat) catalog
   WHERE catalog.return_rank <= 10 OR catalog.currency_rank <= 10
   UNION
   SELECT 'store' AS channel, store.item, store.return_ratio,
          store.return_rank, store.currency_rank
   FROM (SELECT item, return_ratio, currency_ratio,
                RANK() OVER (ORDER BY return_ratio) AS return_rank,
                RANK() OVER (ORDER BY currency_ratio) AS currency_rank
         FROM (SELECT sts.ss_item_sk AS item,
                      (SUM(COALESCE(sr.sr_return_quantity, 0)) * 1.0000)
                        / SUM(COALESCE(sts.ss_quantity, 0))
                        AS return_ratio,
                      (SUM(COALESCE(sr.sr_return_amt, 0)) * 1.0000)
                        / SUM(COALESCE(sts.ss_net_paid, 0))
                        AS currency_ratio
               FROM store_sales sts
               LEFT OUTER JOIN store_returns sr
                 ON (sts.ss_ticket_number = sr.sr_ticket_number
                     AND sts.ss_item_sk = sr.sr_item_sk), date_dim
               WHERE sr.sr_return_amt > 100
                 AND sts.ss_net_profit > 1 AND sts.ss_net_paid > 0
                 AND sts.ss_quantity > 0 AND ss_sold_date_sk = d_date_sk
                 AND d_year = 2000 AND d_moy = 12
               GROUP BY sts.ss_item_sk) in_store) store
   WHERE store.return_rank <= 10 OR store.currency_rank <= 10) sq1
ORDER BY channel, return_rank, currency_rank, item
LIMIT 100
"""

Q51 = """
WITH web_v1 AS
  (SELECT ws_item_sk item_sk, d_date,
          SUM(SUM(ws_sales_price))
            OVER (PARTITION BY ws_item_sk ORDER BY d_date
                  ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
            cume_sales
   FROM web_sales, date_dim
   WHERE ws_sold_date_sk = d_date_sk
     AND d_month_seq BETWEEN 1200 AND 1200 + 11
     AND ws_item_sk IS NOT NULL
   GROUP BY ws_item_sk, d_date),
     store_v1 AS
  (SELECT ss_item_sk item_sk, d_date,
          SUM(SUM(ss_sales_price))
            OVER (PARTITION BY ss_item_sk ORDER BY d_date
                  ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
            cume_sales
   FROM store_sales, date_dim
   WHERE ss_sold_date_sk = d_date_sk
     AND d_month_seq BETWEEN 1200 AND 1200 + 11
     AND ss_item_sk IS NOT NULL
   GROUP BY ss_item_sk, d_date)
SELECT *
FROM (SELECT item_sk, d_date, web_sales, store_sales,
             MAX(web_sales)
               OVER (PARTITION BY item_sk ORDER BY d_date
                     ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
               web_cumulative,
             MAX(store_sales)
               OVER (PARTITION BY item_sk ORDER BY d_date
                     ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
               store_cumulative
      FROM (SELECT CASE WHEN web.item_sk IS NOT NULL THEN web.item_sk
                        ELSE store.item_sk END item_sk,
                   CASE WHEN web.d_date IS NOT NULL THEN web.d_date
                        ELSE store.d_date END d_date,
                   web.cume_sales web_sales,
                   store.cume_sales store_sales
            FROM web_v1 web
            FULL OUTER JOIN store_v1 store
              ON (web.item_sk = store.item_sk
                  AND web.d_date = store.d_date)) x) y
WHERE web_cumulative > store_cumulative
ORDER BY item_sk, d_date
LIMIT 100
"""

Q54 = """
WITH my_customers AS
  (SELECT DISTINCT c_customer_sk, c_current_addr_sk
   FROM (SELECT cs_sold_date_sk sold_date_sk,
                cs_bill_customer_sk customer_sk, cs_item_sk item_sk
         FROM catalog_sales
         UNION ALL
         SELECT ws_sold_date_sk, ws_bill_customer_sk, ws_item_sk
         FROM web_sales) cs_or_ws_sales, item, date_dim, customer
   WHERE sold_date_sk = d_date_sk AND item_sk = i_item_sk
     AND i_category = 'Women' AND i_class = 'dresses'
     AND c_customer_sk = cs_or_ws_sales.customer_sk
     AND d_moy = 12 AND d_year = 1999),
     my_revenue AS
  (SELECT c_customer_sk, SUM(ss_ext_sales_price) AS revenue
   FROM my_customers, store_sales, customer_address, store, date_dim
   WHERE c_current_addr_sk = ca_address_sk
     AND ca_county = s_county AND ca_state = s_state
     AND ss_sold_date_sk = d_date_sk
     AND c_customer_sk = ss_customer_sk
     AND d_month_seq BETWEEN (SELECT DISTINCT d_month_seq + 1
                              FROM date_dim
                              WHERE d_year = 1999 AND d_moy = 12)
                         AND (SELECT DISTINCT d_month_seq + 3
                              FROM date_dim
                              WHERE d_year = 1999 AND d_moy = 12)
   GROUP BY c_customer_sk),
     segments AS
  (SELECT CAST(ROUND(revenue / 50) AS INT) AS segment FROM my_revenue)
SELECT segment, COUNT(*) AS num_customers, segment * 50 AS segment_base
FROM segments
GROUP BY segment
ORDER BY segment, num_customers, segment_base
LIMIT 100
"""

Q57 = """
WITH v1 AS
  (SELECT i_category, i_brand, cc_name, d_year, d_moy,
          SUM(cs_sales_price) sum_sales,
          AVG(SUM(cs_sales_price))
            OVER (PARTITION BY i_category, i_brand, cc_name, d_year)
            avg_monthly_sales,
          RANK() OVER (PARTITION BY i_category, i_brand, cc_name
                       ORDER BY d_year, d_moy) rn
   FROM item, catalog_sales, date_dim, call_center
   WHERE cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
     AND cc_call_center_sk = cs_call_center_sk
     AND (d_year = 2000
          OR (d_year = 2000 - 1 AND d_moy = 12)
          OR (d_year = 2000 + 1 AND d_moy = 1))
   GROUP BY i_category, i_brand, cc_name, d_year, d_moy),
     v2 AS
  (SELECT v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
          v1.avg_monthly_sales, v1.sum_sales,
          v1_lag.sum_sales psum, v1_lead.sum_sales nsum
   FROM v1, v1 v1_lag, v1 v1_lead
   WHERE v1.i_category = v1_lag.i_category
     AND v1.i_category = v1_lead.i_category
     AND v1.i_brand = v1_lag.i_brand
     AND v1.i_brand = v1_lead.i_brand
     AND v1.cc_name = v1_lag.cc_name
     AND v1.cc_name = v1_lead.cc_name
     AND v1.rn = v1_lag.rn + 1
     AND v1.rn = v1_lead.rn - 1)
SELECT *
FROM v2
WHERE d_year = 2000
  AND avg_monthly_sales > 0
  AND CASE WHEN avg_monthly_sales > 0
           THEN ABS(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, cc_name
LIMIT 100
"""

Q58 = """
WITH ss_items AS
  (SELECT i_item_id item_id, SUM(ss_ext_sales_price) ss_item_rev
   FROM store_sales, item, date_dim
   WHERE ss_item_sk = i_item_sk
     AND d_date IN (SELECT d_date FROM date_dim
                    WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                        WHERE d_date
                                          = CAST('2000-01-03' AS DATE)))
     AND ss_sold_date_sk = d_date_sk
   GROUP BY i_item_id),
     cs_items AS
  (SELECT i_item_id item_id, SUM(cs_ext_sales_price) cs_item_rev
   FROM catalog_sales, item, date_dim
   WHERE cs_item_sk = i_item_sk
     AND d_date IN (SELECT d_date FROM date_dim
                    WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                        WHERE d_date
                                          = CAST('2000-01-03' AS DATE)))
     AND cs_sold_date_sk = d_date_sk
   GROUP BY i_item_id),
     ws_items AS
  (SELECT i_item_id item_id, SUM(ws_ext_sales_price) ws_item_rev
   FROM web_sales, item, date_dim
   WHERE ws_item_sk = i_item_sk
     AND d_date IN (SELECT d_date FROM date_dim
                    WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                        WHERE d_date
                                          = CAST('2000-01-03' AS DATE)))
     AND ws_sold_date_sk = d_date_sk
   GROUP BY i_item_id)
SELECT ss_items.item_id, ss_item_rev,
       (ss_item_rev * 1.0000)
         / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 ss_dev,
       cs_item_rev,
       (cs_item_rev * 1.0000)
         / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 cs_dev,
       ws_item_rev,
       (ws_item_rev * 1.0000)
         / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 ws_dev,
       (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
  AND ss_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND cs_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND cs_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND ws_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND ws_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
ORDER BY ss_items.item_id, ss_item_rev
LIMIT 100
"""

Q64 = """
WITH cs_ui AS
  (SELECT cs_item_sk, SUM(cs_ext_list_price) AS sale,
          SUM(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
            AS refund
   FROM catalog_sales, catalog_returns
   WHERE cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number
   GROUP BY cs_item_sk
   HAVING SUM(cs_ext_list_price)
          > 2 * SUM(cr_refunded_cash + cr_reversed_charge
                    + cr_store_credit)),
     cross_sales AS
  (SELECT i_product_name product_name, i_item_sk item_sk,
          s_store_name store_name, s_zip store_zip,
          ad1.ca_street_number b_street_number,
          ad1.ca_street_name b_street_name, ad1.ca_city b_city,
          ad1.ca_zip b_zip, ad2.ca_street_number c_street_number,
          ad2.ca_street_name c_street_name, ad2.ca_city c_city,
          ad2.ca_zip c_zip, d1.d_year AS syear, d2.d_year AS fsyear,
          d3.d_year s2year, COUNT(*) cnt, SUM(ss_wholesale_cost) s1,
          SUM(ss_list_price) s2, SUM(ss_coupon_amt) s3
   FROM store_sales, store_returns, cs_ui, date_dim d1, date_dim d2,
        date_dim d3, store, customer, customer_demographics cd1,
        customer_demographics cd2, promotion,
        household_demographics hd1, household_demographics hd2,
        customer_address ad1, customer_address ad2, income_band ib1,
        income_band ib2, item
   WHERE ss_store_sk = s_store_sk AND ss_sold_date_sk = d1.d_date_sk
     AND ss_customer_sk = c_customer_sk AND ss_cdemo_sk = cd1.cd_demo_sk
     AND ss_hdemo_sk = hd1.hd_demo_sk AND ss_addr_sk = ad1.ca_address_sk
     AND ss_item_sk = i_item_sk AND ss_item_sk = sr_item_sk
     AND ss_ticket_number = sr_ticket_number
     AND ss_item_sk = cs_ui.cs_item_sk
     AND c_current_cdemo_sk = cd2.cd_demo_sk
     AND c_current_hdemo_sk = hd2.hd_demo_sk
     AND c_current_addr_sk = ad2.ca_address_sk
     AND c_first_sales_date_sk = d2.d_date_sk
     AND c_first_shipto_date_sk = d3.d_date_sk
     AND ss_promo_sk = p_promo_sk
     AND hd1.hd_income_band_sk = ib1.ib_income_band_sk
     AND hd2.hd_income_band_sk = ib2.ib_income_band_sk
     AND cd1.cd_marital_status <> cd2.cd_marital_status
     AND i_color IN ('powder', 'orchid', 'slate', 'peach', 'smoke',
                     'sienna')
     AND i_current_price BETWEEN 40 AND 40 + 30
   GROUP BY i_product_name, i_item_sk, s_store_name, s_zip,
            ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city,
            ad1.ca_zip, ad2.ca_street_number, ad2.ca_street_name,
            ad2.ca_city, ad2.ca_zip, d1.d_year, d2.d_year, d3.d_year)
SELECT cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear cs1syear, cs1.cnt cs1cnt, cs1.s1 AS s11,
       cs1.s2 AS s21, cs1.s3 AS s31, cs2.s1 AS s12, cs2.s2 AS s22,
       cs2.s3 AS s32, cs2.syear, cs2.cnt
FROM cross_sales cs1, cross_sales cs2
WHERE cs1.item_sk = cs2.item_sk AND cs1.syear = 1999
  AND cs2.syear = 1999 + 1 AND cs2.cnt <= cs1.cnt
  AND cs1.store_name = cs2.store_name AND cs1.store_zip = cs2.store_zip
ORDER BY cs1.product_name, cs1.store_name, cs2.cnt, cs1.s1, cs2.s1
"""

Q66 = """
SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
       w_country, ship_carriers, year_,
       SUM(jan_sales) AS jan_sales, SUM(feb_sales) AS feb_sales,
       SUM(mar_sales) AS mar_sales, SUM(apr_sales) AS apr_sales,
       SUM(may_sales) AS may_sales, SUM(jun_sales) AS jun_sales,
       SUM(jul_sales) AS jul_sales, SUM(aug_sales) AS aug_sales,
       SUM(sep_sales) AS sep_sales, SUM(oct_sales) AS oct_sales,
       SUM(nov_sales) AS nov_sales, SUM(dec_sales) AS dec_sales,
       SUM(jan_sales / w_warehouse_sq_ft) AS jan_sales_per_sq_foot,
       SUM(dec_sales / w_warehouse_sq_ft) AS dec_sales_per_sq_foot,
       SUM(jan_net) AS jan_net, SUM(dec_net) AS dec_net
FROM (SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
             w_state, w_country, 'DHL,UPS' AS ship_carriers,
             d_year AS year_,
             SUM(CASE WHEN d_moy = 1 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS jan_sales,
             SUM(CASE WHEN d_moy = 2 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS feb_sales,
             SUM(CASE WHEN d_moy = 3 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS mar_sales,
             SUM(CASE WHEN d_moy = 4 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS apr_sales,
             SUM(CASE WHEN d_moy = 5 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS may_sales,
             SUM(CASE WHEN d_moy = 6 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS jun_sales,
             SUM(CASE WHEN d_moy = 7 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS jul_sales,
             SUM(CASE WHEN d_moy = 8 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS aug_sales,
             SUM(CASE WHEN d_moy = 9 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS sep_sales,
             SUM(CASE WHEN d_moy = 10 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS oct_sales,
             SUM(CASE WHEN d_moy = 11 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS nov_sales,
             SUM(CASE WHEN d_moy = 12 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS dec_sales,
             SUM(CASE WHEN d_moy = 1 THEN ws_net_paid * ws_quantity
                      ELSE 0 END) AS jan_net,
             SUM(CASE WHEN d_moy = 12 THEN ws_net_paid * ws_quantity
                      ELSE 0 END) AS dec_net
      FROM web_sales, warehouse, date_dim, time_dim, ship_mode
      WHERE ws_warehouse_sk = w_warehouse_sk
        AND ws_sold_date_sk = d_date_sk
        AND ws_sold_time_sk = t_time_sk
        AND ws_ship_mode_sk = sm_ship_mode_sk
        AND d_year = 2000
        AND t_time BETWEEN 30838 AND 30838 + 28800
        AND sm_carrier IN ('DHL', 'UPS')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
               w_state, w_country, d_year
      UNION ALL
      SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
             w_state, w_country, 'DHL,UPS' AS ship_carriers,
             d_year AS year_,
             SUM(CASE WHEN d_moy = 1 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS jan_sales,
             SUM(CASE WHEN d_moy = 2 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS feb_sales,
             SUM(CASE WHEN d_moy = 3 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS mar_sales,
             SUM(CASE WHEN d_moy = 4 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS apr_sales,
             SUM(CASE WHEN d_moy = 5 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS may_sales,
             SUM(CASE WHEN d_moy = 6 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS jun_sales,
             SUM(CASE WHEN d_moy = 7 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS jul_sales,
             SUM(CASE WHEN d_moy = 8 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS aug_sales,
             SUM(CASE WHEN d_moy = 9 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS sep_sales,
             SUM(CASE WHEN d_moy = 10 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS oct_sales,
             SUM(CASE WHEN d_moy = 11 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS nov_sales,
             SUM(CASE WHEN d_moy = 12 THEN cs_sales_price * cs_quantity
                      ELSE 0 END) AS dec_sales,
             SUM(CASE WHEN d_moy = 1 THEN cs_net_paid_inc_tax * cs_quantity
                      ELSE 0 END) AS jan_net,
             SUM(CASE WHEN d_moy = 12
                      THEN cs_net_paid_inc_tax * cs_quantity
                      ELSE 0 END) AS dec_net
      FROM catalog_sales, warehouse, date_dim, time_dim, ship_mode
      WHERE cs_warehouse_sk = w_warehouse_sk
        AND cs_sold_date_sk = d_date_sk
        AND cs_sold_time_sk = t_time_sk
        AND cs_ship_mode_sk = sm_ship_mode_sk
        AND d_year = 2000
        AND t_time BETWEEN 30838 AND 30838 + 28800
        AND sm_carrier IN ('DHL', 'UPS')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
               w_state, w_country, d_year) x
GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country, ship_carriers, year_
ORDER BY w_warehouse_name
LIMIT 100
"""

Q70 = """
SELECT SUM(ss_net_profit) AS total_sum, s_state, s_county,
       GROUPING(s_state) + GROUPING(s_county) AS lochierarchy,
       RANK() OVER (PARTITION BY GROUPING(s_state) + GROUPING(s_county),
                                 CASE WHEN GROUPING(s_county) = 0
                                      THEN s_state END
                    ORDER BY SUM(ss_net_profit) DESC) AS rank_within_parent
FROM store_sales, date_dim d1, store
WHERE d1.d_month_seq BETWEEN 1200 AND 1200 + 11
  AND d1.d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_state IN
    (SELECT s_state
     FROM (SELECT s_state AS s_state,
                  RANK() OVER (PARTITION BY s_state
                               ORDER BY SUM(ss_net_profit) DESC) AS ranking
           FROM store_sales, store, date_dim
           WHERE d_month_seq BETWEEN 1200 AND 1200 + 11
             AND d_date_sk = ss_sold_date_sk
             AND s_store_sk = ss_store_sk
           GROUP BY s_state) tmp1
     WHERE ranking <= 5)
GROUP BY ROLLUP (s_state, s_county)
ORDER BY lochierarchy DESC,
         CASE WHEN GROUPING(s_state) + GROUPING(s_county) = 0
              THEN s_state END,
         rank_within_parent
LIMIT 100
"""

Q72 = """
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq,
       SUM(CASE WHEN p_promo_sk IS NULL THEN 1 ELSE 0 END) no_promo,
       SUM(CASE WHEN p_promo_sk IS NOT NULL THEN 1 ELSE 0 END) promo,
       COUNT(*) total_cnt
FROM catalog_sales
JOIN inventory ON (cs_item_sk = inv_item_sk)
JOIN warehouse ON (w_warehouse_sk = inv_warehouse_sk)
JOIN item ON (i_item_sk = cs_item_sk)
JOIN customer_demographics ON (cs_bill_cdemo_sk = cd_demo_sk)
JOIN household_demographics ON (cs_bill_hdemo_sk = hd_demo_sk)
JOIN date_dim d1 ON (cs_sold_date_sk = d1.d_date_sk)
JOIN date_dim d2 ON (inv_date_sk = d2.d_date_sk)
JOIN date_dim d3 ON (cs_ship_date_sk = d3.d_date_sk)
LEFT OUTER JOIN promotion ON (cs_promo_sk = p_promo_sk)
LEFT OUTER JOIN catalog_returns ON (cr_item_sk = cs_item_sk
                                    AND cr_order_number = cs_order_number)
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date > d1.d_date + INTERVAL '5' DAY
  AND hd_buy_potential = '>10000'
  AND d1.d_year = 2000
  AND cd_marital_status = 'D'
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d1.d_week_seq
LIMIT 100
"""

Q74 = """
WITH year_total AS
  (SELECT c_customer_id customer_id, c_first_name customer_first_name,
          c_last_name customer_last_name, d_year AS year_,
          SUM(ss_net_paid) year_total, 's' sale_type
   FROM customer, store_sales, date_dim
   WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
     AND d_year IN (2000, 2000 + 1)
   GROUP BY c_customer_id, c_first_name, c_last_name, d_year
   UNION ALL
   SELECT c_customer_id, c_first_name, c_last_name, d_year AS year_,
          SUM(ws_net_paid) year_total, 'w' sale_type
   FROM customer, web_sales, date_dim
   WHERE c_customer_sk = ws_bill_customer_sk
     AND ws_sold_date_sk = d_date_sk
     AND d_year IN (2000, 2000 + 1)
   GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.year_ = 2000 AND t_s_secyear.year_ = 2000 + 1
  AND t_w_firstyear.year_ = 2000 AND t_w_secyear.year_ = 2000 + 1
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE NULL END
    > CASE WHEN t_s_firstyear.year_total > 0
           THEN t_s_secyear.year_total / t_s_firstyear.year_total
           ELSE NULL END
ORDER BY 1
LIMIT 100
"""

Q75 = """
WITH all_sales AS
  (SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
          SUM(sales_cnt) AS sales_cnt, SUM(sales_amt) AS sales_amt
   FROM (SELECT d_year, i_brand_id, i_class_id, i_category_id,
                i_manufact_id,
                cs_quantity - COALESCE(cr_return_quantity, 0)
                  AS sales_cnt,
                cs_ext_sales_price - COALESCE(cr_return_amount, 0.0)
                  AS sales_amt
         FROM catalog_sales
         JOIN item ON i_item_sk = cs_item_sk
         JOIN date_dim ON d_date_sk = cs_sold_date_sk
         LEFT JOIN catalog_returns
           ON (cs_order_number = cr_order_number
               AND cs_item_sk = cr_item_sk)
         WHERE i_category = 'Books'
         UNION
         SELECT d_year, i_brand_id, i_class_id, i_category_id,
                i_manufact_id,
                ss_quantity - COALESCE(sr_return_quantity, 0),
                ss_ext_sales_price - COALESCE(sr_return_amt, 0.0)
         FROM store_sales
         JOIN item ON i_item_sk = ss_item_sk
         JOIN date_dim ON d_date_sk = ss_sold_date_sk
         LEFT JOIN store_returns
           ON (ss_ticket_number = sr_ticket_number
               AND ss_item_sk = sr_item_sk)
         WHERE i_category = 'Books'
         UNION
         SELECT d_year, i_brand_id, i_class_id, i_category_id,
                i_manufact_id,
                ws_quantity - COALESCE(wr_return_quantity, 0),
                ws_ext_sales_price - COALESCE(wr_return_amt, 0.0)
         FROM web_sales
         JOIN item ON i_item_sk = ws_item_sk
         JOIN date_dim ON d_date_sk = ws_sold_date_sk
         LEFT JOIN web_returns
           ON (ws_order_number = wr_order_number
               AND ws_item_sk = wr_item_sk)
         WHERE i_category = 'Books') sales_detail
   GROUP BY d_year, i_brand_id, i_class_id, i_category_id,
            i_manufact_id)
SELECT prev_yr.d_year AS prev_year, curr_yr.d_year AS year_,
       curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,
       curr_yr.i_manufact_id, prev_yr.sales_cnt AS prev_yr_cnt,
       curr_yr.sales_cnt AS curr_yr_cnt,
       curr_yr.sales_cnt - prev_yr.sales_cnt AS sales_cnt_diff,
       curr_yr.sales_amt - prev_yr.sales_amt AS sales_amt_diff
FROM all_sales curr_yr, all_sales prev_yr
WHERE curr_yr.i_brand_id = prev_yr.i_brand_id
  AND curr_yr.i_class_id = prev_yr.i_class_id
  AND curr_yr.i_category_id = prev_yr.i_category_id
  AND curr_yr.i_manufact_id = prev_yr.i_manufact_id
  AND curr_yr.d_year = 2001 AND prev_yr.d_year = 2001 - 1
  AND (curr_yr.sales_cnt * 1.0000) / prev_yr.sales_cnt < 0.9
ORDER BY sales_cnt_diff, sales_amt_diff
LIMIT 100
"""

Q78 = """
WITH ws AS
  (SELECT d_year AS ws_sold_year, ws_item_sk,
          ws_bill_customer_sk ws_customer_sk, SUM(ws_quantity) ws_qty,
          SUM(ws_wholesale_cost) ws_wc, SUM(ws_sales_price) ws_sp
   FROM web_sales
   LEFT JOIN web_returns ON wr_order_number = ws_order_number
                        AND ws_item_sk = wr_item_sk
   JOIN date_dim ON ws_sold_date_sk = d_date_sk
   WHERE wr_order_number IS NULL
   GROUP BY d_year, ws_item_sk, ws_bill_customer_sk),
     cs AS
  (SELECT d_year AS cs_sold_year, cs_item_sk,
          cs_bill_customer_sk cs_customer_sk, SUM(cs_quantity) cs_qty,
          SUM(cs_wholesale_cost) cs_wc, SUM(cs_sales_price) cs_sp
   FROM catalog_sales
   LEFT JOIN catalog_returns ON cr_order_number = cs_order_number
                            AND cs_item_sk = cr_item_sk
   JOIN date_dim ON cs_sold_date_sk = d_date_sk
   WHERE cr_order_number IS NULL
   GROUP BY d_year, cs_item_sk, cs_bill_customer_sk),
     ss AS
  (SELECT d_year AS ss_sold_year, ss_item_sk, ss_customer_sk,
          SUM(ss_quantity) ss_qty, SUM(ss_wholesale_cost) ss_wc,
          SUM(ss_sales_price) ss_sp
   FROM store_sales
   LEFT JOIN store_returns ON sr_ticket_number = ss_ticket_number
                          AND ss_item_sk = sr_item_sk
   JOIN date_dim ON ss_sold_date_sk = d_date_sk
   WHERE sr_ticket_number IS NULL
   GROUP BY d_year, ss_item_sk, ss_customer_sk)
SELECT ss_sold_year, ss_item_sk, ss_customer_sk,
       ROUND((ss_qty * 1.00) / (COALESCE(ws_qty, 0)
                                + COALESCE(cs_qty, 0)), 2) ratio,
       ss_qty store_qty, ss_wc store_wholesale_cost,
       ss_sp store_sales_price,
       COALESCE(ws_qty, 0) + COALESCE(cs_qty, 0) other_chan_qty,
       COALESCE(ws_wc, 0) + COALESCE(cs_wc, 0)
         other_chan_wholesale_cost,
       COALESCE(ws_sp, 0) + COALESCE(cs_sp, 0) other_chan_sales_price
FROM ss
LEFT JOIN ws ON (ws_sold_year = ss_sold_year
                 AND ws_item_sk = ss_item_sk
                 AND ws_customer_sk = ss_customer_sk)
LEFT JOIN cs ON (cs_sold_year = ss_sold_year
                 AND cs_item_sk = ss_item_sk
                 AND cs_customer_sk = ss_customer_sk)
WHERE (COALESCE(ws_qty, 0) > 0 OR COALESCE(cs_qty, 0) > 0)
  AND ss_sold_year = 2000
ORDER BY ss_sold_year, ss_item_sk, ss_customer_sk, ss_qty DESC,
         ss_wc DESC, ss_sp DESC, other_chan_qty,
         other_chan_wholesale_cost, other_chan_sales_price, ratio
LIMIT 100
"""

Q83 = """
WITH sr_items AS
  (SELECT i_item_id item_id, SUM(sr_return_quantity) sr_item_qty
   FROM store_returns, item, date_dim
   WHERE sr_item_sk = i_item_sk
     AND d_date IN (SELECT d_date FROM date_dim
                    WHERE d_week_seq IN
                        (SELECT d_week_seq FROM date_dim
                         WHERE d_date IN (CAST('2000-06-30' AS DATE),
                                          CAST('2000-09-27' AS DATE),
                                          CAST('2000-11-17' AS DATE))))
     AND sr_returned_date_sk = d_date_sk
   GROUP BY i_item_id),
     cr_items AS
  (SELECT i_item_id item_id, SUM(cr_return_quantity) cr_item_qty
   FROM catalog_returns, item, date_dim
   WHERE cr_item_sk = i_item_sk
     AND d_date IN (SELECT d_date FROM date_dim
                    WHERE d_week_seq IN
                        (SELECT d_week_seq FROM date_dim
                         WHERE d_date IN (CAST('2000-06-30' AS DATE),
                                          CAST('2000-09-27' AS DATE),
                                          CAST('2000-11-17' AS DATE))))
     AND cr_returned_date_sk = d_date_sk
   GROUP BY i_item_id),
     wr_items AS
  (SELECT i_item_id item_id, SUM(wr_return_quantity) wr_item_qty
   FROM web_returns, item, date_dim
   WHERE wr_item_sk = i_item_sk
     AND d_date IN (SELECT d_date FROM date_dim
                    WHERE d_week_seq IN
                        (SELECT d_week_seq FROM date_dim
                         WHERE d_date IN (CAST('2000-06-30' AS DATE),
                                          CAST('2000-09-27' AS DATE),
                                          CAST('2000-11-17' AS DATE))))
     AND wr_returned_date_sk = d_date_sk
   GROUP BY i_item_id)
SELECT sr_items.item_id, sr_item_qty,
       (sr_item_qty * 1.0000) / (sr_item_qty + cr_item_qty + wr_item_qty)
         / 3.0000 * 100 sr_dev,
       cr_item_qty,
       (cr_item_qty * 1.0000) / (sr_item_qty + cr_item_qty + wr_item_qty)
         / 3.0000 * 100 cr_dev,
       wr_item_qty,
       (wr_item_qty * 1.0000) / (sr_item_qty + cr_item_qty + wr_item_qty)
         / 3.0000 * 100 wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
FROM sr_items, cr_items, wr_items
WHERE sr_items.item_id = cr_items.item_id
  AND sr_items.item_id = wr_items.item_id
ORDER BY sr_items.item_id, sr_item_qty
LIMIT 100
"""

Q86 = """
SELECT SUM(ws_net_paid) AS total_sum, i_category, i_class,
       GROUPING(i_category) + GROUPING(i_class) AS lochierarchy,
       RANK() OVER (PARTITION BY GROUPING(i_category)
                                 + GROUPING(i_class),
                                 CASE WHEN GROUPING(i_class) = 0
                                      THEN i_category END
                    ORDER BY SUM(ws_net_paid) DESC) AS rank_within_parent
FROM web_sales, date_dim d1, item
WHERE d1.d_month_seq BETWEEN 1200 AND 1200 + 11
  AND d1.d_date_sk = ws_sold_date_sk
  AND i_item_sk = ws_item_sk
GROUP BY ROLLUP (i_category, i_class)
ORDER BY lochierarchy DESC,
         CASE WHEN GROUPING(i_category) + GROUPING(i_class) = 0
              THEN i_category END,
         rank_within_parent
LIMIT 100
"""

REST = {4: Q4, 9: Q9, 10: Q10, 11: Q11, 12: Q12, 14: Q14, 17: Q17,
        23: Q23, 24: Q24, 28: Q28, 31: Q31, 35: Q35, 36: Q36, 44: Q44,
        45: Q45, 49: Q49, 51: Q51, 54: Q54, 57: Q57, 58: Q58, 64: Q64,
        66: Q66, 70: Q70, 72: Q72, 74: Q74, 75: Q75, 78: Q78, 83: Q83,
        86: Q86}
