"""30 TPC-DS queries as SQL against the engine's SQL frontend
(reference ships the full 99 in ``benchmarking/tpcds/queries``; this
subset covers every store-channel query family expressible without
ROLLUP). Clause structures follow the spec — the BASELINE trio
Q47/Q63/Q89 carry their year-edge predicates, prev/next-month self-joins
and CASE-abs deviation filters; Q13/Q48 keep the OR-embedded join
predicate groups; Q1/Q6 their correlated scalar subqueries; Q41 its
EXISTS; Q8 its INTERSECT; Q88 its 4-way count cross-join — with literal
vocabularies (brand/category/city names, date ranges) adapted to the
synthetic datagen so results are non-degenerate. Families: rolling
windows (47/63/89), dimensional aggregates (3/42/52/55), demographics +
promotions (7/26/61), address/brand (19), tickets & store hours
(34/73/96/88), quarterly (53), revenue-ratio windows (98), returns
(1/93), subqueries (1/6/41), weekday pivots (43/59), city-pair baskets
(46/68/79), predicate-group scans (13/48), low-revenue inventory (65),
zip-intersect (8)."""

Q47 = """
WITH v1 AS (
  SELECT i_category, i_brand, s_store_name, s_company_name,
         d_year, d_moy,
         SUM(ss_sales_price) AS sum_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND (d_year = 2000
         OR (d_year = 2000 - 1 AND d_moy = 12)
         OR (d_year = 2000 + 1 AND d_moy = 1))
  GROUP BY i_category, i_brand, s_store_name, s_company_name,
           d_year, d_moy
), v1w AS (
  SELECT i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
         sum_sales,
         AVG(sum_sales) OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name, d_year) AS avg_monthly_sales,
         RANK() OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name
             ORDER BY d_year, d_moy) AS rn
  FROM v1
), v2 AS (
  SELECT v1w.i_category, v1w.i_brand, v1w.s_store_name,
         v1w.s_company_name, v1w.d_year, v1w.d_moy,
         v1w.avg_monthly_sales, v1w.sum_sales,
         v1w_lag.sum_sales AS psum, v1w_lead.sum_sales AS nsum
  FROM v1w, v1w v1w_lag, v1w v1w_lead
  WHERE v1w.i_category = v1w_lag.i_category
    AND v1w.i_category = v1w_lead.i_category
    AND v1w.i_brand = v1w_lag.i_brand
    AND v1w.i_brand = v1w_lead.i_brand
    AND v1w.s_store_name = v1w_lag.s_store_name
    AND v1w.s_store_name = v1w_lead.s_store_name
    AND v1w.s_company_name = v1w_lag.s_company_name
    AND v1w.s_company_name = v1w_lead.s_company_name
    AND v1w.rn = v1w_lag.rn + 1
    AND v1w.rn = v1w_lead.rn - 1
)
SELECT i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
       avg_monthly_sales, sum_sales, psum, nsum
FROM v2
WHERE d_year = 2000
  AND avg_monthly_sales > 0
  AND CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, i_category, i_brand,
         s_store_name, s_company_name, d_year, d_moy
LIMIT 100
"""

Q63 = """
WITH tmp1 AS (
  SELECT i_manager_id, d_moy, SUM(ss_sales_price) AS sum_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_month_seq IN (1200, 1200 + 1, 1200 + 2, 1200 + 3, 1200 + 4,
                        1200 + 5, 1200 + 6, 1200 + 7, 1200 + 8, 1200 + 9,
                        1200 + 10, 1200 + 11)
    AND ((i_category IN ('Books', 'Children', 'Electronics')
          AND i_class IN ('personal', 'portable', 'reference',
                          'self-help'))
         OR (i_category IN ('Women', 'Music', 'Men')
             AND i_class IN ('accessories', 'classical', 'fragrances',
                             'pants')))
  GROUP BY i_manager_id, d_moy
), tmp2 AS (
  SELECT i_manager_id, sum_sales,
         AVG(sum_sales) OVER (PARTITION BY i_manager_id)
             AS avg_monthly_sales
  FROM tmp1
)
SELECT i_manager_id, sum_sales, avg_monthly_sales
FROM tmp2
WHERE CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY i_manager_id, avg_monthly_sales, sum_sales
LIMIT 100
"""

Q89 = """
WITH tmp1 AS (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, SUM(ss_sales_price) AS sum_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_year = 2000
    AND ((i_category IN ('Books', 'Electronics', 'Sports')
          AND i_class IN ('computers', 'stereo', 'football'))
         OR (i_category IN ('Men', 'Jewelry', 'Women')
             AND i_class IN ('shirts', 'birdal', 'dresses')))
  GROUP BY i_category, i_class, i_brand, s_store_name, s_company_name,
           d_moy
), tmp2 AS (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, sum_sales,
         AVG(sum_sales) OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name) AS avg_monthly_sales
  FROM tmp1
)
SELECT i_category, i_class, i_brand, s_store_name, s_company_name, d_moy,
       sum_sales, avg_monthly_sales
FROM tmp2
WHERE CASE WHEN avg_monthly_sales <> 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, s_store_name, i_category,
         i_class, i_brand, d_moy
LIMIT 100
"""

Q3 = """
SELECT d_year, i_brand_id, i_brand, SUM(ss_ext_sales_price) AS sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, i_brand_id
LIMIT 100
"""

Q42 = """
SELECT d_year, i_category_id, i_category,
       SUM(ss_ext_sales_price) AS sum_sales
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY sum_sales DESC, d_year, i_category_id, i_category
LIMIT 100
"""

Q52 = """
SELECT d_year, i_brand_id, i_brand, SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, i_brand_id
LIMIT 100
"""

Q53 = """
WITH quarterly AS (
  SELECT i_manufact_id, d_qoy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000
    AND i_category IN ('Books', 'Home', 'Electronics')
  GROUP BY i_manufact_id, d_qoy
)
SELECT i_manufact_id, sum_sales,
       AVG(sum_sales) OVER (PARTITION BY i_manufact_id)
           AS avg_quarterly_sales
FROM quarterly
ORDER BY avg_quarterly_sales DESC, sum_sales, i_manufact_id
LIMIT 100
"""

Q55 = """
SELECT i_brand_id, i_brand, SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, i_brand_id
LIMIT 100
"""

Q98 = """
WITH revenue AS (
  SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
         SUM(ss_ext_sales_price) AS itemrevenue
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND i_category IN ('Sports', 'Books', 'Home')
    AND d_year = 2000
    AND d_moy BETWEEN 2 AND 4
  GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
)
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / SUM(itemrevenue) OVER (PARTITION BY i_class)
           AS revenueratio
FROM revenue
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
"""

Q7 = """
SELECT i_item_id, AVG(ss_quantity) AS agg1, AVG(ss_list_price) AS agg2,
       AVG(ss_coupon_amt) AS agg3, AVG(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

Q19 = """
SELECT i_brand_id, i_brand, i_manufact_id,
       SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id BETWEEN 1 AND 40
  AND d_moy = 11
  AND d_year = 1999
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ss_store_sk = s_store_sk
GROUP BY i_brand_id, i_brand, i_manufact_id
ORDER BY ext_price DESC, i_brand_id, i_manufact_id
LIMIT 100
"""

Q26 = """
SELECT i_item_id, AVG(ss_quantity) AS agg1, AVG(ss_list_price) AS agg2,
       AVG(ss_coupon_amt) AS agg3, AVG(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'F'
  AND cd_marital_status = 'W'
  AND cd_education_status = 'Primary'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

Q34 = """
WITH tickets AS (
  SELECT ss_ticket_number, ss_customer_sk, COUNT(*) AS cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND d_dom BETWEEN 1 AND 3
    AND hd_vehicle_count > 0
    AND d_year = 2000
  GROUP BY ss_ticket_number, ss_customer_sk
)
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM tickets, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 15 AND 20
ORDER BY c_last_name, c_first_name, ss_ticket_number DESC
"""

Q73 = """
WITH tickets AS (
  SELECT ss_ticket_number, ss_customer_sk, COUNT(*) AS cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND d_dom BETWEEN 1 AND 2
    AND hd_buy_potential IN ('>10000', 'Unknown')
    AND hd_vehicle_count > 0
    AND d_year = 2000
  GROUP BY ss_ticket_number, ss_customer_sk
)
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM tickets, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name
"""

Q96 = """
SELECT COUNT(1) AS cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk
  AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND t_hour = 20
  AND t_minute >= 30
  AND hd_dep_count = 7
ORDER BY cnt
LIMIT 100
"""

ALL = {3: Q3, 7: Q7, 19: Q19, 26: Q26, 34: Q34, 42: Q42, 47: Q47, 52: Q52,
       53: Q53, 55: Q55, 63: Q63, 73: Q73, 89: Q89, 96: Q96, 98: Q98}


TABLES = ("store_sales", "store_returns", "item", "date_dim", "store",
          "customer", "customer_address", "customer_demographics",
          "promotion", "household_demographics", "time_dim", "reason")


def tables_of(qnum: int):
    """Table names a query actually references (underscores are word
    chars, so e.g. ``store`` never matches inside ``store_sales``)."""
    import re
    sql = ALL[qnum]
    return [t for t in TABLES if re.search(rf"\b{t}\b", sql)]


def run(qnum: int, get_df):
    """Execute a query with only its referenced tables bound from
    ``get_df(name)`` — datasets generated before newer tables were added
    keep working for the queries they cover."""
    import daft_tpu as dt
    tables = {name: get_df(name) for name in tables_of(qnum)}
    return dt.sql(ALL[qnum], **tables)

Q1 = """
WITH customer_total_return AS (
  SELECT sr_customer_sk AS ctr_customer_sk,
         sr_store_sk AS ctr_store_sk,
         SUM(sr_return_amt) AS ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk
)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return > (
    SELECT AVG(ctr_total_return) * 1.2
    FROM customer_total_return ctr2
    WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk
  AND s_state = 'TN'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
"""

Q6 = """
WITH target_month AS (
  SELECT DISTINCT d_month_seq AS m
  FROM date_dim WHERE d_year = 2000 AND d_moy = 1
)
SELECT a.ca_state AS state, COUNT(*) AS cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i
WHERE a.ca_address_sk = c.c_current_addr_sk
  AND c.c_customer_sk = s.ss_customer_sk
  AND s.ss_sold_date_sk = d.d_date_sk
  AND s.ss_item_sk = i.i_item_sk
  AND d.d_month_seq = (SELECT m FROM target_month)
  AND i.i_current_price > 1.2 * (
      SELECT AVG(j.i_current_price) FROM item j
      WHERE j.i_category = i.i_category)
GROUP BY a.ca_state
HAVING COUNT(*) >= 10
ORDER BY cnt, state
LIMIT 100
"""

Q8 = """
WITH zips AS (
  SELECT substr(ca_zip, 1, 5) AS ca_zip
  FROM customer_address
  WHERE substr(ca_zip, 1, 2) IN ('10', '22', '35', '47', '58', '63')
  INTERSECT
  SELECT substr(ca_zip, 1, 5) AS ca_zip
  FROM customer_address ca, customer c
  WHERE ca.ca_address_sk = c.c_current_addr_sk
    AND c.c_preferred_cust_flag = 'Y'
)
SELECT s_store_name, SUM(ss_net_profit) AS profit
FROM store_sales, date_dim, store
WHERE ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2000
  AND substr(s_zip, 1, 2) IN (SELECT substr(ca_zip, 1, 2) FROM zips)
GROUP BY s_store_name
ORDER BY s_store_name
LIMIT 100
"""

Q13 = """
SELECT AVG(ss_quantity) AS avg_q, AVG(ss_ext_sales_price) AS avg_esp,
       AVG(ss_ext_wholesale_cost) AS avg_ewc,
       SUM(ss_ext_wholesale_cost) AS sum_ewc
FROM store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M' AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00 AND hd_dep_count = 3)
       OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
           AND cd_marital_status = 'S' AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 50.00 AND 100.00 AND hd_dep_count = 1)
       OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
           AND cd_marital_status = 'W' AND cd_education_status = 'Secondary'
           AND ss_sales_price BETWEEN 150.00 AND 200.00 AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OR', 'WA')
        AND ss_net_profit BETWEEN 100 AND 200)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('CA', 'NY', 'TN')
           AND ss_net_profit BETWEEN 150 AND 300)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('SD', 'GA', 'KY')
           AND ss_net_profit BETWEEN 50 AND 250))
"""

Q41 = """
SELECT DISTINCT i_product_name
FROM item i1
WHERE i_manufact_id BETWEEN 70 AND 110
  AND EXISTS (
    SELECT * FROM item i2
    WHERE i2.i_manufact = i1.i_manufact
      AND ((i2.i_category = 'Women'
            AND i2.i_color IN ('powder', 'orchid')
            AND i2.i_units IN ('Oz', 'Each')
            AND i2.i_size IN ('medium', 'N/A'))
           OR (i2.i_category = 'Men'
               AND i2.i_color IN ('slate', 'navy')
               AND i2.i_units IN ('Bunch', 'Ton')
               AND i2.i_size IN ('large', 'petite'))))
ORDER BY i_product_name
LIMIT 100
"""

Q43 = """
SELECT s_store_name, s_store_sk,
       SUM(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                ELSE NULL END) AS sun_sales,
       SUM(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                ELSE NULL END) AS mon_sales,
       SUM(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                ELSE NULL END) AS fri_sales,
       SUM(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price
                ELSE NULL END) AS sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_gmt_offset = -5.0
  AND d_year = 2000
GROUP BY s_store_name, s_store_sk
ORDER BY s_store_name, s_store_sk
LIMIT 100
"""

Q46 = """
WITH dn AS (
  SELECT ss_ticket_number, ss_customer_sk, ca_city AS bought_city,
         SUM(ss_coupon_amt) AS amt, SUM(ss_net_profit) AS profit
  FROM store_sales, date_dim, store, household_demographics,
       customer_address
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND ss_addr_sk = ca_address_sk
    AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
    AND d_dow IN (5, 6)
    AND d_year = 2000
    AND s_city IN ('rivertown', 'lakeside')
  GROUP BY ss_ticket_number, ss_customer_sk, ca_city
)
SELECT c_last_name, c_first_name, ca_city AS current_city, bought_city,
       ss_ticket_number, amt, profit
FROM dn, customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, current_city, bought_city,
         ss_ticket_number
LIMIT 100
"""

Q48 = """
SELECT SUM(ss_quantity) AS total_q
FROM store_sales, store, customer_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'M'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'D'
           AND cd_education_status = 'Primary'
           AND ss_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'W'
           AND cd_education_status = 'Secondary'
           AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'NM', 'OR')
        AND ss_net_profit BETWEEN 0 AND 2000)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('CA', 'NY', 'WA')
           AND ss_net_profit BETWEEN 150 AND 3000)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('TN', 'GA', 'KY')
           AND ss_net_profit BETWEEN 50 AND 25000))
"""

Q59 = """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
         SUM(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                  ELSE NULL END) AS sun_sales,
         SUM(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                  ELSE NULL END) AS mon_sales,
         SUM(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                  ELSE NULL END) AS fri_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk
), y AS (
  SELECT s_store_name AS s_store_name1, wss.d_week_seq AS d_week_seq1,
         s_store_id AS s_store_id1, sun_sales AS sun_sales1,
         mon_sales AS mon_sales1, fri_sales AS fri_sales1
  FROM wss, store, date_dim d
  WHERE d.d_week_seq = wss.d_week_seq
    AND ss_store_sk = s_store_sk AND d_year = 1999
), x AS (
  SELECT s_store_name AS s_store_name2, wss.d_week_seq AS d_week_seq2,
         s_store_id AS s_store_id2, sun_sales AS sun_sales2,
         mon_sales AS mon_sales2, fri_sales AS fri_sales2
  FROM wss, store, date_dim d
  WHERE d.d_week_seq = wss.d_week_seq
    AND ss_store_sk = s_store_sk AND d_year = 2000
)
SELECT s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2 AS sun_ratio,
       mon_sales1 / mon_sales2 AS mon_ratio,
       fri_sales1 / fri_sales2 AS fri_ratio
FROM y, x
WHERE s_store_id1 = s_store_id2
  AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
LIMIT 100
"""

Q61 = """
WITH promotional AS (
  SELECT SUM(ss_ext_sales_price) AS promotions
  FROM store_sales, store, promotion, date_dim, customer,
       customer_address, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_promo_sk = p_promo_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk
    AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5.0 AND s_gmt_offset = -5.0
    AND i_category = 'Jewelry'
    AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
         OR p_channel_tv = 'Y')
    AND d_year = 2000 AND d_moy = 11
), all_sales AS (
  SELECT SUM(ss_ext_sales_price) AS total
  FROM store_sales, store, date_dim, customer, customer_address, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk
    AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5.0 AND s_gmt_offset = -5.0
    AND i_category = 'Jewelry'
    AND d_year = 2000 AND d_moy = 11
)
SELECT promotions, total, promotions / total * 100 AS pct
FROM promotional, all_sales
"""

Q65 = """
WITH sa AS (
  SELECT ss_store_sk, ss_item_sk, SUM(ss_sales_price) AS revenue
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  GROUP BY ss_store_sk, ss_item_sk
), sb AS (
  SELECT ss_store_sk AS store_sk, AVG(revenue) AS ave
  FROM sa
  GROUP BY ss_store_sk
)
SELECT s_store_name, i_item_desc, sa.revenue, i_current_price,
       i_wholesale_cost, i_brand
FROM store, item, sa, sb
WHERE sb.store_sk = sa.ss_store_sk
  AND sa.revenue <= 0.1 * sb.ave
  AND s_store_sk = sa.ss_store_sk
  AND i_item_sk = sa.ss_item_sk
ORDER BY s_store_name, i_item_desc, sa.revenue
LIMIT 100
"""

Q68 = """
WITH dn AS (
  SELECT ss_ticket_number, ss_customer_sk, ca_city AS bought_city,
         SUM(ss_ext_sales_price) AS extended_price,
         SUM(ss_ext_list_price) AS list_price,
         SUM(ss_ext_tax) AS extended_tax
  FROM store_sales, date_dim, store, household_demographics,
       customer_address
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND ss_addr_sk = ca_address_sk
    AND d_dom BETWEEN 1 AND 2
    AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
    AND d_year = 2000
    AND s_city IN ('rivertown', 'hilltop')
  GROUP BY ss_ticket_number, ss_customer_sk, ca_city
)
SELECT c_last_name, c_first_name, ca_city AS current_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
FROM dn, customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, ss_ticket_number
LIMIT 100
"""

Q79 = """
WITH ms AS (
  SELECT ss_ticket_number, ss_customer_sk, s_city,
         SUM(ss_coupon_amt) AS amt, SUM(ss_net_profit) AS profit
  FROM store_sales, date_dim, store, household_demographics
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
    AND d_dow = 0
    AND d_year = 2000
    AND s_number_employees BETWEEN 200 AND 295
  GROUP BY ss_ticket_number, ss_customer_sk, s_city
)
SELECT c_last_name, c_first_name, substr(s_city, 1, 30) AS city,
       ss_ticket_number, amt, profit
FROM ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, city, profit
LIMIT 100
"""

Q88 = """
SELECT *
FROM
 (SELECT COUNT(*) AS h8_30_to_9 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
    AND ss_store_sk = s_store_sk AND t_hour = 8 AND t_minute >= 30
    AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
         OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
         OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
    AND s_store_name = 'ese') s1,
 (SELECT COUNT(*) AS h9_to_9_30 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
    AND ss_store_sk = s_store_sk AND t_hour = 9 AND t_minute < 30
    AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
         OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
         OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
    AND s_store_name = 'ese') s2,
 (SELECT COUNT(*) AS h9_30_to_10 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
    AND ss_store_sk = s_store_sk AND t_hour = 9 AND t_minute >= 30
    AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
         OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
         OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
    AND s_store_name = 'ese') s3,
 (SELECT COUNT(*) AS h10_to_10_30 FROM store_sales,
         household_demographics, time_dim, store
  WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
    AND ss_store_sk = s_store_sk AND t_hour = 10 AND t_minute < 30
    AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
         OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
         OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
    AND s_store_name = 'ese') s4
"""

Q93 = """
WITH t AS (
  SELECT ss_customer_sk,
         CASE WHEN sr_return_quantity IS NOT NULL
              THEN (ss_quantity - sr_return_quantity) * ss_sales_price
              ELSE ss_quantity * ss_sales_price END AS act_sales
  FROM store_sales
  LEFT JOIN store_returns
    ON sr_item_sk = ss_item_sk AND sr_ticket_number = ss_ticket_number,
       reason
  WHERE sr_reason_sk = r_reason_sk
    AND r_reason_desc = 'reason 3'
)
SELECT ss_customer_sk, SUM(act_sales) AS sumsales
FROM t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
LIMIT 100
"""

ALL.update({1: Q1, 6: Q6, 8: Q8, 13: Q13, 41: Q41, 43: Q43, 46: Q46,
            48: Q48, 59: Q59, 61: Q61, 65: Q65, 68: Q68, 79: Q79,
            88: Q88, 93: Q93})
