"""TPC-DS query subset as SQL against the engine's SQL frontend
(reference ships the full 99 in ``benchmarking/tpcds/queries``). Shapes
preserved and sized to the synthetic datagen: the BASELINE configs'
rolling/window trio (Q47/Q63/Q89), the dimensional-aggregate family
(Q3/Q42/Q52/Q55), quarterly windows (Q53), and the class-revenue-ratio
window (Q98)."""

Q47 = """
WITH monthly AS (
  SELECT i_category, i_brand, s_store_name, s_company_name,
         d_year, d_moy,
         SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_year = 2000
  GROUP BY i_category, i_brand, s_store_name, s_company_name,
           d_year, d_moy
), v1 AS (
  SELECT i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
         sum_sales,
         AVG(sum_sales) OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name, d_year) AS avg_monthly_sales,
         RANK() OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name
             ORDER BY d_year, d_moy) AS rn
  FROM monthly
)
SELECT i_category, i_brand, s_store_name, d_year, d_moy, sum_sales,
       avg_monthly_sales
FROM v1
WHERE avg_monthly_sales > 0
  AND sum_sales - avg_monthly_sales > 0.1 * avg_monthly_sales
ORDER BY sum_sales DESC, i_category, i_brand, s_store_name, d_moy
LIMIT 100
"""

Q63 = """
WITH monthly AS (
  SELECT i_manager_id, d_moy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000
  GROUP BY i_manager_id, d_moy
)
SELECT i_manager_id, sum_sales,
       AVG(sum_sales) OVER (PARTITION BY i_manager_id) AS avg_monthly_sales
FROM monthly
ORDER BY i_manager_id, avg_monthly_sales, sum_sales
LIMIT 100
"""

Q89 = """
WITH monthly AS (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_year = 2000
  GROUP BY i_category, i_class, i_brand, s_store_name, s_company_name,
           d_moy
)
SELECT i_category, i_class, i_brand, s_store_name, s_company_name, d_moy,
       sum_sales,
       AVG(sum_sales) OVER (
           PARTITION BY i_category, i_brand, s_store_name,
                        s_company_name) AS avg_monthly_sales
FROM monthly
ORDER BY sum_sales - avg_monthly_sales, s_store_name
LIMIT 100
"""

Q3 = """
SELECT d_year, i_brand_id, i_brand, SUM(ss_ext_sales_price) AS sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, i_brand_id
LIMIT 100
"""

Q42 = """
SELECT d_year, i_category_id, i_category,
       SUM(ss_ext_sales_price) AS sum_sales
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY sum_sales DESC, d_year, i_category_id, i_category
LIMIT 100
"""

Q52 = """
SELECT d_year, i_brand_id, i_brand, SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, i_brand_id
LIMIT 100
"""

Q53 = """
WITH quarterly AS (
  SELECT i_manufact_id, d_qoy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000
    AND i_category IN ('Books', 'Home', 'Electronics')
  GROUP BY i_manufact_id, d_qoy
)
SELECT i_manufact_id, sum_sales,
       AVG(sum_sales) OVER (PARTITION BY i_manufact_id)
           AS avg_quarterly_sales
FROM quarterly
ORDER BY avg_quarterly_sales DESC, sum_sales, i_manufact_id
LIMIT 100
"""

Q55 = """
SELECT i_brand_id, i_brand, SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, i_brand_id
LIMIT 100
"""

Q98 = """
WITH revenue AS (
  SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
         SUM(ss_ext_sales_price) AS itemrevenue
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND i_category IN ('Sports', 'Books', 'Home')
    AND d_year = 2000
    AND d_moy BETWEEN 2 AND 4
  GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
)
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / SUM(itemrevenue) OVER (PARTITION BY i_class)
           AS revenueratio
FROM revenue
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
"""

ALL = {3: Q3, 42: Q42, 47: Q47, 52: Q52, 53: Q53, 55: Q55, 63: Q63,
       89: Q89, 98: Q98}


def run(qnum: int, get_df):
    """Execute a query with tables bound from ``get_df(name)``."""
    import daft_tpu as dt
    tables = {name: get_df(name)
              for name in ("store_sales", "item", "date_dim", "store")}
    return dt.sql(ALL[qnum], **tables)
