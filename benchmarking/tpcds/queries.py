"""TPC-DS window-function queries (the BASELINE configs' rolling subset):
Q47, Q63, Q89 as SQL against the engine's SQL frontend (reference ships
them in ``benchmarking/tpcds/queries``; shapes preserved — monthly
aggregates joined over date_dim/item/store with OVER(PARTITION BY …)
windows — sized to the synthetic datagen)."""

Q47 = """
WITH monthly AS (
  SELECT i_category, i_brand, s_store_name, s_company_name,
         d_year, d_moy,
         SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_year = 2000
  GROUP BY i_category, i_brand, s_store_name, s_company_name,
           d_year, d_moy
), v1 AS (
  SELECT i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
         sum_sales,
         AVG(sum_sales) OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name, d_year) AS avg_monthly_sales,
         RANK() OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name
             ORDER BY d_year, d_moy) AS rn
  FROM monthly
)
SELECT i_category, i_brand, s_store_name, d_year, d_moy, sum_sales,
       avg_monthly_sales
FROM v1
WHERE avg_monthly_sales > 0
  AND sum_sales - avg_monthly_sales > 0.1 * avg_monthly_sales
ORDER BY sum_sales DESC, i_category, i_brand, s_store_name, d_moy
LIMIT 100
"""

Q63 = """
WITH monthly AS (
  SELECT i_manager_id, d_moy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000
  GROUP BY i_manager_id, d_moy
)
SELECT i_manager_id, sum_sales,
       AVG(sum_sales) OVER (PARTITION BY i_manager_id) AS avg_monthly_sales
FROM monthly
ORDER BY i_manager_id, avg_monthly_sales, sum_sales
LIMIT 100
"""

Q89 = """
WITH monthly AS (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_year = 2000
  GROUP BY i_category, i_class, i_brand, s_store_name, s_company_name,
           d_moy
)
SELECT i_category, i_class, i_brand, s_store_name, s_company_name, d_moy,
       sum_sales,
       AVG(sum_sales) OVER (
           PARTITION BY i_category, i_brand, s_store_name,
                        s_company_name) AS avg_monthly_sales
FROM monthly
ORDER BY sum_sales - avg_monthly_sales, s_store_name
LIMIT 100
"""

ALL = {47: Q47, 63: Q63, 89: Q89}


def run(qnum: int, get_df):
    """Execute a query with tables bound from ``get_df(name)``."""
    import daft_tpu as dt
    tables = {name: get_df(name)
              for name in ("store_sales", "item", "date_dim", "store")}
    return dt.sql(ALL[qnum], **tables)
