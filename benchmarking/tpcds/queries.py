"""TPC-DS query subset as SQL against the engine's SQL frontend
(reference ships the full 99 in ``benchmarking/tpcds/queries``). Shapes
preserved and sized to the synthetic datagen: the BASELINE configs'
rolling/window trio (Q47/Q63/Q89), the dimensional-aggregate family
(Q3/Q42/Q52/Q55), the demographics/promotion family (Q7/Q26), the
customer-address brand query (Q19), the store-hours/ticket family
(Q34/Q73/Q96), quarterly windows (Q53), and the class-revenue-ratio
window (Q98)."""

Q47 = """
WITH monthly AS (
  SELECT i_category, i_brand, s_store_name, s_company_name,
         d_year, d_moy,
         SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_year = 2000
  GROUP BY i_category, i_brand, s_store_name, s_company_name,
           d_year, d_moy
), v1 AS (
  SELECT i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
         sum_sales,
         AVG(sum_sales) OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name, d_year) AS avg_monthly_sales,
         RANK() OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name
             ORDER BY d_year, d_moy) AS rn
  FROM monthly
)
SELECT i_category, i_brand, s_store_name, d_year, d_moy, sum_sales,
       avg_monthly_sales
FROM v1
WHERE avg_monthly_sales > 0
  AND sum_sales - avg_monthly_sales > 0.1 * avg_monthly_sales
ORDER BY sum_sales DESC, i_category, i_brand, s_store_name, d_moy
LIMIT 100
"""

Q63 = """
WITH monthly AS (
  SELECT i_manager_id, d_moy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000
  GROUP BY i_manager_id, d_moy
)
SELECT i_manager_id, sum_sales,
       AVG(sum_sales) OVER (PARTITION BY i_manager_id) AS avg_monthly_sales
FROM monthly
ORDER BY i_manager_id, avg_monthly_sales, sum_sales
LIMIT 100
"""

Q89 = """
WITH monthly AS (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_year = 2000
  GROUP BY i_category, i_class, i_brand, s_store_name, s_company_name,
           d_moy
)
SELECT i_category, i_class, i_brand, s_store_name, s_company_name, d_moy,
       sum_sales,
       AVG(sum_sales) OVER (
           PARTITION BY i_category, i_brand, s_store_name,
                        s_company_name) AS avg_monthly_sales
FROM monthly
ORDER BY sum_sales - avg_monthly_sales, s_store_name
LIMIT 100
"""

Q3 = """
SELECT d_year, i_brand_id, i_brand, SUM(ss_ext_sales_price) AS sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, i_brand_id
LIMIT 100
"""

Q42 = """
SELECT d_year, i_category_id, i_category,
       SUM(ss_ext_sales_price) AS sum_sales
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY sum_sales DESC, d_year, i_category_id, i_category
LIMIT 100
"""

Q52 = """
SELECT d_year, i_brand_id, i_brand, SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, i_brand_id
LIMIT 100
"""

Q53 = """
WITH quarterly AS (
  SELECT i_manufact_id, d_qoy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000
    AND i_category IN ('Books', 'Home', 'Electronics')
  GROUP BY i_manufact_id, d_qoy
)
SELECT i_manufact_id, sum_sales,
       AVG(sum_sales) OVER (PARTITION BY i_manufact_id)
           AS avg_quarterly_sales
FROM quarterly
ORDER BY avg_quarterly_sales DESC, sum_sales, i_manufact_id
LIMIT 100
"""

Q55 = """
SELECT i_brand_id, i_brand, SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, i_brand_id
LIMIT 100
"""

Q98 = """
WITH revenue AS (
  SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
         SUM(ss_ext_sales_price) AS itemrevenue
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND i_category IN ('Sports', 'Books', 'Home')
    AND d_year = 2000
    AND d_moy BETWEEN 2 AND 4
  GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
)
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / SUM(itemrevenue) OVER (PARTITION BY i_class)
           AS revenueratio
FROM revenue
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
"""

Q7 = """
SELECT i_item_id, AVG(ss_quantity) AS agg1, AVG(ss_list_price) AS agg2,
       AVG(ss_coupon_amt) AS agg3, AVG(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

Q19 = """
SELECT i_brand_id, i_brand, i_manufact_id,
       SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id BETWEEN 1 AND 40
  AND d_moy = 11
  AND d_year = 1999
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ss_store_sk = s_store_sk
GROUP BY i_brand_id, i_brand, i_manufact_id
ORDER BY ext_price DESC, i_brand_id, i_manufact_id
LIMIT 100
"""

Q26 = """
SELECT i_item_id, AVG(ss_quantity) AS agg1, AVG(ss_list_price) AS agg2,
       AVG(ss_coupon_amt) AS agg3, AVG(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'F'
  AND cd_marital_status = 'W'
  AND cd_education_status = 'Primary'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

Q34 = """
WITH tickets AS (
  SELECT ss_ticket_number, ss_customer_sk, COUNT(*) AS cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND d_dom BETWEEN 1 AND 3
    AND hd_vehicle_count > 0
    AND d_year = 2000
  GROUP BY ss_ticket_number, ss_customer_sk
)
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM tickets, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 15 AND 20
ORDER BY c_last_name, c_first_name, ss_ticket_number DESC
"""

Q73 = """
WITH tickets AS (
  SELECT ss_ticket_number, ss_customer_sk, COUNT(*) AS cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND d_dom BETWEEN 1 AND 2
    AND hd_buy_potential IN ('>10000', 'Unknown')
    AND hd_vehicle_count > 0
    AND d_year = 2000
  GROUP BY ss_ticket_number, ss_customer_sk
)
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM tickets, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name
"""

Q96 = """
SELECT COUNT(1) AS cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk
  AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND t_hour = 20
  AND t_minute >= 30
  AND hd_dep_count = 7
ORDER BY cnt
LIMIT 100
"""

ALL = {3: Q3, 7: Q7, 19: Q19, 26: Q26, 34: Q34, 42: Q42, 47: Q47, 52: Q52,
       53: Q53, 55: Q55, 63: Q63, 73: Q73, 89: Q89, 96: Q96, 98: Q98}


TABLES = ("store_sales", "item", "date_dim", "store", "customer",
          "customer_address", "customer_demographics", "promotion",
          "household_demographics", "time_dim")


def tables_of(qnum: int):
    """Table names a query actually references (underscores are word
    chars, so e.g. ``store`` never matches inside ``store_sales``)."""
    import re
    sql = ALL[qnum]
    return [t for t in TABLES if re.search(rf"\b{t}\b", sql)]


def run(qnum: int, get_df):
    """Execute a query with only its referenced tables bound from
    ``get_df(name)`` — datasets generated before newer tables were added
    keep working for the queries they cover."""
    import daft_tpu as dt
    tables = {name: get_df(name) for name in tables_of(qnum)}
    return dt.sql(ALL[qnum], **tables)
