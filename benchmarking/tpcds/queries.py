"""All 99 TPC-DS queries as SQL against the engine's SQL frontend
(reference ships the full set in ``benchmarking/tpcds/queries``), covering
all three sales channels (store / catalog / web), inventory, and the
ROLLUP families. Clause structures follow the public spec; literal
vocabularies (brand/category/city names, date ranges) adapt to the
synthetic datagen's 1999-2001 calendar so results are non-degenerate.
Families: rolling windows (47/63/89), dimensional aggregates (3/42/52/55),
demographics + promotions (7/26/61), returns (1/30/81/91/93), correlated
scalar subqueries (1/6/30/32/81/92), EXISTS incl. non-equality residual
correlation (16/41/69/94/95), set ops (8/38/87), ROLLUP/CUBE
(5/18/22/27/67/77/80), inventory (21/22/37/39/82), cross-channel unions
(2/5/33/56/60/71/76/77/80), ship-day pivots (50/62/99), weekday pivots
(43/59), windows-over-aggregates (12-shape: 20/98), full outer (97)."""

Q47 = """
WITH v1 AS (
  SELECT i_category, i_brand, s_store_name, s_company_name,
         d_year, d_moy,
         SUM(ss_sales_price) AS sum_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND (d_year = 2000
         OR (d_year = 2000 - 1 AND d_moy = 12)
         OR (d_year = 2000 + 1 AND d_moy = 1))
  GROUP BY i_category, i_brand, s_store_name, s_company_name,
           d_year, d_moy
), v1w AS (
  SELECT i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
         sum_sales,
         AVG(sum_sales) OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name, d_year) AS avg_monthly_sales,
         RANK() OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name
             ORDER BY d_year, d_moy) AS rn
  FROM v1
), v2 AS (
  SELECT v1w.i_category, v1w.i_brand, v1w.s_store_name,
         v1w.s_company_name, v1w.d_year, v1w.d_moy,
         v1w.avg_monthly_sales, v1w.sum_sales,
         v1w_lag.sum_sales AS psum, v1w_lead.sum_sales AS nsum
  FROM v1w, v1w v1w_lag, v1w v1w_lead
  WHERE v1w.i_category = v1w_lag.i_category
    AND v1w.i_category = v1w_lead.i_category
    AND v1w.i_brand = v1w_lag.i_brand
    AND v1w.i_brand = v1w_lead.i_brand
    AND v1w.s_store_name = v1w_lag.s_store_name
    AND v1w.s_store_name = v1w_lead.s_store_name
    AND v1w.s_company_name = v1w_lag.s_company_name
    AND v1w.s_company_name = v1w_lead.s_company_name
    AND v1w.rn = v1w_lag.rn + 1
    AND v1w.rn = v1w_lead.rn - 1
)
SELECT i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
       avg_monthly_sales, sum_sales, psum, nsum
FROM v2
WHERE d_year = 2000
  AND avg_monthly_sales > 0
  AND CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, i_category, i_brand,
         s_store_name, s_company_name, d_year, d_moy
LIMIT 100
"""

Q63 = """
WITH tmp1 AS (
  SELECT i_manager_id, d_moy, SUM(ss_sales_price) AS sum_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_month_seq IN (1200, 1200 + 1, 1200 + 2, 1200 + 3, 1200 + 4,
                        1200 + 5, 1200 + 6, 1200 + 7, 1200 + 8, 1200 + 9,
                        1200 + 10, 1200 + 11)
    AND ((i_category IN ('Books', 'Children', 'Electronics')
          AND i_class IN ('personal', 'portable', 'reference',
                          'self-help'))
         OR (i_category IN ('Women', 'Music', 'Men')
             AND i_class IN ('accessories', 'classical', 'fragrances',
                             'pants')))
  GROUP BY i_manager_id, d_moy
), tmp2 AS (
  SELECT i_manager_id, sum_sales,
         AVG(sum_sales) OVER (PARTITION BY i_manager_id)
             AS avg_monthly_sales
  FROM tmp1
)
SELECT i_manager_id, sum_sales, avg_monthly_sales
FROM tmp2
WHERE CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY i_manager_id, avg_monthly_sales, sum_sales
LIMIT 100
"""

Q89 = """
WITH tmp1 AS (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, SUM(ss_sales_price) AS sum_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_year = 2000
    AND ((i_category IN ('Books', 'Electronics', 'Sports')
          AND i_class IN ('computers', 'stereo', 'football'))
         OR (i_category IN ('Men', 'Jewelry', 'Women')
             AND i_class IN ('shirts', 'birdal', 'dresses')))
  GROUP BY i_category, i_class, i_brand, s_store_name, s_company_name,
           d_moy
), tmp2 AS (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, sum_sales,
         AVG(sum_sales) OVER (
             PARTITION BY i_category, i_brand, s_store_name,
                          s_company_name) AS avg_monthly_sales
  FROM tmp1
)
SELECT i_category, i_class, i_brand, s_store_name, s_company_name, d_moy,
       sum_sales, avg_monthly_sales
FROM tmp2
WHERE CASE WHEN avg_monthly_sales <> 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, s_store_name, i_category,
         i_class, i_brand, d_moy
LIMIT 100
"""

Q3 = """
SELECT d_year, i_brand_id, i_brand, SUM(ss_ext_sales_price) AS sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, i_brand_id
LIMIT 100
"""

Q42 = """
SELECT d_year, i_category_id, i_category,
       SUM(ss_ext_sales_price) AS sum_sales
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY sum_sales DESC, d_year, i_category_id, i_category
LIMIT 100
"""

Q52 = """
SELECT d_year, i_brand_id, i_brand, SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, i_brand_id
LIMIT 100
"""

Q53 = """
WITH quarterly AS (
  SELECT i_manufact_id, d_qoy, SUM(ss_sales_price) AS sum_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000
    AND i_category IN ('Books', 'Home', 'Electronics')
  GROUP BY i_manufact_id, d_qoy
)
SELECT i_manufact_id, sum_sales,
       AVG(sum_sales) OVER (PARTITION BY i_manufact_id)
           AS avg_quarterly_sales
FROM quarterly
ORDER BY avg_quarterly_sales DESC, sum_sales, i_manufact_id
LIMIT 100
"""

Q55 = """
SELECT i_brand_id, i_brand, SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, i_brand_id
LIMIT 100
"""

Q98 = """
WITH revenue AS (
  SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
         SUM(ss_ext_sales_price) AS itemrevenue
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND i_category IN ('Sports', 'Books', 'Home')
    AND d_year = 2000
    AND d_moy BETWEEN 2 AND 4
  GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
)
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / SUM(itemrevenue) OVER (PARTITION BY i_class)
           AS revenueratio
FROM revenue
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
"""

Q7 = """
SELECT i_item_id, AVG(ss_quantity) AS agg1, AVG(ss_list_price) AS agg2,
       AVG(ss_coupon_amt) AS agg3, AVG(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

Q19 = """
SELECT i_brand_id, i_brand, i_manufact_id,
       SUM(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id BETWEEN 1 AND 40
  AND d_moy = 11
  AND d_year = 1999
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ss_store_sk = s_store_sk
GROUP BY i_brand_id, i_brand, i_manufact_id
ORDER BY ext_price DESC, i_brand_id, i_manufact_id
LIMIT 100
"""

Q26 = """
SELECT i_item_id, AVG(ss_quantity) AS agg1, AVG(ss_list_price) AS agg2,
       AVG(ss_coupon_amt) AS agg3, AVG(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'F'
  AND cd_marital_status = 'W'
  AND cd_education_status = 'Primary'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

Q34 = """
WITH tickets AS (
  SELECT ss_ticket_number, ss_customer_sk, COUNT(*) AS cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND d_dom BETWEEN 1 AND 3
    AND hd_vehicle_count > 0
    AND d_year = 2000
  GROUP BY ss_ticket_number, ss_customer_sk
)
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM tickets, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 15 AND 20
ORDER BY c_last_name, c_first_name, ss_ticket_number DESC
"""

Q73 = """
WITH tickets AS (
  SELECT ss_ticket_number, ss_customer_sk, COUNT(*) AS cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND d_dom BETWEEN 1 AND 2
    AND hd_buy_potential IN ('>10000', 'Unknown')
    AND hd_vehicle_count > 0
    AND d_year = 2000
  GROUP BY ss_ticket_number, ss_customer_sk
)
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM tickets, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name
"""

Q96 = """
SELECT COUNT(1) AS cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk
  AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND t_hour = 20
  AND t_minute >= 30
  AND hd_dep_count = 7
ORDER BY cnt
LIMIT 100
"""

ALL = {3: Q3, 7: Q7, 19: Q19, 26: Q26, 34: Q34, 42: Q42, 47: Q47, 52: Q52,
       53: Q53, 55: Q55, 63: Q63, 73: Q73, 89: Q89, 96: Q96, 98: Q98}


TABLES = ("store_sales", "store_returns", "item", "date_dim", "store",
          "customer", "customer_address", "customer_demographics",
          "promotion", "household_demographics", "time_dim", "reason",
          "income_band", "warehouse", "call_center", "catalog_page",
          "ship_mode", "catalog_sales", "catalog_returns", "web_site",
          "web_page", "web_sales", "web_returns", "inventory")


def tables_of(qnum: int):
    """Table names a query actually references (underscores are word
    chars, so e.g. ``store`` never matches inside ``store_sales``)."""
    import re
    sql = ALL[qnum]
    return [t for t in TABLES if re.search(rf"\b{t}\b", sql)]


def run(qnum: int, get_df):
    """Execute a query with only its referenced tables bound from
    ``get_df(name)`` — datasets generated before newer tables were added
    keep working for the queries they cover."""
    import daft_tpu as dt
    tables = {name: get_df(name) for name in tables_of(qnum)}
    return dt.sql(ALL[qnum], **tables)

Q1 = """
WITH customer_total_return AS (
  SELECT sr_customer_sk AS ctr_customer_sk,
         sr_store_sk AS ctr_store_sk,
         SUM(sr_return_amt) AS ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk
)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return > (
    SELECT AVG(ctr_total_return) * 1.2
    FROM customer_total_return ctr2
    WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk
  AND s_state = 'TN'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
"""

Q6 = """
WITH target_month AS (
  SELECT DISTINCT d_month_seq AS m
  FROM date_dim WHERE d_year = 2000 AND d_moy = 1
)
SELECT a.ca_state AS state, COUNT(*) AS cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i
WHERE a.ca_address_sk = c.c_current_addr_sk
  AND c.c_customer_sk = s.ss_customer_sk
  AND s.ss_sold_date_sk = d.d_date_sk
  AND s.ss_item_sk = i.i_item_sk
  AND d.d_month_seq = (SELECT m FROM target_month)
  AND i.i_current_price > 1.2 * (
      SELECT AVG(j.i_current_price) FROM item j
      WHERE j.i_category = i.i_category)
GROUP BY a.ca_state
HAVING COUNT(*) >= 10
ORDER BY cnt, state
LIMIT 100
"""

Q8 = """
WITH zips AS (
  SELECT substr(ca_zip, 1, 5) AS ca_zip
  FROM customer_address
  WHERE substr(ca_zip, 1, 2) IN ('10', '22', '35', '47', '58', '63')
  INTERSECT
  SELECT substr(ca_zip, 1, 5) AS ca_zip
  FROM customer_address ca, customer c
  WHERE ca.ca_address_sk = c.c_current_addr_sk
    AND c.c_preferred_cust_flag = 'Y'
)
SELECT s_store_name, SUM(ss_net_profit) AS profit
FROM store_sales, date_dim, store
WHERE ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2000
  AND substr(s_zip, 1, 2) IN (SELECT substr(ca_zip, 1, 2) FROM zips)
GROUP BY s_store_name
ORDER BY s_store_name
LIMIT 100
"""

Q13 = """
SELECT AVG(ss_quantity) AS avg_q, AVG(ss_ext_sales_price) AS avg_esp,
       AVG(ss_ext_wholesale_cost) AS avg_ewc,
       SUM(ss_ext_wholesale_cost) AS sum_ewc
FROM store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M' AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00 AND hd_dep_count = 3)
       OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
           AND cd_marital_status = 'S' AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 50.00 AND 100.00 AND hd_dep_count = 1)
       OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
           AND cd_marital_status = 'W' AND cd_education_status = 'Secondary'
           AND ss_sales_price BETWEEN 150.00 AND 200.00 AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OR', 'WA')
        AND ss_net_profit BETWEEN 100 AND 200)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('CA', 'NY', 'TN')
           AND ss_net_profit BETWEEN 150 AND 300)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('SD', 'GA', 'KY')
           AND ss_net_profit BETWEEN 50 AND 250))
"""

Q41 = """
SELECT DISTINCT i_product_name
FROM item i1
WHERE i_manufact_id BETWEEN 70 AND 110
  AND EXISTS (
    SELECT * FROM item i2
    WHERE i2.i_manufact = i1.i_manufact
      AND ((i2.i_category = 'Women'
            AND i2.i_color IN ('powder', 'orchid')
            AND i2.i_units IN ('Oz', 'Each')
            AND i2.i_size IN ('medium', 'N/A'))
           OR (i2.i_category = 'Men'
               AND i2.i_color IN ('slate', 'navy')
               AND i2.i_units IN ('Bunch', 'Ton')
               AND i2.i_size IN ('large', 'petite'))))
ORDER BY i_product_name
LIMIT 100
"""

Q43 = """
SELECT s_store_name, s_store_sk,
       SUM(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                ELSE NULL END) AS sun_sales,
       SUM(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                ELSE NULL END) AS mon_sales,
       SUM(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                ELSE NULL END) AS fri_sales,
       SUM(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price
                ELSE NULL END) AS sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_gmt_offset = -5.0
  AND d_year = 2000
GROUP BY s_store_name, s_store_sk
ORDER BY s_store_name, s_store_sk
LIMIT 100
"""

Q46 = """
WITH dn AS (
  SELECT ss_ticket_number, ss_customer_sk, ca_city AS bought_city,
         SUM(ss_coupon_amt) AS amt, SUM(ss_net_profit) AS profit
  FROM store_sales, date_dim, store, household_demographics,
       customer_address
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND ss_addr_sk = ca_address_sk
    AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
    AND d_dow IN (5, 6)
    AND d_year = 2000
    AND s_city IN ('rivertown', 'lakeside')
  GROUP BY ss_ticket_number, ss_customer_sk, ca_city
)
SELECT c_last_name, c_first_name, ca_city AS current_city, bought_city,
       ss_ticket_number, amt, profit
FROM dn, customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, current_city, bought_city,
         ss_ticket_number
LIMIT 100
"""

Q48 = """
SELECT SUM(ss_quantity) AS total_q
FROM store_sales, store, customer_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'M'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'D'
           AND cd_education_status = 'Primary'
           AND ss_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'W'
           AND cd_education_status = 'Secondary'
           AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'NM', 'OR')
        AND ss_net_profit BETWEEN 0 AND 2000)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('CA', 'NY', 'WA')
           AND ss_net_profit BETWEEN 150 AND 3000)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('TN', 'GA', 'KY')
           AND ss_net_profit BETWEEN 50 AND 25000))
"""

Q59 = """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
         SUM(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                  ELSE NULL END) AS sun_sales,
         SUM(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                  ELSE NULL END) AS mon_sales,
         SUM(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                  ELSE NULL END) AS fri_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk
), y AS (
  SELECT s_store_name AS s_store_name1, wss.d_week_seq AS d_week_seq1,
         s_store_id AS s_store_id1, sun_sales AS sun_sales1,
         mon_sales AS mon_sales1, fri_sales AS fri_sales1
  FROM wss, store, date_dim d
  WHERE d.d_week_seq = wss.d_week_seq
    AND ss_store_sk = s_store_sk AND d_year = 1999
), x AS (
  SELECT s_store_name AS s_store_name2, wss.d_week_seq AS d_week_seq2,
         s_store_id AS s_store_id2, sun_sales AS sun_sales2,
         mon_sales AS mon_sales2, fri_sales AS fri_sales2
  FROM wss, store, date_dim d
  WHERE d.d_week_seq = wss.d_week_seq
    AND ss_store_sk = s_store_sk AND d_year = 2000
)
SELECT s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2 AS sun_ratio,
       mon_sales1 / mon_sales2 AS mon_ratio,
       fri_sales1 / fri_sales2 AS fri_ratio
FROM y, x
WHERE s_store_id1 = s_store_id2
  AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
LIMIT 100
"""

Q61 = """
WITH promotional AS (
  SELECT SUM(ss_ext_sales_price) AS promotions
  FROM store_sales, store, promotion, date_dim, customer,
       customer_address, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_promo_sk = p_promo_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk
    AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5.0 AND s_gmt_offset = -5.0
    AND i_category = 'Jewelry'
    AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
         OR p_channel_tv = 'Y')
    AND d_year = 2000 AND d_moy = 11
), all_sales AS (
  SELECT SUM(ss_ext_sales_price) AS total
  FROM store_sales, store, date_dim, customer, customer_address, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk
    AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5.0 AND s_gmt_offset = -5.0
    AND i_category = 'Jewelry'
    AND d_year = 2000 AND d_moy = 11
)
SELECT promotions, total, promotions / total * 100 AS pct
FROM promotional, all_sales
"""

Q65 = """
WITH sa AS (
  SELECT ss_store_sk, ss_item_sk, SUM(ss_sales_price) AS revenue
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  GROUP BY ss_store_sk, ss_item_sk
), sb AS (
  SELECT ss_store_sk AS store_sk, AVG(revenue) AS ave
  FROM sa
  GROUP BY ss_store_sk
)
SELECT s_store_name, i_item_desc, sa.revenue, i_current_price,
       i_wholesale_cost, i_brand
FROM store, item, sa, sb
WHERE sb.store_sk = sa.ss_store_sk
  AND sa.revenue <= 0.1 * sb.ave
  AND s_store_sk = sa.ss_store_sk
  AND i_item_sk = sa.ss_item_sk
ORDER BY s_store_name, i_item_desc, sa.revenue
LIMIT 100
"""

Q68 = """
WITH dn AS (
  SELECT ss_ticket_number, ss_customer_sk, ca_city AS bought_city,
         SUM(ss_ext_sales_price) AS extended_price,
         SUM(ss_ext_list_price) AS list_price,
         SUM(ss_ext_tax) AS extended_tax
  FROM store_sales, date_dim, store, household_demographics,
       customer_address
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND ss_addr_sk = ca_address_sk
    AND d_dom BETWEEN 1 AND 2
    AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
    AND d_year = 2000
    AND s_city IN ('rivertown', 'hilltop')
  GROUP BY ss_ticket_number, ss_customer_sk, ca_city
)
SELECT c_last_name, c_first_name, ca_city AS current_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
FROM dn, customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, ss_ticket_number
LIMIT 100
"""

Q79 = """
WITH ms AS (
  SELECT ss_ticket_number, ss_customer_sk, s_city,
         SUM(ss_coupon_amt) AS amt, SUM(ss_net_profit) AS profit
  FROM store_sales, date_dim, store, household_demographics
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_hdemo_sk = hd_demo_sk
    AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
    AND d_dow = 0
    AND d_year = 2000
    AND s_number_employees BETWEEN 200 AND 295
  GROUP BY ss_ticket_number, ss_customer_sk, s_city
)
SELECT c_last_name, c_first_name, substr(s_city, 1, 30) AS city,
       ss_ticket_number, amt, profit
FROM ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, city, profit
LIMIT 100
"""

Q88 = """
SELECT *
FROM
 (SELECT COUNT(*) AS h8_30_to_9 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
    AND ss_store_sk = s_store_sk AND t_hour = 8 AND t_minute >= 30
    AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
         OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
         OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
    AND s_store_name = 'ese') s1,
 (SELECT COUNT(*) AS h9_to_9_30 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
    AND ss_store_sk = s_store_sk AND t_hour = 9 AND t_minute < 30
    AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
         OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
         OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
    AND s_store_name = 'ese') s2,
 (SELECT COUNT(*) AS h9_30_to_10 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
    AND ss_store_sk = s_store_sk AND t_hour = 9 AND t_minute >= 30
    AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
         OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
         OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
    AND s_store_name = 'ese') s3,
 (SELECT COUNT(*) AS h10_to_10_30 FROM store_sales,
         household_demographics, time_dim, store
  WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
    AND ss_store_sk = s_store_sk AND t_hour = 10 AND t_minute < 30
    AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
         OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
         OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
    AND s_store_name = 'ese') s4
"""

Q93 = """
WITH t AS (
  SELECT ss_customer_sk,
         CASE WHEN sr_return_quantity IS NOT NULL
              THEN (ss_quantity - sr_return_quantity) * ss_sales_price
              ELSE ss_quantity * ss_sales_price END AS act_sales
  FROM store_sales
  LEFT JOIN store_returns
    ON sr_item_sk = ss_item_sk AND sr_ticket_number = ss_ticket_number,
       reason
  WHERE sr_reason_sk = r_reason_sk
    AND r_reason_desc = 'reason 3'
)
SELECT ss_customer_sk, SUM(act_sales) AS sumsales
FROM t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
LIMIT 100
"""

ALL.update({1: Q1, 6: Q6, 8: Q8, 13: Q13, 41: Q41, 43: Q43, 46: Q46,
            48: Q48, 59: Q59, 61: Q61, 65: Q65, 68: Q68, 79: Q79,
            88: Q88, 93: Q93})

# --------------------------------------------------------------------------
# round 4: cross-channel (catalog/web/inventory) + ROLLUP query families.
# Spec-faithful paraphrases of the public TPC-DS query set
# (reference ships the full text under benchmarking/tpcds/queries/*.sql);
# qualification parameters adapted to this datagen's 1999-2001 calendar.

Q15 = """
SELECT ca_zip, SUM(cs_sales_price) AS total_sales
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348', '81792')
       OR ca_state IN ('CA', 'WA', 'GA')
       OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2000
GROUP BY ca_zip
ORDER BY ca_zip
LIMIT 100
"""

Q20 = """
WITH revenue AS (
  SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
         SUM(cs_ext_sales_price) AS itemrevenue
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk = i_item_sk
    AND i_category IN ('Sports', 'Books', 'Home')
    AND cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '1999-02-22'
                   AND DATE '1999-02-22' + INTERVAL '30' DAY
  GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
)
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0 / SUM(itemrevenue) OVER (PARTITION BY i_class)
           AS revenueratio
FROM revenue
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

Q21 = """
SELECT w_warehouse_name, i_item_id,
       SUM(CASE WHEN d_date < DATE '2000-03-11' THEN inv_quantity_on_hand
                ELSE 0 END) AS inv_before,
       SUM(CASE WHEN d_date >= DATE '2000-03-11' THEN inv_quantity_on_hand
                ELSE 0 END) AS inv_after
FROM inventory, warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = inv_item_sk
  AND inv_warehouse_sk = w_warehouse_sk
  AND inv_date_sk = d_date_sk
  AND d_date BETWEEN DATE '2000-03-11' - INTERVAL '30' DAY
                 AND DATE '2000-03-11' + INTERVAL '30' DAY
GROUP BY w_warehouse_name, i_item_id
HAVING (CASE WHEN SUM(CASE WHEN d_date < DATE '2000-03-11'
                           THEN inv_quantity_on_hand ELSE 0 END) > 0
             THEN SUM(CASE WHEN d_date >= DATE '2000-03-11'
                           THEN inv_quantity_on_hand ELSE 0 END) * 1.0 /
                  SUM(CASE WHEN d_date < DATE '2000-03-11'
                           THEN inv_quantity_on_hand ELSE 0 END)
             ELSE NULL END) BETWEEN 0.666667 AND 1.5
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
"""

Q25 = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       SUM(ss_net_profit) AS store_sales_profit,
       SUM(sr_net_loss) AS store_returns_loss,
       SUM(cs_net_profit) AS catalog_sales_profit
FROM store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 2000 AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 4 AND 10 AND d2.d_year = 2000
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_moy BETWEEN 4 AND 10 AND d3.d_year = 2000
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
"""

Q29 = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       SUM(ss_quantity) AS store_sales_quantity,
       SUM(sr_return_quantity) AS store_returns_quantity,
       SUM(cs_quantity) AS catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 1999 AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 4 AND 7 AND d2.d_year = 1999
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
"""

Q37 = """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 20 AND 50
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '2000-02-01' AND DATE '2000-02-01' + INTERVAL '60' DAY
  AND i_manufact_id IN (100, 120, 140, 160)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
"""

Q50 = """
SELECT s_store_name, s_company_id, s_street_number, s_street_name,
       SUM(CASE WHEN sr_returned_date_sk - ss_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS days_30,
       SUM(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 30
                 AND sr_returned_date_sk - ss_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS days_31_60,
       SUM(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 60
                 AND sr_returned_date_sk - ss_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS days_61_90,
       SUM(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 90
                 AND sr_returned_date_sk - ss_sold_date_sk <= 120
                THEN 1 ELSE 0 END) AS days_91_120,
       SUM(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 120
                THEN 1 ELSE 0 END) AS days_over_120
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = 2000 AND d2.d_moy = 8
  AND ss_ticket_number = sr_ticket_number AND ss_item_sk = sr_item_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND sr_returned_date_sk = d2.d_date_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_company_id, s_street_number, s_street_name
ORDER BY s_store_name, s_company_id
LIMIT 100
"""

Q62 = """
SELECT substr(w_warehouse_name, 1, 20) AS wh, sm_type, web_name,
       SUM(CASE WHEN ws_ship_date_sk - ws_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS days_30,
       SUM(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 30
                 AND ws_ship_date_sk - ws_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS days_31_60,
       SUM(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 60
                 AND ws_ship_date_sk - ws_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS days_61_90,
       SUM(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 90
                 AND ws_ship_date_sk - ws_sold_date_sk <= 120
                THEN 1 ELSE 0 END) AS days_91_120,
       SUM(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 120
                THEN 1 ELSE 0 END) AS days_over_120
FROM web_sales, warehouse, ship_mode, web_site, date_dim
WHERE d_month_seq BETWEEN 1212 AND 1212 + 11
  AND ws_ship_date_sk = d_date_sk
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
  AND ws_web_site_sk = web_site_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY wh, sm_type, web_name
LIMIT 100
"""

Q79 = """
SELECT c_last_name, c_first_name, substr(s_city, 1, 30) AS city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, s_city,
             SUM(ss_coupon_amt) AS amt, SUM(ss_net_profit) AS profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
        AND d_dow = 1
        AND d_year IN (1999, 2000, 2001)
        AND s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, city, profit
LIMIT 100
"""

Q82 = """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 30 AND 60
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '2000-05-25' AND DATE '2000-05-25' + INTERVAL '60' DAY
  AND i_manufact_id IN (50, 70, 90, 110)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
"""

Q84 = """
SELECT c_customer_id AS customer_id,
       c_last_name + ', ' + c_first_name AS customername
FROM customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
WHERE ca_city = 'hilltop'
  AND c_current_addr_sk = ca_address_sk
  AND ib_lower_bound >= 30000
  AND ib_upper_bound <= 80000
  AND ib_income_band_sk = hd_income_band_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND sr_cdemo_sk = cd_demo_sk
ORDER BY c_customer_id
LIMIT 100
"""

Q90 = """
SELECT CAST(amc AS DOUBLE) / CAST(pmc AS DOUBLE) AS am_pm_ratio
FROM (SELECT COUNT(*) AS amc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = t_time_sk
        AND ws_ship_hdemo_sk = hd_demo_sk
        AND ws_web_page_sk = wp_web_page_sk
        AND t_hour BETWEEN 8 AND 9
        AND hd_dep_count = 6
        AND wp_char_count BETWEEN 5000 AND 5200) at_,
     (SELECT COUNT(*) AS pmc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = t_time_sk
        AND ws_ship_hdemo_sk = hd_demo_sk
        AND ws_web_page_sk = wp_web_page_sk
        AND t_hour BETWEEN 19 AND 20
        AND hd_dep_count = 6
        AND wp_char_count BETWEEN 5000 AND 5200) pt
ORDER BY am_pm_ratio
LIMIT 100
"""

Q91 = """
SELECT cc_call_center_id AS call_center, cc_name AS center_name,
       cc_manager AS manager, SUM(cr_net_loss) AS returns_loss
FROM call_center, catalog_returns, date_dim, customer,
     customer_demographics, household_demographics
WHERE cr_call_center_sk = cc_call_center_sk
  AND cr_returned_date_sk = d_date_sk
  AND cr_returning_customer_sk = c_customer_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND d_year = 2000 AND d_moy = 11
  AND ((cd_marital_status = 'M' AND cd_education_status = 'Unknown')
       OR (cd_marital_status = 'W'
           AND cd_education_status = 'Advanced Degree'))
  AND hd_buy_potential LIKE 'Unknown%'
GROUP BY cc_call_center_id, cc_name, cc_manager, cd_marital_status,
         cd_education_status
ORDER BY returns_loss DESC
"""

Q93 = """
SELECT ss_customer_sk, SUM(act_sales) AS sumsales
FROM (SELECT ss_item_sk, ss_ticket_number, ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
                  THEN (ss_quantity - sr_return_quantity) * ss_sales_price
                  ELSE ss_quantity * ss_sales_price END AS act_sales
      FROM store_sales
      LEFT JOIN store_returns
        ON sr_item_sk = ss_item_sk AND sr_ticket_number = ss_ticket_number
      , reason
      WHERE sr_reason_sk = r_reason_sk AND r_reason_desc = 'reason 1') t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
LIMIT 100
"""

Q99 = """
SELECT substr(w_warehouse_name, 1, 20) AS wh, sm_type, cc_name,
       SUM(CASE WHEN cs_ship_date_sk - cs_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS days_30,
       SUM(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 30
                 AND cs_ship_date_sk - cs_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS days_31_60,
       SUM(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 60
                 AND cs_ship_date_sk - cs_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS days_61_90,
       SUM(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 90
                 AND cs_ship_date_sk - cs_sold_date_sk <= 120
                THEN 1 ELSE 0 END) AS days_91_120,
       SUM(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 120
                THEN 1 ELSE 0 END) AS days_over_120
FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE d_month_seq BETWEEN 1212 AND 1212 + 11
  AND cs_ship_date_sk = d_date_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_ship_mode_sk = sm_ship_mode_sk
  AND cs_call_center_sk = cc_call_center_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY wh, sm_type, cc_name
LIMIT 100
"""

ALL.update({15: Q15, 20: Q20, 21: Q21, 25: Q25, 29: Q29, 37: Q37, 50: Q50,
            62: Q62, 79: Q79, 82: Q82, 84: Q84, 90: Q90, 91: Q91, 93: Q93,
            99: Q99})

Q5 = """
WITH ssr AS (
  SELECT s_store_id, SUM(sales_price) AS sales, SUM(profit) AS profit,
         SUM(return_amt) AS returns_, SUM(net_loss) AS profit_loss
  FROM (SELECT ss_store_sk AS store_sk, ss_sold_date_sk AS date_sk,
               ss_ext_sales_price AS sales_price, ss_net_profit AS profit,
               CAST(0 AS DOUBLE) AS return_amt, CAST(0 AS DOUBLE) AS net_loss
        FROM store_sales
        UNION ALL
        SELECT sr_store_sk AS store_sk, sr_returned_date_sk AS date_sk,
               CAST(0 AS DOUBLE) AS sales_price, CAST(0 AS DOUBLE) AS profit,
               sr_return_amt AS return_amt, sr_net_loss AS net_loss
        FROM store_returns) salesreturns, date_dim, store
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '14' DAY
    AND store_sk = s_store_sk
  GROUP BY s_store_id
), csr AS (
  SELECT cp_catalog_page_id, SUM(sales_price) AS sales,
         SUM(profit) AS profit, SUM(return_amt) AS returns_,
         SUM(net_loss) AS profit_loss
  FROM (SELECT cs_catalog_page_sk AS page_sk, cs_sold_date_sk AS date_sk,
               cs_ext_sales_price AS sales_price, cs_net_profit AS profit,
               CAST(0 AS DOUBLE) AS return_amt, CAST(0 AS DOUBLE) AS net_loss
        FROM catalog_sales
        UNION ALL
        SELECT cr_catalog_page_sk AS page_sk,
               cr_returned_date_sk AS date_sk,
               CAST(0 AS DOUBLE) AS sales_price, CAST(0 AS DOUBLE) AS profit,
               cr_return_amount AS return_amt, cr_net_loss AS net_loss
        FROM catalog_returns) salesreturns, date_dim, catalog_page
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '14' DAY
    AND page_sk = cp_catalog_page_sk
  GROUP BY cp_catalog_page_id
), wsr AS (
  SELECT web_site_id, SUM(sales_price) AS sales, SUM(profit) AS profit,
         SUM(return_amt) AS returns_, SUM(net_loss) AS profit_loss
  FROM (SELECT ws_web_site_sk AS wsr_web_site_sk,
               ws_sold_date_sk AS date_sk,
               ws_ext_sales_price AS sales_price, ws_net_profit AS profit,
               CAST(0 AS DOUBLE) AS return_amt, CAST(0 AS DOUBLE) AS net_loss
        FROM web_sales
        UNION ALL
        SELECT ws_web_site_sk AS wsr_web_site_sk,
               wr_returned_date_sk AS date_sk,
               CAST(0 AS DOUBLE) AS sales_price, CAST(0 AS DOUBLE) AS profit,
               wr_return_amt AS return_amt, wr_net_loss AS net_loss
        FROM web_returns
        LEFT JOIN web_sales
          ON wr_item_sk = ws_item_sk
         AND wr_order_number = ws_order_number) salesreturns, date_dim,
       web_site
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '14' DAY
    AND wsr_web_site_sk = web_site_sk
  GROUP BY web_site_id
)
SELECT channel, id, SUM(sales) AS sales, SUM(returns_) AS returns_,
       SUM(profit) AS profit
FROM (SELECT 'store channel' AS channel, 'store' + s_store_id AS id,
             sales, returns_, profit - profit_loss AS profit
      FROM ssr
      UNION ALL
      SELECT 'catalog channel' AS channel,
             'catalog_page' + cp_catalog_page_id AS id,
             sales, returns_, profit - profit_loss AS profit
      FROM csr
      UNION ALL
      SELECT 'web channel' AS channel, 'web_site' + web_site_id AS id,
             sales, returns_, profit - profit_loss AS profit
      FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
"""

Q18 = """
SELECT i_item_id, ca_country, ca_state, ca_county,
       AVG(CAST(cs_quantity AS DOUBLE)) AS agg1,
       AVG(CAST(cs_list_price AS DOUBLE)) AS agg2,
       AVG(CAST(cs_coupon_amt AS DOUBLE)) AS agg3,
       AVG(CAST(cs_sales_price AS DOUBLE)) AS agg4,
       AVG(CAST(cs_net_profit AS DOUBLE)) AS agg5,
       AVG(CAST(c_birth_year AS DOUBLE)) AS agg6,
       AVG(CAST(cd1.cd_dep_count AS DOUBLE)) AS agg7
FROM catalog_sales, customer_demographics cd1, customer_demographics cd2,
     customer, customer_address, date_dim, item
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = 'F'
  AND cd1.cd_education_status = 'Unknown'
  AND c_current_cdemo_sk = cd2.cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND c_birth_month IN (1, 6, 8, 9, 12, 2)
  AND d_year = 2000
  AND ca_state IN ('CA', 'NY', 'TX', 'WA', 'OR', 'TN', 'SD')
GROUP BY ROLLUP (i_item_id, ca_country, ca_state, ca_county)
ORDER BY ca_country, ca_state, ca_county, i_item_id
LIMIT 100
"""

Q22 = """
SELECT i_product_name, i_brand, i_class, i_category,
       AVG(inv_quantity_on_hand) AS qoh
FROM inventory, date_dim, item
WHERE inv_date_sk = d_date_sk
  AND inv_item_sk = i_item_sk
  AND d_month_seq BETWEEN 1212 AND 1212 + 11
GROUP BY ROLLUP (i_product_name, i_brand, i_class, i_category)
ORDER BY qoh, i_product_name, i_brand, i_class, i_category
LIMIT 100
"""

Q27 = """
SELECT i_item_id, s_state, GROUPING(s_state) AS g_state,
       AVG(CAST(ss_quantity AS DOUBLE)) AS agg1,
       AVG(CAST(ss_list_price AS DOUBLE)) AS agg2,
       AVG(CAST(ss_coupon_amt AS DOUBLE)) AS agg3,
       AVG(CAST(ss_sales_price AS DOUBLE)) AS agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2000
  AND s_state IN ('TN', 'SD', 'CA')
GROUP BY ROLLUP (i_item_id, s_state)
ORDER BY i_item_id, s_state
LIMIT 100
"""

Q67 = """
SELECT *
FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
             d_moy, s_store_id, sumsales,
             RANK() OVER (PARTITION BY i_category
                          ORDER BY sumsales DESC) AS rk
      FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year,
                   d_qoy, d_moy, s_store_id,
                   SUM(COALESCE(ss_sales_price * ss_quantity, 0))
                       AS sumsales
            FROM store_sales, date_dim, store, item
            WHERE ss_sold_date_sk = d_date_sk
              AND ss_item_sk = i_item_sk
              AND ss_store_sk = s_store_sk
              AND d_month_seq BETWEEN 1212 AND 1212 + 11
            GROUP BY ROLLUP (i_category, i_class, i_brand, i_product_name,
                             d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
WHERE rk <= 100
ORDER BY i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales, rk
LIMIT 100
"""

Q77 = """
WITH ss AS (
  SELECT s_store_sk, SUM(ss_ext_sales_price) AS sales,
         SUM(ss_net_profit) AS profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk
), sr AS (
  SELECT s_store_sk AS sr_store_sk, SUM(sr_return_amt) AS returns_,
         SUM(sr_net_loss) AS profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk
), cs AS (
  SELECT cs_call_center_sk, SUM(cs_ext_sales_price) AS sales,
         SUM(cs_net_profit) AS profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
  GROUP BY cs_call_center_sk
), cr AS (
  SELECT cr_call_center_sk, SUM(cr_return_amount) AS returns_,
         SUM(cr_net_loss) AS profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
  GROUP BY cr_call_center_sk
), ws AS (
  SELECT wp_web_page_sk, SUM(ws_ext_sales_price) AS sales,
         SUM(ws_net_profit) AS profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk
), wr AS (
  SELECT wp_web_page_sk AS wr_web_page_sk, SUM(wr_return_amt) AS returns_,
         SUM(wr_net_loss) AS profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk
)
SELECT channel, id, SUM(sales) AS sales, SUM(returns_) AS returns_,
       SUM(profit) AS profit
FROM (SELECT 'store channel' AS channel, ss.s_store_sk AS id, sales,
             COALESCE(returns_, 0) AS returns_,
             profit - COALESCE(profit_loss, 0) AS profit
      FROM ss LEFT JOIN sr ON ss.s_store_sk = sr.sr_store_sk
      UNION ALL
      SELECT 'catalog channel' AS channel, cs_call_center_sk AS id, sales,
             COALESCE(returns_, 0) AS returns_,
             profit - COALESCE(profit_loss, 0) AS profit
      FROM cs LEFT JOIN cr ON cs.cs_call_center_sk = cr.cr_call_center_sk
      UNION ALL
      SELECT 'web channel' AS channel, ws.wp_web_page_sk AS id, sales,
             COALESCE(returns_, 0) AS returns_,
             profit - COALESCE(profit_loss, 0) AS profit
      FROM ws LEFT JOIN wr ON ws.wp_web_page_sk = wr.wr_web_page_sk) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
"""

Q80 = """
WITH ssr AS (
  SELECT s_store_id AS store_id, SUM(ss_ext_sales_price) AS sales,
         SUM(COALESCE(sr_return_amt, 0)) AS returns_,
         SUM(ss_net_profit - COALESCE(sr_net_loss, 0)) AS profit
  FROM store_sales
  LEFT JOIN store_returns
    ON ss_item_sk = sr_item_sk AND ss_ticket_number = sr_ticket_number
  , date_dim, store, item, promotion
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND ss_store_sk = s_store_sk
    AND ss_item_sk = i_item_sk
    AND i_current_price > 50
    AND ss_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY s_store_id
), csr AS (
  SELECT cp_catalog_page_id AS catalog_page_id,
         SUM(cs_ext_sales_price) AS sales,
         SUM(COALESCE(cr_return_amount, 0)) AS returns_,
         SUM(cs_net_profit - COALESCE(cr_net_loss, 0)) AS profit
  FROM catalog_sales
  LEFT JOIN catalog_returns
    ON cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number
  , date_dim, catalog_page, item, promotion
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND cs_catalog_page_sk = cp_catalog_page_sk
    AND cs_item_sk = i_item_sk
    AND i_current_price > 50
    AND cs_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY cp_catalog_page_id
), wsr AS (
  SELECT web_site_id, SUM(ws_ext_sales_price) AS sales,
         SUM(COALESCE(wr_return_amt, 0)) AS returns_,
         SUM(ws_net_profit - COALESCE(wr_net_loss, 0)) AS profit
  FROM web_sales
  LEFT JOIN web_returns
    ON ws_item_sk = wr_item_sk AND ws_order_number = wr_order_number
  , date_dim, web_site, item, promotion
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND ws_web_site_sk = web_site_sk
    AND ws_item_sk = i_item_sk
    AND i_current_price > 50
    AND ws_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY web_site_id
)
SELECT channel, id, SUM(sales) AS sales, SUM(returns_) AS returns_,
       SUM(profit) AS profit
FROM (SELECT 'store channel' AS channel, 'store' + store_id AS id,
             sales, returns_, profit
      FROM ssr
      UNION ALL
      SELECT 'catalog channel' AS channel,
             'catalog_page' + catalog_page_id AS id,
             sales, returns_, profit
      FROM csr
      UNION ALL
      SELECT 'web channel' AS channel, 'web_site' + web_site_id AS id,
             sales, returns_, profit
      FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
"""

ALL.update({5: Q5, 18: Q18, 22: Q22, 27: Q27, 67: Q67, 77: Q77, 80: Q80})

Q2 = """
WITH wscs AS (
  SELECT sold_date_sk, sales_price
  FROM (SELECT ws_sold_date_sk AS sold_date_sk,
               ws_ext_sales_price AS sales_price
        FROM web_sales
        UNION ALL
        SELECT cs_sold_date_sk AS sold_date_sk,
               cs_ext_sales_price AS sales_price
        FROM catalog_sales) x
), wswscs AS (
  SELECT d_week_seq,
         SUM(CASE WHEN d_day_name = 'Sunday' THEN sales_price END)
             AS sun_sales,
         SUM(CASE WHEN d_day_name = 'Monday' THEN sales_price END)
             AS mon_sales,
         SUM(CASE WHEN d_day_name = 'Tuesday' THEN sales_price END)
             AS tue_sales,
         SUM(CASE WHEN d_day_name = 'Wednesday' THEN sales_price END)
             AS wed_sales,
         SUM(CASE WHEN d_day_name = 'Thursday' THEN sales_price END)
             AS thu_sales,
         SUM(CASE WHEN d_day_name = 'Friday' THEN sales_price END)
             AS fri_sales,
         SUM(CASE WHEN d_day_name = 'Saturday' THEN sales_price END)
             AS sat_sales
  FROM wscs, date_dim
  WHERE d_date_sk = sold_date_sk
  GROUP BY d_week_seq
)
SELECT d_week_seq1, ROUND(sun_sales1 / sun_sales2, 2) AS r_sun,
       ROUND(mon_sales1 / mon_sales2, 2) AS r_mon,
       ROUND(tue_sales1 / tue_sales2, 2) AS r_tue,
       ROUND(wed_sales1 / wed_sales2, 2) AS r_wed,
       ROUND(thu_sales1 / thu_sales2, 2) AS r_thu,
       ROUND(fri_sales1 / fri_sales2, 2) AS r_fri,
       ROUND(sat_sales1 / sat_sales2, 2) AS r_sat
FROM (SELECT wswscs.d_week_seq AS d_week_seq1,
             sun_sales AS sun_sales1, mon_sales AS mon_sales1,
             tue_sales AS tue_sales1, wed_sales AS wed_sales1,
             thu_sales AS thu_sales1, fri_sales AS fri_sales1,
             sat_sales AS sat_sales1
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq AND d_year = 1999) y,
     (SELECT wswscs.d_week_seq AS d_week_seq2,
             sun_sales AS sun_sales2, mon_sales AS mon_sales2,
             tue_sales AS tue_sales2, wed_sales AS wed_sales2,
             thu_sales AS thu_sales2, fri_sales AS fri_sales2,
             sat_sales AS sat_sales2
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq AND d_year = 2000) z
WHERE d_week_seq1 = d_week_seq2 - 52
ORDER BY d_week_seq1
"""

Q16 = """
SELECT COUNT(DISTINCT cs_order_number) AS order_count,
       SUM(cs_ext_ship_cost) AS total_shipping_cost,
       SUM(cs_net_profit) AS total_net_profit
FROM catalog_sales cs1, date_dim, customer_address, call_center
WHERE d_date BETWEEN DATE '2000-02-01'
                 AND DATE '2000-02-01' + INTERVAL '60' DAY
  AND cs1.cs_ship_date_sk = d_date_sk
  AND cs1.cs_ship_addr_sk = ca_address_sk
  AND ca_state = 'CA'
  AND cs1.cs_call_center_sk = cc_call_center_sk
  AND EXISTS (SELECT 1 FROM catalog_sales cs2
              WHERE cs1.cs_order_number = cs2.cs_order_number
                AND cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  AND NOT EXISTS (SELECT 1 FROM catalog_returns cr1
                  WHERE cs1.cs_order_number = cr1.cr_order_number)
ORDER BY order_count
LIMIT 100
"""

Q30 = """
WITH customer_total_return AS (
  SELECT wr_returning_customer_sk AS ctr_customer_sk,
         ca_state AS ctr_state,
         SUM(wr_return_amt) AS ctr_total_return
  FROM web_returns, date_dim, customer_address
  WHERE wr_returned_date_sk = d_date_sk AND d_year = 2000
    AND wr_returning_addr_sk = ca_address_sk
  GROUP BY wr_returning_customer_sk, ca_state
)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       c_preferred_cust_flag, c_birth_day, c_birth_month, c_birth_year,
       c_birth_country, c_login, c_email_address, ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (
    SELECT AVG(ctr_total_return) * 1.2
    FROM customer_total_return ctr2
    WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'CA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name
LIMIT 100
"""

Q32 = """
SELECT SUM(cs_ext_discount_amt) AS excess_discount_amount
FROM catalog_sales, item, date_dim
WHERE i_manufact_id = 77
  AND i_item_sk = cs_item_sk
  AND d_date BETWEEN DATE '2000-01-27'
                 AND DATE '2000-01-27' + INTERVAL '90' DAY
  AND d_date_sk = cs_sold_date_sk
  AND cs_ext_discount_amt > (
      SELECT 1.3 * AVG(cs_ext_discount_amt)
      FROM catalog_sales cs2, date_dim d2
      WHERE cs2.cs_item_sk = i_item_sk
        AND d2.d_date BETWEEN DATE '2000-01-27'
                          AND DATE '2000-01-27' + INTERVAL '90' DAY
        AND d2.d_date_sk = cs2.cs_sold_date_sk)
ORDER BY excess_discount_amount
LIMIT 100
"""

Q33 = """
WITH ss AS (
  SELECT i_manufact_id, SUM(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Books'))
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 1
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_manufact_id
), cs AS (
  SELECT i_manufact_id, SUM(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Books'))
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 1
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_manufact_id
), ws AS (
  SELECT i_manufact_id, SUM(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Books'))
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 1
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_manufact_id
)
SELECT i_manufact_id, SUM(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales
LIMIT 100
"""

Q38 = """
SELECT COUNT(*) AS cnt
FROM (SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM store_sales, date_dim, customer
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11
      INTERSECT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM catalog_sales, date_dim, customer
      WHERE cs_sold_date_sk = d_date_sk
        AND cs_bill_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11
      INTERSECT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM web_sales, date_dim, customer
      WHERE ws_sold_date_sk = d_date_sk
        AND ws_bill_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11) hot_cust
LIMIT 100
"""

Q40 = """
SELECT w_state, i_item_id,
       SUM(CASE WHEN d_date < DATE '2000-03-11'
                THEN cs_sales_price - COALESCE(cr_refunded_cash, 0)
                ELSE 0 END) AS sales_before,
       SUM(CASE WHEN d_date >= DATE '2000-03-11'
                THEN cs_sales_price - COALESCE(cr_refunded_cash, 0)
                ELSE 0 END) AS sales_after
FROM catalog_sales
LEFT JOIN catalog_returns
  ON cs_order_number = cr_order_number AND cs_item_sk = cr_item_sk
, warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = cs_item_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '2000-03-11' - INTERVAL '30' DAY
                 AND DATE '2000-03-11' + INTERVAL '30' DAY
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
"""

Q56 = """
WITH ss AS (
  SELECT i_item_id, SUM(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blanched', 'burnished'))
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 2
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
), cs AS (
  SELECT i_item_id, SUM(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blanched', 'burnished'))
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 2
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
), ws AS (
  SELECT i_item_id, SUM(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blanched', 'burnished'))
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 2
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
)
SELECT i_item_id, SUM(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
"""

Q59 = """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
         SUM(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price END)
             AS sun_sales,
         SUM(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price END)
             AS mon_sales,
         SUM(CASE WHEN d_day_name = 'Tuesday' THEN ss_sales_price END)
             AS tue_sales,
         SUM(CASE WHEN d_day_name = 'Wednesday' THEN ss_sales_price END)
             AS wed_sales,
         SUM(CASE WHEN d_day_name = 'Thursday' THEN ss_sales_price END)
             AS thu_sales,
         SUM(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price END)
             AS fri_sales,
         SUM(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price END)
             AS sat_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk
)
SELECT s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2 AS r_sun, mon_sales1 / mon_sales2 AS r_mon,
       tue_sales1 / tue_sales2 AS r_tue, wed_sales1 / wed_sales2 AS r_wed,
       thu_sales1 / thu_sales2 AS r_thu, fri_sales1 / fri_sales2 AS r_fri,
       sat_sales1 / sat_sales2 AS r_sat
FROM (SELECT s_store_name AS s_store_name1, wss.d_week_seq AS d_week_seq1,
             s_store_id AS s_store_id1, sun_sales AS sun_sales1,
             mon_sales AS mon_sales1, tue_sales AS tue_sales1,
             wed_sales AS wed_sales1, thu_sales AS thu_sales1,
             fri_sales AS fri_sales1, sat_sales AS sat_sales1
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq
        AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11) y,
     (SELECT s_store_name AS s_store_name2, wss.d_week_seq AS d_week_seq2,
             s_store_id AS s_store_id2, sun_sales AS sun_sales2,
             mon_sales AS mon_sales2, tue_sales AS tue_sales2,
             wed_sales AS wed_sales2, thu_sales AS thu_sales2,
             fri_sales AS fri_sales2, sat_sales AS sat_sales2
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq
        AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN 1212 AND 1212 + 11) x
WHERE s_store_id1 = s_store_id2
  AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
LIMIT 100
"""

Q60 = """
WITH ss AS (
  SELECT i_item_id, SUM(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
), cs AS (
  SELECT i_item_id, SUM(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
), ws AS (
  SELECT i_item_id, SUM(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id
)
SELECT i_item_id, SUM(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
LIMIT 100
"""

Q61 = """
SELECT promotions, total,
       CAST(promotions AS DOUBLE) / CAST(total AS DOUBLE) * 100 AS ratio
FROM (SELECT SUM(ss_ext_sales_price) AS promotions
      FROM store_sales, store, promotion, date_dim, customer,
           customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_promo_sk = p_promo_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = -5
        AND i_category = 'Jewelry'
        AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
             OR p_channel_tv = 'Y')
        AND s_gmt_offset = -5
        AND d_year = 2000 AND d_moy = 11) promotional_sales,
     (SELECT SUM(ss_ext_sales_price) AS total
      FROM store_sales, store, date_dim, customer, customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = -5
        AND i_category = 'Jewelry'
        AND s_gmt_offset = -5
        AND d_year = 2000 AND d_moy = 11) all_sales
ORDER BY promotions, total
LIMIT 100
"""

Q69 = """
SELECT cd_gender, cd_marital_status, cd_education_status,
       COUNT(*) AS cnt1, cd_purchase_estimate, COUNT(*) AS cnt2,
       cd_credit_rating, COUNT(*) AS cnt3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_state IN ('CA', 'TX', 'NY')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT 1 FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2000 AND d_moy BETWEEN 1 AND 3)
  AND NOT EXISTS (SELECT 1 FROM web_sales, date_dim
                  WHERE c.c_customer_sk = ws_bill_customer_sk
                    AND ws_sold_date_sk = d_date_sk
                    AND d_year = 2000 AND d_moy BETWEEN 1 AND 3)
  AND NOT EXISTS (SELECT 1 FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2000 AND d_moy BETWEEN 1 AND 3)
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
LIMIT 100
"""

Q71 = """
SELECT i_brand_id AS brand_id, i_brand AS brand, t_hour, t_minute,
       SUM(ext_price) AS ext_price
FROM item,
     (SELECT ws_ext_sales_price AS ext_price,
             ws_sold_date_sk AS sold_date_sk, ws_item_sk AS sold_item_sk,
             ws_sold_time_sk AS time_sk
      FROM web_sales, date_dim
      WHERE d_date_sk = ws_sold_date_sk AND d_moy = 11 AND d_year = 2000
      UNION ALL
      SELECT cs_ext_sales_price AS ext_price,
             cs_sold_date_sk AS sold_date_sk, cs_item_sk AS sold_item_sk,
             cs_sold_time_sk AS time_sk
      FROM catalog_sales, date_dim
      WHERE d_date_sk = cs_sold_date_sk AND d_moy = 11 AND d_year = 2000
      UNION ALL
      SELECT ss_ext_sales_price AS ext_price,
             ss_sold_date_sk AS sold_date_sk, ss_item_sk AS sold_item_sk,
             ss_sold_time_sk AS time_sk
      FROM store_sales, date_dim
      WHERE d_date_sk = ss_sold_date_sk AND d_moy = 11 AND d_year = 2000
     ) tmp, time_dim
WHERE sold_item_sk = i_item_sk
  AND i_manager_id = 1
  AND time_sk = t_time_sk
  AND (t_meal_time = 'breakfast' OR t_meal_time = 'dinner')
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, i_brand_id
"""

Q76 = """
SELECT channel, col_name, d_year, d_qoy, i_category, COUNT(*) AS sales_cnt,
       SUM(ext_sales_price) AS sales_amt
FROM (SELECT 'store' AS channel, 'ss_store_sk' AS col_name, d_year, d_qoy,
             i_category, ss_ext_sales_price AS ext_sales_price
      FROM store_sales, item, date_dim
      WHERE ss_store_sk IS NULL
        AND ss_sold_date_sk = d_date_sk
        AND ss_item_sk = i_item_sk
      UNION ALL
      SELECT 'web' AS channel, 'ws_ship_customer_sk' AS col_name, d_year,
             d_qoy, i_category, ws_ext_sales_price AS ext_sales_price
      FROM web_sales, item, date_dim
      WHERE ws_ship_customer_sk IS NULL
        AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk = i_item_sk
      UNION ALL
      SELECT 'catalog' AS channel, 'cs_ship_addr_sk' AS col_name, d_year,
             d_qoy, i_category, cs_ext_sales_price AS ext_sales_price
      FROM catalog_sales, item, date_dim
      WHERE cs_ship_addr_sk IS NULL
        AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk = i_item_sk) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category
LIMIT 100
"""

Q81 = """
WITH customer_total_return AS (
  SELECT cr_returning_customer_sk AS ctr_customer_sk,
         ca_state AS ctr_state,
         SUM(cr_return_amt_inc_tax) AS ctr_total_return
  FROM catalog_returns, date_dim, customer_address
  WHERE cr_returned_date_sk = d_date_sk AND d_year = 2000
    AND cr_returning_addr_sk = ca_address_sk
  GROUP BY cr_returning_customer_sk, ca_state
)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       ca_street_number, ca_street_name, ca_street_type, ca_suite_number,
       ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset,
       ca_location_type, ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (
    SELECT AVG(ctr_total_return) * 1.2
    FROM customer_total_return ctr2
    WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'CA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name
LIMIT 100
"""

Q87 = """
SELECT COUNT(*) AS cnt
FROM ((SELECT DISTINCT c_last_name, c_first_name, d_date
       FROM store_sales, date_dim, customer
       WHERE ss_sold_date_sk = d_date_sk
         AND ss_customer_sk = c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1200 + 11)
      EXCEPT
      (SELECT DISTINCT c_last_name, c_first_name, d_date
       FROM catalog_sales, date_dim, customer
       WHERE cs_sold_date_sk = d_date_sk
         AND cs_bill_customer_sk = c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1200 + 11)
      EXCEPT
      (SELECT DISTINCT c_last_name, c_first_name, d_date
       FROM web_sales, date_dim, customer
       WHERE ws_sold_date_sk = d_date_sk
         AND ws_bill_customer_sk = c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1200 + 11)) cool_cust
"""

Q92 = """
SELECT SUM(ws_ext_discount_amt) AS excess_discount_amount
FROM web_sales, item, date_dim
WHERE i_manufact_id = 77
  AND i_item_sk = ws_item_sk
  AND d_date BETWEEN DATE '2000-01-27'
                 AND DATE '2000-01-27' + INTERVAL '90' DAY
  AND d_date_sk = ws_sold_date_sk
  AND ws_ext_discount_amt > (
      SELECT 1.3 * AVG(ws_ext_discount_amt)
      FROM web_sales ws2, date_dim d2
      WHERE ws2.ws_item_sk = i_item_sk
        AND d2.d_date BETWEEN DATE '2000-01-27'
                          AND DATE '2000-01-27' + INTERVAL '90' DAY
        AND d2.d_date_sk = ws2.ws_sold_date_sk)
ORDER BY excess_discount_amount
LIMIT 100
"""

Q94 = """
SELECT COUNT(DISTINCT ws_order_number) AS order_count,
       SUM(ws_ext_ship_cost) AS total_shipping_cost,
       SUM(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN DATE '2000-02-01'
                 AND DATE '2000-02-01' + INTERVAL '60' DAY
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'CA'
  AND ws1.ws_web_site_sk = web_site_sk
  AND web_company_name = 'pri'
  AND EXISTS (SELECT 1 FROM web_sales ws2
              WHERE ws1.ws_order_number = ws2.ws_order_number
                AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  AND NOT EXISTS (SELECT 1 FROM web_returns wr1
                  WHERE ws1.ws_order_number = wr1.wr_order_number)
ORDER BY order_count
LIMIT 100
"""


Q65 = """
SELECT s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
FROM store, item,
     (SELECT ss_store_sk, AVG(revenue) AS ave
      FROM (SELECT ss_store_sk, ss_item_sk,
                   SUM(ss_sales_price) AS revenue
            FROM store_sales, date_dim
            WHERE ss_sold_date_sk = d_date_sk
              AND d_month_seq BETWEEN 1200 AND 1200 + 11
            GROUP BY ss_store_sk, ss_item_sk) sa
      GROUP BY ss_store_sk) sb,
     (SELECT ss_store_sk, ss_item_sk, SUM(ss_sales_price) AS revenue
      FROM store_sales, date_dim
      WHERE ss_sold_date_sk = d_date_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11
      GROUP BY ss_store_sk, ss_item_sk) sc
WHERE sb.ss_store_sk = sc.ss_store_sk
  AND sc.revenue <= 0.1 * sb.ave
  AND s_store_sk = sc.ss_store_sk
  AND i_item_sk = sc.ss_item_sk
ORDER BY s_store_name, i_item_desc
LIMIT 100
"""

Q85 = """
SELECT substr(r_reason_desc, 1, 20) AS reason_desc,
       AVG(ws_quantity) AS avg_q,
       AVG(wr_refunded_cash) AS avg_cash,
       AVG(wr_fee) AS avg_fee
FROM web_sales, web_returns, web_page, customer_demographics cd1,
     customer_demographics cd2, customer_address, date_dim, reason
WHERE ws_web_page_sk = wp_web_page_sk
  AND ws_item_sk = wr_item_sk
  AND ws_order_number = wr_order_number
  AND ws_sold_date_sk = d_date_sk AND d_year = 2000
  AND cd1.cd_demo_sk = wr_refunded_cdemo_sk
  AND cd2.cd_demo_sk = wr_returning_cdemo_sk
  AND ca_address_sk = wr_refunded_addr_sk
  AND r_reason_sk = wr_reason_sk
  AND ((cd1.cd_marital_status = 'M'
        AND cd1.cd_marital_status = cd2.cd_marital_status
        AND cd1.cd_education_status = 'Advanced Degree'
        AND cd1.cd_education_status = cd2.cd_education_status
        AND ws_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd1.cd_marital_status = 'S'
           AND cd1.cd_marital_status = cd2.cd_marital_status
           AND cd1.cd_education_status = 'College'
           AND cd1.cd_education_status = cd2.cd_education_status
           AND ws_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd1.cd_marital_status = 'W'
           AND cd1.cd_marital_status = cd2.cd_marital_status
           AND cd1.cd_education_status = '2 yr Degree'
           AND cd1.cd_education_status = cd2.cd_education_status
           AND ws_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ca_country = 'United States'
        AND ca_state IN ('CA', 'TX', 'NY')
        AND ws_net_profit BETWEEN 100 AND 200)
       OR (ca_country = 'United States'
           AND ca_state IN ('WA', 'OR', 'TN')
           AND ws_net_profit BETWEEN 150 AND 300)
       OR (ca_country = 'United States'
           AND ca_state IN ('SD', 'GA', 'NM')
           AND ws_net_profit BETWEEN 50 AND 250))
GROUP BY r_reason_desc
ORDER BY substr(r_reason_desc, 1, 20), avg_q, avg_cash, avg_fee
LIMIT 100
"""

Q95 = """
WITH ws_wh AS (
  SELECT ws1.ws_order_number, ws1.ws_warehouse_sk AS wh1,
         ws2.ws_warehouse_sk AS wh2
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
)
SELECT COUNT(DISTINCT ws_order_number) AS order_count,
       SUM(ws_ext_ship_cost) AS total_shipping_cost,
       SUM(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN DATE '2000-02-01'
                 AND DATE '2000-02-01' + INTERVAL '60' DAY
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'CA'
  AND ws1.ws_web_site_sk = web_site_sk
  AND web_company_name = 'pri'
  AND ws1.ws_order_number IN (SELECT ws_order_number FROM ws_wh)
  AND ws1.ws_order_number IN (SELECT wr_order_number
                              FROM web_returns, ws_wh
                              WHERE wr_order_number = ws_wh.ws_order_number)
ORDER BY order_count
LIMIT 100
"""

Q97 = """
WITH ssci AS (
  SELECT ss_customer_sk AS customer_sk, ss_item_sk AS item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1200 + 11
  GROUP BY ss_customer_sk, ss_item_sk
), csci AS (
  SELECT cs_bill_customer_sk AS customer_sk, cs_item_sk AS item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1200 + 11
  GROUP BY cs_bill_customer_sk, cs_item_sk
)
SELECT SUM(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NULL THEN 1 ELSE 0 END)
           AS store_only,
       SUM(CASE WHEN ssci.customer_sk IS NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
           AS catalog_only,
       SUM(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
           AS store_and_catalog
FROM ssci
FULL OUTER JOIN csci
  ON ssci.customer_sk = csci.customer_sk AND ssci.item_sk = csci.item_sk
LIMIT 100
"""

Q39 = """
WITH inv AS (
  SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         CASE mean WHEN 0 THEN NULL ELSE stdev / mean END AS cov
  FROM (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               STDDEV(inv_quantity_on_hand) AS stdev,
               AVG(inv_quantity_on_hand) AS mean
        FROM inventory, item, warehouse, date_dim
        WHERE inv_item_sk = i_item_sk
          AND inv_warehouse_sk = w_warehouse_sk
          AND inv_date_sk = d_date_sk
          AND d_year = 2000
        GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  WHERE CASE mean WHEN 0 THEN 0 ELSE stdev / mean END > 1
)
SELECT inv1.w_warehouse_sk AS wsk1, inv1.i_item_sk AS isk1,
       inv1.d_moy AS moy1, inv1.mean AS mean1, inv1.cov AS cov1,
       inv2.w_warehouse_sk AS wsk2, inv2.i_item_sk AS isk2,
       inv2.d_moy AS moy2, inv2.mean AS mean2, inv2.cov AS cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
  AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
  AND inv1.d_moy = 1
  AND inv2.d_moy = 1 + 1
ORDER BY wsk1, isk1, moy1, mean1, cov1
LIMIT 100
"""

ALL.update({2: Q2, 16: Q16, 30: Q30, 32: Q32, 33: Q33, 38: Q38, 39: Q39,
            40: Q40, 56: Q56, 59: Q59, 60: Q60, 61: Q61, 65: Q65, 69: Q69,
            71: Q71, 76: Q76, 81: Q81, 85: Q85, 87: Q87, 92: Q92, 94: Q94,
            95: Q95, 97: Q97})

from .queries_remaining import REST  # noqa: E402  (the final 29 → 99/99)
ALL.update(REST)
