"""Synthetic TPC-DS-shaped data covering all three sales channels.

The reference ships full dsdgen + 99 queries (``benchmarking/tpcds``).
This generator produces the store channel (store_sales with
ticket-coherent baskets, store_returns), the catalog channel
(catalog_sales/catalog_returns with order-coherent lines, call_center,
catalog_page, warehouse, ship_mode), the web channel
(web_sales/web_returns, web_site, web_page), weekly inventory, and the
shared dimensions (item, date_dim, time_dim, store, customer,
customer_address, customer_demographics, household_demographics,
income_band, promotion, reason) — TPC-DS column names and realistic key
relationships, vectorized numpy like the TPC-H datagen. Line counts
follow the spec's rough channel ratios (store : catalog : web ≈
1 : 0.5 : 0.25).
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def generate_tpcds(root: str, scale: float = 0.01, seed: int = 0) -> None:
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(seed)

    n_items = max(int(1000 * scale), 50)
    n_stores = max(int(20 * scale), 4)
    n_sales = max(int(500_000 * scale), 5000)

    # date_dim: 3 years of days
    import datetime as _dt
    n_days = 3 * 365
    d_date_sk = np.arange(1, n_days + 1)
    years = 1999 + (np.arange(n_days) // 365)
    moy = ((np.arange(n_days) % 365) // 31) + 1
    moy_clip = np.minimum(moy, 12)
    base_date = _dt.date(1999, 1, 1)
    dates = [base_date + _dt.timedelta(days=int(i)) for i in range(n_days)]
    day_names = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
                 "Saturday", "Sunday"]
    dow = np.array([d.weekday() for d in dates])
    qoy = (moy_clip - 1) // 3 + 1
    date_dim = pa.table({
        "d_date_sk": d_date_sk,
        "d_date_id": ["D%08d" % i for i in range(n_days)],
        "d_date": pa.array(dates, pa.date32()),
        "d_year": years,
        "d_moy": moy_clip,
        "d_qoy": qoy,
        "d_dom": (np.arange(n_days) % 31) + 1,
        "d_dow": dow,
        "d_day_name": [day_names[d.weekday()] for d in dates],
        "d_week_seq": np.arange(n_days) // 7 + 1,
        "d_month_seq": (years - 1999) * 12 + moy_clip - 1 + 1200,
        "d_quarter_name": ["%dQ%d" % (y, q) for y, q in zip(years, qoy)],
        "d_weekend": np.where(dow >= 5, "Y", "N"),
        "d_holiday": np.where((np.arange(n_days) % 97) == 0, "Y", "N"),
        "d_following_holiday": np.where(
            (np.arange(n_days) % 97) == 1, "Y", "N"),
        "d_first_dom": d_date_sk - ((np.arange(n_days) % 31 + 1) - 1),
    })

    categories = ["Books", "Home", "Electronics", "Music", "Sports",
                  "Children", "Women", "Men", "Jewelry", "Shoes"]
    classes = ["computers", "stereo", "football", "shirts", "birdal",
               "dresses", "personal", "portable", "reference", "self-help",
               "accessories", "classical", "fragrances", "pants"]
    brands = ["brand%03d" % i for i in range(50)]
    cat = rng.choice(len(categories), n_items)
    cls = rng.choice(len(classes), n_items)
    brd = rng.choice(len(brands), n_items)
    item = pa.table({
        "i_item_sk": np.arange(1, n_items + 1),
        "i_item_id": ["AAAA%08d" % i for i in range(n_items)],
        "i_item_desc": ["item description %d" % i for i in range(n_items)],
        "i_current_price": rng.uniform(0.5, 100, n_items).round(2),
        "i_category": np.array(categories)[cat],
        "i_category_id": cat + 1,
        "i_class": np.array(classes)[cls],
        "i_class_id": cls + 1,
        "i_brand": np.array(brands)[brd],
        "i_brand_id": brd + 1,
        "i_manager_id": rng.integers(1, 100, n_items),
        "i_manufact_id": rng.integers(1, 200, n_items),
        "i_manufact": ["manu%03d" % m for m in rng.integers(0, 60, n_items)],
        "i_product_name": ["product%05d" % i for i in range(n_items)],
        "i_color": rng.choice(["powder", "orchid", "slate", "peach",
                               "smoke", "sienna", "navy", "aquamarine"],
                              n_items),
        "i_size": rng.choice(["small", "medium", "large", "petite",
                              "extra large", "N/A"], n_items),
        "i_units": rng.choice(["Oz", "Bunch", "Ton", "Each", "Case"],
                              n_items),
        "i_wholesale_cost": rng.uniform(0.5, 80, n_items).round(2),
    })

    store = pa.table({
        "s_store_sk": np.arange(1, n_stores + 1),
        "s_store_name": ["ese" if i == 0 else "store%d" % i
                         for i in range(n_stores)],
        "s_company_name": ["company%d" % (i % 3) for i in range(n_stores)],
        "s_city": rng.choice(["rivertown", "lakeside", "hilltop"], n_stores),
        "s_county": rng.choice(["Ziebach County", "Williamson County"],
                               n_stores),
        "s_state": rng.choice(["TN", "SD", "CA"], n_stores),
        "s_gmt_offset": rng.choice([-5.0, -6.0, -8.0], n_stores),
        "s_number_employees": rng.integers(200, 300, n_stores),
        "s_store_id": ["S%08d" % i for i in range(n_stores)],
        "s_zip": ["%05d" % z for z in rng.integers(10000, 99999, n_stores)],
        "s_market_id": rng.integers(1, 11, n_stores),
        "s_floor_space": rng.integers(5000000, 10000000, n_stores),
        "s_company_id": rng.integers(1, 4, n_stores),
        "s_street_number": ["%d" % n for n in
                            rng.integers(1, 1000, n_stores)],
        "s_street_name": rng.choice(["Main", "Oak", "Elm", "First",
                                     "Park"], n_stores),
        "s_street_type": rng.choice(["St", "Ave", "Blvd"], n_stores),
    })

    n_custs = max(int(2000 * scale), 100)
    n_cd = 200  # demographic combinations
    n_hd = 100
    customer = pa.table({
        "c_customer_sk": np.arange(1, n_custs + 1),
        "c_customer_id": ["CUST%08d" % i for i in range(n_custs)],
        "c_current_cdemo_sk": rng.integers(1, n_cd + 1, n_custs),
        "c_current_hdemo_sk": rng.integers(1, n_hd + 1, n_custs),
        "c_current_addr_sk": np.arange(1, n_custs + 1),
        "c_salutation": rng.choice(["Mr.", "Mrs.", "Ms.", "Dr."], n_custs),
        "c_first_name": ["first%d" % i for i in range(n_custs)],
        "c_last_name": ["last%d" % i for i in range(n_custs)],
        "c_birth_year": rng.integers(1930, 2005, n_custs),
        "c_birth_month": rng.integers(1, 13, n_custs),
        "c_birth_day": rng.integers(1, 29, n_custs),
        "c_birth_country": rng.choice(
            ["UNITED STATES", "CANADA", "MEXICO", "GERMANY", "JAPAN"],
            n_custs),
        "c_email_address": ["c%d@example.org" % i for i in range(n_custs)],
        "c_login": ["login%d" % i for i in range(n_custs)],
        "c_preferred_cust_flag": rng.choice(["Y", "N"], n_custs),
        "c_first_sales_date_sk": rng.integers(1, n_days + 1, n_custs),
        "c_first_shipto_date_sk": rng.integers(1, n_days + 1, n_custs),
        "c_last_review_date_sk": rng.integers(1, n_days + 1, n_custs),
    })
    customer_address = pa.table({
        "ca_address_sk": np.arange(1, n_custs + 1),
        "ca_address_id": ["ADDR%08d" % i for i in range(n_custs)],
        "ca_street_number": ["%d" % n for n in
                             rng.integers(1, 1000, n_custs)],
        "ca_street_name": rng.choice(["Main", "Oak", "Elm", "First",
                                      "Park"], n_custs),
        "ca_street_type": rng.choice(["St", "Ave", "Blvd", "Way"], n_custs),
        "ca_suite_number": ["Suite %d" % n for n in
                            rng.integers(1, 500, n_custs)],
        "ca_city": rng.choice(["rivertown", "lakeside", "hilltop",
                               "meadow", "brookfield"], n_custs),
        "ca_county": rng.choice(["Ziebach County", "Williamson County",
                                 "Walker County"], n_custs),
        "ca_state": rng.choice(["CA", "NY", "TX", "WA", "OR", "TN", "SD",
                                "GA", "KY", "NM"], n_custs),
        "ca_zip": ["%05d" % z for z in rng.integers(10000, 99999, n_custs)],
        "ca_country": ["United States"] * n_custs,
        "ca_gmt_offset": rng.choice([-5.0, -6.0, -7.0, -8.0], n_custs),
        "ca_location_type": rng.choice(["apartment", "condo",
                                        "single family"], n_custs),
    })
    customer_demographics = pa.table({
        "cd_demo_sk": np.arange(1, n_cd + 1),
        "cd_gender": rng.choice(["M", "F"], n_cd),
        "cd_marital_status": rng.choice(["S", "M", "D", "W", "U"], n_cd),
        "cd_education_status": rng.choice(
            ["Primary", "Secondary", "College", "Advanced Degree",
             "2 yr Degree", "4 yr Degree", "Unknown"], n_cd),
        "cd_purchase_estimate": rng.integers(1, 11, n_cd) * 500,
        "cd_credit_rating": rng.choice(["Low Risk", "Good", "High Risk",
                                        "Unknown"], n_cd),
        "cd_dep_count": rng.integers(0, 7, n_cd),
        "cd_dep_employed_count": rng.integers(0, 7, n_cd),
        "cd_dep_college_count": rng.integers(0, 7, n_cd),
    })
    n_promos = 30
    promotion = pa.table({
        "p_promo_sk": np.arange(1, n_promos + 1),
        "p_promo_id": ["PROMO%06d" % i for i in range(n_promos)],
        "p_promo_name": ["promo%d" % i for i in range(n_promos)],
        "p_cost": rng.uniform(500, 2000, n_promos).round(2),
        "p_channel_email": rng.choice(["Y", "N"], n_promos),
        "p_channel_event": rng.choice(["Y", "N"], n_promos),
        "p_channel_dmail": rng.choice(["Y", "N"], n_promos),
        "p_channel_tv": rng.choice(["Y", "N"], n_promos),
        "p_channel_catalog": rng.choice(["Y", "N"], n_promos),
        "p_channel_internet": rng.choice(["Y", "N"], n_promos),
        "p_discount_active": rng.choice(["Y", "N"], n_promos),
    })
    n_reasons = 10
    reason = pa.table({
        "r_reason_sk": np.arange(1, n_reasons + 1),
        "r_reason_desc": ["reason %d" % i for i in range(n_reasons)],
    })

    n_ib = 20
    income_band = pa.table({
        "ib_income_band_sk": np.arange(1, n_ib + 1),
        "ib_lower_bound": np.arange(n_ib) * 10000,
        "ib_upper_bound": (np.arange(n_ib) + 1) * 10000,
    })
    household_demographics = pa.table({
        "hd_demo_sk": np.arange(1, n_hd + 1),
        "hd_income_band_sk": rng.integers(1, n_ib + 1, n_hd),
        "hd_dep_count": rng.integers(0, 10, n_hd),
        "hd_vehicle_count": rng.integers(0, 5, n_hd),
        "hd_buy_potential": rng.choice(
            [">10000", "5001-10000", "1001-5000", "501-1000", "0-500",
             "Unknown"], n_hd),
    })
    n_times = 24 * 60  # one row per minute of day
    t_hour = np.arange(n_times) // 60
    time_dim = pa.table({
        "t_time_sk": np.arange(1, n_times + 1),
        "t_time_id": ["T%08d" % i for i in range(n_times)],
        "t_time": np.arange(n_times) * 60,
        "t_hour": t_hour,
        "t_minute": np.arange(n_times) % 60,
        "t_am_pm": np.where(t_hour < 12, "AM", "PM"),
        "t_shift": np.where(t_hour < 8, "third",
                            np.where(t_hour < 16, "first", "second")),
        "t_meal_time": np.where((t_hour >= 6) & (t_hour <= 8), "breakfast",
                                np.where((t_hour >= 11) & (t_hour <= 13),
                                         "lunch",
                                         np.where((t_hour >= 17)
                                                  & (t_hour <= 20),
                                                  "dinner", ""))),
    })

    # tickets are coherent baskets: every line item of a ticket shares its
    # date/time/store/customer/demographics (like real receipts — the
    # Q34/Q73 per-ticket line counts depend on this); ~12 lines per ticket
    n_tickets = max(n_sales // 12, 1)
    ticket = rng.integers(1, n_tickets + 1, n_sales)
    t_date = rng.integers(1, n_days + 1, n_tickets + 1)
    t_time = rng.integers(1, n_times + 1, n_tickets + 1)
    t_store = rng.integers(1, n_stores + 1, n_tickets + 1)
    t_cust = rng.integers(1, n_custs + 1, n_tickets + 1)
    t_cd = rng.integers(1, n_cd + 1, n_tickets + 1)
    t_hd = rng.integers(1, n_hd + 1, n_tickets + 1)
    # delivery address is NOT always the customer's own (Q46/Q68 compare
    # bought city vs current city)
    t_addr = rng.integers(1, n_custs + 1, n_tickets + 1)
    # zipf-skewed item popularity: real catalogs have hits and long
    # tails (Q65 hunts store-item pairs far below the store average)
    ss_item = (rng.zipf(1.3, n_sales) - 1) % n_items + 1
    store_sales = pa.table({
        "ss_sold_date_sk": t_date[ticket],
        "ss_sold_time_sk": t_time[ticket],
        "ss_item_sk": ss_item,
        "ss_store_sk": t_store[ticket],
        "ss_customer_sk": t_cust[ticket],
        "ss_cdemo_sk": t_cd[ticket],
        "ss_hdemo_sk": t_hd[ticket],
        "ss_addr_sk": t_addr[ticket],
        "ss_promo_sk": rng.integers(1, n_promos + 1, n_sales),
        "ss_ticket_number": ticket,
        "ss_sales_price": rng.uniform(1, 300, n_sales).round(2),
        "ss_quantity": rng.integers(1, 100, n_sales),
        "ss_list_price": rng.uniform(1, 300, n_sales).round(2),
        "ss_coupon_amt": rng.uniform(0, 50, n_sales).round(2),
        "ss_ext_sales_price": rng.uniform(1, 3000, n_sales).round(2),
        "ss_ext_list_price": rng.uniform(1, 3000, n_sales).round(2),
        "ss_ext_discount_amt": rng.uniform(0, 300, n_sales).round(2),
        "ss_ext_wholesale_cost": rng.uniform(1, 1500, n_sales).round(2),
        "ss_wholesale_cost": rng.uniform(1, 100, n_sales).round(2),
        "ss_ext_tax": rng.uniform(0, 200, n_sales).round(2),
        "ss_net_paid": rng.uniform(1, 2500, n_sales).round(2),
        "ss_net_profit": rng.uniform(-500, 1500, n_sales).round(2),
    })

    # ----------------------------------------------------------- catalog
    # order-coherent lines like store tickets; ~half the store volume
    n_wh = max(int(5 * scale), 2)
    warehouse = pa.table({
        "w_warehouse_sk": np.arange(1, n_wh + 1),
        "w_warehouse_name": ["warehouse%d" % i for i in range(n_wh)],
        "w_warehouse_sq_ft": rng.integers(50000, 1000000, n_wh),
        "w_city": rng.choice(["rivertown", "lakeside", "hilltop"], n_wh),
        "w_county": rng.choice(["Ziebach County", "Williamson County"],
                               n_wh),
        "w_state": rng.choice(["TN", "SD", "CA"], n_wh),
        "w_country": ["United States"] * n_wh,
        "w_gmt_offset": rng.choice([-5.0, -6.0, -8.0], n_wh),
    })
    n_cc = 4
    call_center = pa.table({
        "cc_call_center_sk": np.arange(1, n_cc + 1),
        "cc_call_center_id": ["CC%06d" % i for i in range(n_cc)],
        "cc_name": ["call center %d" % i for i in range(n_cc)],
        "cc_county": rng.choice(["Ziebach County", "Williamson County"],
                                n_cc),
        "cc_manager": ["manager%d" % i for i in range(n_cc)],
    })
    n_cp = 50
    catalog_page = pa.table({
        "cp_catalog_page_sk": np.arange(1, n_cp + 1),
        "cp_catalog_page_id": ["CP%08d" % i for i in range(n_cp)],
        "cp_catalog_number": rng.integers(1, 10, n_cp),
        "cp_catalog_page_number": rng.integers(1, 100, n_cp),
    })
    n_sm = 10
    ship_mode = pa.table({
        "sm_ship_mode_sk": np.arange(1, n_sm + 1),
        "sm_ship_mode_id": ["SM%06d" % i for i in range(n_sm)],
        "sm_type": rng.choice(["EXPRESS", "NEXT DAY", "OVERNIGHT",
                               "REGULAR", "TWO DAY", "LIBRARY"], n_sm),
        "sm_code": rng.choice(["AIR", "SURFACE", "SEA"], n_sm),
        "sm_carrier": rng.choice(["UPS", "FEDEX", "DHL", "USPS",
                                  "LATVIAN", "ZOUROS"], n_sm),
    })

    def _channel_sales(n_lines: int, lines_per_order: int):
        """(order ids, per-order date/time/customer/addr/demo planes)."""
        n_orders = max(n_lines // lines_per_order, 1)
        order = rng.integers(1, n_orders + 1, n_lines)
        return order, {
            "date": rng.integers(1, n_days + 1, n_orders + 1),
            "time": rng.integers(1, n_times + 1, n_orders + 1),
            "cust": rng.integers(1, n_custs + 1, n_orders + 1),
            "addr": rng.integers(1, n_custs + 1, n_orders + 1),
            "cd": rng.integers(1, n_cd + 1, n_orders + 1),
            "hd": rng.integers(1, n_hd + 1, n_orders + 1),
            "ship_cust": rng.integers(1, n_custs + 1, n_orders + 1),
            "ship_addr": rng.integers(1, n_custs + 1, n_orders + 1),
        }

    n_cs = max(n_sales // 2, 2500)
    cs_order, cso = _channel_sales(n_cs, 10)
    cs_item = (rng.zipf(1.3, n_cs) - 1) % n_items + 1
    cs_price = rng.uniform(1, 300, n_cs).round(2)
    cs_qty = rng.integers(1, 100, n_cs)
    catalog_sales = pa.table({
        "cs_sold_date_sk": cso["date"][cs_order],
        "cs_sold_time_sk": cso["time"][cs_order],
        "cs_ship_date_sk": np.minimum(
            cso["date"][cs_order] + rng.integers(1, 30, n_cs), n_days),
        "cs_bill_customer_sk": cso["cust"][cs_order],
        "cs_bill_cdemo_sk": cso["cd"][cs_order],
        "cs_bill_hdemo_sk": cso["hd"][cs_order],
        "cs_bill_addr_sk": cso["addr"][cs_order],
        "cs_ship_customer_sk": cso["ship_cust"][cs_order],
        "cs_ship_addr_sk": cso["ship_addr"][cs_order],
        "cs_ship_mode_sk": rng.integers(1, n_sm + 1, n_cs),
        "cs_call_center_sk": rng.integers(1, n_cc + 1, n_cs),
        "cs_catalog_page_sk": rng.integers(1, n_cp + 1, n_cs),
        "cs_warehouse_sk": rng.integers(1, n_wh + 1, n_cs),
        "cs_item_sk": cs_item,
        "cs_promo_sk": rng.integers(1, n_promos + 1, n_cs),
        "cs_order_number": cs_order,
        "cs_quantity": cs_qty,
        "cs_wholesale_cost": rng.uniform(1, 100, n_cs).round(2),
        "cs_list_price": rng.uniform(1, 300, n_cs).round(2),
        "cs_sales_price": cs_price,
        "cs_ext_discount_amt": rng.uniform(0, 300, n_cs).round(2),
        "cs_ext_sales_price": (cs_price * cs_qty).round(2),
        "cs_ext_wholesale_cost": rng.uniform(1, 1500, n_cs).round(2),
        "cs_ext_list_price": rng.uniform(1, 3000, n_cs).round(2),
        "cs_ext_tax": rng.uniform(0, 200, n_cs).round(2),
        "cs_coupon_amt": rng.uniform(0, 50, n_cs).round(2),
        "cs_ext_ship_cost": rng.uniform(0, 150, n_cs).round(2),
        "cs_net_paid": rng.uniform(1, 2500, n_cs).round(2),
        "cs_net_paid_inc_tax": rng.uniform(1, 2700, n_cs).round(2),
        "cs_net_paid_inc_ship": rng.uniform(1, 2600, n_cs).round(2),
        "cs_net_paid_inc_ship_tax": rng.uniform(1, 2800, n_cs).round(2),
        "cs_net_profit": rng.uniform(-500, 1500, n_cs).round(2),
    })
    cr_idx = rng.choice(n_cs, max(n_cs // 12, 6), replace=False)
    cr_pair = cs_item[cr_idx].astype(np.int64) * (n_cs + 2) \
        + cs_order[cr_idx]
    _, cr_first = np.unique(cr_pair, return_index=True)
    cr_idx = cr_idx[np.sort(cr_first)]
    n_cr = len(cr_idx)
    catalog_returns = pa.table({
        "cr_returned_date_sk": np.minimum(
            cso["date"][cs_order[cr_idx]] + rng.integers(1, 60, n_cr),
            n_days),
        "cr_returned_time_sk": rng.integers(1, n_times + 1, n_cr),
        "cr_item_sk": cs_item[cr_idx],
        "cr_refunded_customer_sk": cso["cust"][cs_order[cr_idx]],
        "cr_refunded_addr_sk": cso["addr"][cs_order[cr_idx]],
        "cr_refunded_cdemo_sk": cso["cd"][cs_order[cr_idx]],
        "cr_refunded_hdemo_sk": cso["hd"][cs_order[cr_idx]],
        "cr_returning_customer_sk": cso["cust"][cs_order[cr_idx]],
        "cr_returning_addr_sk": cso["addr"][cs_order[cr_idx]],
        "cr_call_center_sk": rng.integers(1, n_cc + 1, n_cr),
        "cr_catalog_page_sk": rng.integers(1, n_cp + 1, n_cr),
        "cr_ship_mode_sk": rng.integers(1, n_sm + 1, n_cr),
        "cr_warehouse_sk": rng.integers(1, n_wh + 1, n_cr),
        "cr_reason_sk": rng.integers(1, n_reasons + 1, n_cr),
        "cr_order_number": cs_order[cr_idx],
        "cr_return_quantity": rng.integers(1, 20, n_cr),
        "cr_return_amount": rng.uniform(1, 300, n_cr).round(2),
        "cr_return_amt_inc_tax": rng.uniform(1, 330, n_cr).round(2),
        "cr_fee": rng.uniform(0, 100, n_cr).round(2),
        "cr_return_ship_cost": rng.uniform(0, 120, n_cr).round(2),
        "cr_refunded_cash": rng.uniform(0, 250, n_cr).round(2),
        "cr_reversed_charge": rng.uniform(0, 120, n_cr).round(2),
        "cr_store_credit": rng.uniform(0, 120, n_cr).round(2),
        "cr_net_loss": rng.uniform(1, 400, n_cr).round(2),
    })

    # --------------------------------------------------------------- web
    n_web_sites = 6
    web_site = pa.table({
        "web_site_sk": np.arange(1, n_web_sites + 1),
        "web_site_id": ["WEB%06d" % i for i in range(n_web_sites)],
        "web_name": ["site_%d" % i for i in range(n_web_sites)],
        "web_company_name": ["pri" if i == 0 else "company%d" % (i % 3)
                             for i in range(n_web_sites)],
    })
    n_wp = 60
    web_page = pa.table({
        "wp_web_page_sk": np.arange(1, n_wp + 1),
        "wp_web_page_id": ["WP%08d" % i for i in range(n_wp)],
        "wp_char_count": rng.integers(100, 8000, n_wp),
        "wp_type": rng.choice(["ad", "dynamic", "feedback", "general",
                               "order", "protected", "welcome"], n_wp),
    })
    n_ws = max(n_sales // 4, 1250)
    ws_order, wso = _channel_sales(n_ws, 8)
    ws_item = (rng.zipf(1.3, n_ws) - 1) % n_items + 1
    ws_price = rng.uniform(1, 300, n_ws).round(2)
    ws_qty = rng.integers(1, 100, n_ws)
    web_sales = pa.table({
        "ws_sold_date_sk": wso["date"][ws_order],
        "ws_sold_time_sk": wso["time"][ws_order],
        "ws_ship_date_sk": np.minimum(
            wso["date"][ws_order] + rng.integers(1, 30, n_ws), n_days),
        "ws_item_sk": ws_item,
        "ws_bill_customer_sk": wso["cust"][ws_order],
        "ws_bill_cdemo_sk": wso["cd"][ws_order],
        "ws_bill_hdemo_sk": wso["hd"][ws_order],
        "ws_bill_addr_sk": wso["addr"][ws_order],
        "ws_ship_customer_sk": wso["ship_cust"][ws_order],
        "ws_ship_cdemo_sk": wso["cd"][ws_order],
        "ws_ship_hdemo_sk": wso["hd"][ws_order],
        "ws_ship_addr_sk": wso["ship_addr"][ws_order],
        "ws_web_page_sk": rng.integers(1, n_wp + 1, n_ws),
        "ws_web_site_sk": rng.integers(1, n_web_sites + 1, n_ws),
        "ws_ship_mode_sk": rng.integers(1, n_sm + 1, n_ws),
        "ws_warehouse_sk": rng.integers(1, n_wh + 1, n_ws),
        "ws_promo_sk": rng.integers(1, n_promos + 1, n_ws),
        "ws_order_number": ws_order,
        "ws_quantity": ws_qty,
        "ws_wholesale_cost": rng.uniform(1, 100, n_ws).round(2),
        "ws_list_price": rng.uniform(1, 300, n_ws).round(2),
        "ws_sales_price": ws_price,
        "ws_ext_discount_amt": rng.uniform(0, 300, n_ws).round(2),
        "ws_ext_sales_price": (ws_price * ws_qty).round(2),
        "ws_ext_wholesale_cost": rng.uniform(1, 1500, n_ws).round(2),
        "ws_ext_list_price": rng.uniform(1, 3000, n_ws).round(2),
        "ws_ext_tax": rng.uniform(0, 200, n_ws).round(2),
        "ws_coupon_amt": rng.uniform(0, 50, n_ws).round(2),
        "ws_ext_ship_cost": rng.uniform(0, 150, n_ws).round(2),
        "ws_net_paid": rng.uniform(1, 2500, n_ws).round(2),
        "ws_net_paid_inc_tax": rng.uniform(1, 2700, n_ws).round(2),
        "ws_net_paid_inc_ship": rng.uniform(1, 2600, n_ws).round(2),
        "ws_net_paid_inc_ship_tax": rng.uniform(1, 2800, n_ws).round(2),
        "ws_net_profit": rng.uniform(-500, 1500, n_ws).round(2),
    })
    wr_idx = rng.choice(n_ws, max(n_ws // 12, 5), replace=False)
    wr_pair = ws_item[wr_idx].astype(np.int64) * (n_ws + 2) \
        + ws_order[wr_idx]
    _, wr_first = np.unique(wr_pair, return_index=True)
    wr_idx = wr_idx[np.sort(wr_first)]
    n_wr = len(wr_idx)
    web_returns = pa.table({
        "wr_returned_date_sk": np.minimum(
            wso["date"][ws_order[wr_idx]] + rng.integers(1, 60, n_wr),
            n_days),
        "wr_returned_time_sk": rng.integers(1, n_times + 1, n_wr),
        "wr_item_sk": ws_item[wr_idx],
        "wr_refunded_customer_sk": wso["cust"][ws_order[wr_idx]],
        "wr_refunded_addr_sk": wso["addr"][ws_order[wr_idx]],
        "wr_refunded_cdemo_sk": wso["cd"][ws_order[wr_idx]],
        "wr_refunded_hdemo_sk": wso["hd"][ws_order[wr_idx]],
        "wr_returning_cdemo_sk": wso["cd"][ws_order[wr_idx]],
        "wr_returning_customer_sk": wso["cust"][ws_order[wr_idx]],
        "wr_returning_addr_sk": wso["addr"][ws_order[wr_idx]],
        "wr_web_page_sk": rng.integers(1, n_wp + 1, n_wr),
        "wr_reason_sk": rng.integers(1, n_reasons + 1, n_wr),
        "wr_order_number": ws_order[wr_idx],
        "wr_return_quantity": rng.integers(1, 20, n_wr),
        "wr_return_amt": rng.uniform(1, 300, n_wr).round(2),
        "wr_return_amt_inc_tax": rng.uniform(1, 330, n_wr).round(2),
        "wr_fee": rng.uniform(0, 100, n_wr).round(2),
        "wr_return_ship_cost": rng.uniform(0, 120, n_wr).round(2),
        "wr_refunded_cash": rng.uniform(0, 250, n_wr).round(2),
        "wr_account_credit": rng.uniform(0, 120, n_wr).round(2),
        "wr_net_loss": rng.uniform(1, 400, n_wr).round(2),
    })

    # --------------------------------------------------------- inventory
    # weekly snapshots: one row per (week-start date, item, warehouse)
    week_starts = d_date_sk[::7]
    ii, ww, dd = np.meshgrid(np.arange(1, n_items + 1),
                             np.arange(1, n_wh + 1),
                             week_starts, indexing="ij")
    inventory = pa.table({
        "inv_date_sk": dd.ravel(),
        "inv_item_sk": ii.ravel(),
        "inv_warehouse_sk": ww.ravel(),
        "inv_quantity_on_hand": rng.integers(0, 1000, dd.size),
    })

    # store_returns: ~8% of sale lines come back, days after the sale.
    # (sr_item_sk, sr_ticket_number) is the spec's PK — dedupe candidate
    # lines on that pair (tickets often hold several lines of one item)
    cand = rng.choice(n_sales, max(n_sales // 10, 12), replace=False)
    pair = ss_item[cand].astype(np.int64) * (n_tickets + 2) + ticket[cand]
    _, first = np.unique(pair, return_index=True)
    ret_idx = cand[np.sort(first)]
    n_ret = len(ret_idx)
    store_returns = pa.table({
        "sr_returned_date_sk": np.minimum(
            t_date[ticket[ret_idx]] + rng.integers(1, 60, n_ret), n_days),
        "sr_item_sk": ss_item[ret_idx],
        "sr_customer_sk": t_cust[ticket[ret_idx]],
        "sr_cdemo_sk": t_cd[ticket[ret_idx]],
        "sr_hdemo_sk": t_hd[ticket[ret_idx]],
        "sr_store_sk": t_store[ticket[ret_idx]],
        "sr_ticket_number": ticket[ret_idx],
        "sr_reason_sk": rng.integers(1, n_reasons + 1, n_ret),
        "sr_return_quantity": rng.integers(1, 20, n_ret),
        "sr_return_amt": rng.uniform(1, 300, n_ret).round(2),
        "sr_return_amt_inc_tax": rng.uniform(1, 330, n_ret).round(2),
        "sr_return_tax": rng.uniform(0, 30, n_ret).round(2),
        "sr_fee": rng.uniform(0, 100, n_ret).round(2),
        "sr_return_ship_cost": rng.uniform(0, 120, n_ret).round(2),
        "sr_refunded_cash": rng.uniform(0, 250, n_ret).round(2),
        "sr_reversed_charge": rng.uniform(0, 120, n_ret).round(2),
        "sr_store_credit": rng.uniform(0, 120, n_ret).round(2),
        "sr_net_loss": rng.uniform(1, 400, n_ret).round(2),
        "sr_addr_sk": t_addr[ticket[ret_idx]],
        "sr_return_time_sk": rng.integers(1, n_times + 1, n_ret),
    })

    for name, t in (("date_dim", date_dim), ("item", item),
                    ("store", store), ("store_sales", store_sales),
                    ("customer", customer),
                    ("customer_address", customer_address),
                    ("customer_demographics", customer_demographics),
                    ("promotion", promotion),
                    ("household_demographics", household_demographics),
                    ("income_band", income_band),
                    ("time_dim", time_dim), ("reason", reason),
                    ("store_returns", store_returns),
                    ("warehouse", warehouse),
                    ("call_center", call_center),
                    ("catalog_page", catalog_page),
                    ("ship_mode", ship_mode),
                    ("catalog_sales", catalog_sales),
                    ("catalog_returns", catalog_returns),
                    ("web_site", web_site), ("web_page", web_page),
                    ("web_sales", web_sales),
                    ("web_returns", web_returns),
                    ("inventory", inventory)):
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        pq.write_table(t, os.path.join(d, "part-0.parquet"))
