"""Synthetic TPC-DS-shaped data for the window-function query subset.

The reference ships full dsdgen + 99 queries (``benchmarking/tpcds``).
This generator produces the ten tables the query subset touches —
store_sales (ticket-coherent baskets), item, date_dim, time_dim, store,
customer, customer_address, customer_demographics, household_demographics,
promotion — with the TPC-DS column names and realistic key relationships,
vectorized numpy like the TPC-H datagen.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def generate_tpcds(root: str, scale: float = 0.01, seed: int = 0) -> None:
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(seed)

    n_items = max(int(1000 * scale), 50)
    n_stores = max(int(20 * scale), 4)
    n_sales = max(int(500_000 * scale), 5000)

    # date_dim: 3 years of days
    import datetime as _dt
    n_days = 3 * 365
    d_date_sk = np.arange(1, n_days + 1)
    years = 1999 + (np.arange(n_days) // 365)
    moy = ((np.arange(n_days) % 365) // 31) + 1
    moy_clip = np.minimum(moy, 12)
    base_date = _dt.date(1999, 1, 1)
    dates = [base_date + _dt.timedelta(days=int(i)) for i in range(n_days)]
    day_names = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
                 "Saturday", "Sunday"]
    date_dim = pa.table({
        "d_date_sk": d_date_sk,
        "d_date": pa.array(dates, pa.date32()),
        "d_year": years,
        "d_moy": moy_clip,
        "d_qoy": (moy_clip - 1) // 3 + 1,
        "d_dom": (np.arange(n_days) % 31) + 1,
        "d_dow": np.array([d.weekday() for d in dates]),
        "d_day_name": [day_names[d.weekday()] for d in dates],
        "d_week_seq": np.arange(n_days) // 7 + 1,
        "d_month_seq": (years - 1999) * 12 + moy_clip - 1 + 1200,
    })

    categories = ["Books", "Home", "Electronics", "Music", "Sports",
                  "Children", "Women", "Men", "Jewelry", "Shoes"]
    classes = ["computers", "stereo", "football", "shirts", "birdal",
               "dresses", "personal", "portable", "reference", "self-help",
               "accessories", "classical", "fragrances", "pants"]
    brands = ["brand%03d" % i for i in range(50)]
    cat = rng.choice(len(categories), n_items)
    cls = rng.choice(len(classes), n_items)
    brd = rng.choice(len(brands), n_items)
    item = pa.table({
        "i_item_sk": np.arange(1, n_items + 1),
        "i_item_id": ["AAAA%08d" % i for i in range(n_items)],
        "i_item_desc": ["item description %d" % i for i in range(n_items)],
        "i_current_price": rng.uniform(0.5, 100, n_items).round(2),
        "i_category": np.array(categories)[cat],
        "i_category_id": cat + 1,
        "i_class": np.array(classes)[cls],
        "i_class_id": cls + 1,
        "i_brand": np.array(brands)[brd],
        "i_brand_id": brd + 1,
        "i_manager_id": rng.integers(1, 100, n_items),
        "i_manufact_id": rng.integers(1, 200, n_items),
        "i_manufact": ["manu%03d" % m for m in rng.integers(0, 60, n_items)],
        "i_product_name": ["product%05d" % i for i in range(n_items)],
        "i_color": rng.choice(["powder", "orchid", "slate", "peach",
                               "smoke", "sienna", "navy", "aquamarine"],
                              n_items),
        "i_size": rng.choice(["small", "medium", "large", "petite",
                              "extra large", "N/A"], n_items),
        "i_units": rng.choice(["Oz", "Bunch", "Ton", "Each", "Case"],
                              n_items),
        "i_wholesale_cost": rng.uniform(0.5, 80, n_items).round(2),
    })

    store = pa.table({
        "s_store_sk": np.arange(1, n_stores + 1),
        "s_store_name": ["ese" if i == 0 else "store%d" % i
                         for i in range(n_stores)],
        "s_company_name": ["company%d" % (i % 3) for i in range(n_stores)],
        "s_city": rng.choice(["rivertown", "lakeside", "hilltop"], n_stores),
        "s_county": rng.choice(["Ziebach County", "Williamson County"],
                               n_stores),
        "s_state": rng.choice(["TN", "SD", "CA"], n_stores),
        "s_gmt_offset": rng.choice([-5.0, -6.0, -8.0], n_stores),
        "s_number_employees": rng.integers(200, 300, n_stores),
        "s_store_id": ["S%08d" % i for i in range(n_stores)],
        "s_zip": ["%05d" % z for z in rng.integers(10000, 99999, n_stores)],
    })

    n_custs = max(int(2000 * scale), 100)
    n_cd = 200  # demographic combinations
    customer = pa.table({
        "c_customer_sk": np.arange(1, n_custs + 1),
        "c_customer_id": ["CUST%08d" % i for i in range(n_custs)],
        "c_current_cdemo_sk": rng.integers(1, n_cd + 1, n_custs),
        "c_current_addr_sk": np.arange(1, n_custs + 1),
        "c_first_name": ["first%d" % i for i in range(n_custs)],
        "c_last_name": ["last%d" % i for i in range(n_custs)],
        "c_birth_year": rng.integers(1930, 2005, n_custs),
        "c_preferred_cust_flag": rng.choice(["Y", "N"], n_custs),
    })
    customer_address = pa.table({
        "ca_address_sk": np.arange(1, n_custs + 1),
        "ca_city": rng.choice(["rivertown", "lakeside", "hilltop",
                               "meadow", "brookfield"], n_custs),
        "ca_county": rng.choice(["Ziebach County", "Williamson County",
                                 "Walker County"], n_custs),
        "ca_state": rng.choice(["CA", "NY", "TX", "WA", "OR", "TN", "SD",
                                "GA", "KY", "NM"], n_custs),
        "ca_zip": ["%05d" % z for z in rng.integers(10000, 99999, n_custs)],
        "ca_country": ["United States"] * n_custs,
        "ca_gmt_offset": rng.choice([-5.0, -6.0, -7.0, -8.0], n_custs),
    })
    customer_demographics = pa.table({
        "cd_demo_sk": np.arange(1, n_cd + 1),
        "cd_gender": rng.choice(["M", "F"], n_cd),
        "cd_marital_status": rng.choice(["S", "M", "D", "W"], n_cd),
        "cd_education_status": rng.choice(
            ["Primary", "Secondary", "College", "Advanced Degree"], n_cd),
    })
    n_promos = 30
    promotion = pa.table({
        "p_promo_sk": np.arange(1, n_promos + 1),
        "p_channel_email": rng.choice(["Y", "N"], n_promos),
        "p_channel_event": rng.choice(["Y", "N"], n_promos),
        "p_channel_dmail": rng.choice(["Y", "N"], n_promos),
        "p_channel_tv": rng.choice(["Y", "N"], n_promos),
    })
    n_reasons = 10
    reason = pa.table({
        "r_reason_sk": np.arange(1, n_reasons + 1),
        "r_reason_desc": ["reason %d" % i for i in range(n_reasons)],
    })

    n_hd = 100
    household_demographics = pa.table({
        "hd_demo_sk": np.arange(1, n_hd + 1),
        "hd_dep_count": rng.integers(0, 10, n_hd),
        "hd_vehicle_count": rng.integers(0, 5, n_hd),
        "hd_buy_potential": rng.choice(
            [">10000", "5001-10000", "1001-5000", "501-1000", "0-500",
             "Unknown"], n_hd),
    })
    n_times = 24 * 60  # one row per minute of day
    time_dim = pa.table({
        "t_time_sk": np.arange(1, n_times + 1),
        "t_hour": np.arange(n_times) // 60,
        "t_minute": np.arange(n_times) % 60,
    })

    # tickets are coherent baskets: every line item of a ticket shares its
    # date/time/store/customer/demographics (like real receipts — the
    # Q34/Q73 per-ticket line counts depend on this); ~12 lines per ticket
    n_tickets = max(n_sales // 12, 1)
    ticket = rng.integers(1, n_tickets + 1, n_sales)
    t_date = rng.integers(1, n_days + 1, n_tickets + 1)
    t_time = rng.integers(1, n_times + 1, n_tickets + 1)
    t_store = rng.integers(1, n_stores + 1, n_tickets + 1)
    t_cust = rng.integers(1, n_custs + 1, n_tickets + 1)
    t_cd = rng.integers(1, n_cd + 1, n_tickets + 1)
    t_hd = rng.integers(1, n_hd + 1, n_tickets + 1)
    # delivery address is NOT always the customer's own (Q46/Q68 compare
    # bought city vs current city)
    t_addr = rng.integers(1, n_custs + 1, n_tickets + 1)
    # zipf-skewed item popularity: real catalogs have hits and long
    # tails (Q65 hunts store-item pairs far below the store average)
    ss_item = (rng.zipf(1.3, n_sales) - 1) % n_items + 1
    store_sales = pa.table({
        "ss_sold_date_sk": t_date[ticket],
        "ss_sold_time_sk": t_time[ticket],
        "ss_item_sk": ss_item,
        "ss_store_sk": t_store[ticket],
        "ss_customer_sk": t_cust[ticket],
        "ss_cdemo_sk": t_cd[ticket],
        "ss_hdemo_sk": t_hd[ticket],
        "ss_addr_sk": t_addr[ticket],
        "ss_promo_sk": rng.integers(1, n_promos + 1, n_sales),
        "ss_ticket_number": ticket,
        "ss_sales_price": rng.uniform(1, 300, n_sales).round(2),
        "ss_quantity": rng.integers(1, 100, n_sales),
        "ss_list_price": rng.uniform(1, 300, n_sales).round(2),
        "ss_coupon_amt": rng.uniform(0, 50, n_sales).round(2),
        "ss_ext_sales_price": rng.uniform(1, 3000, n_sales).round(2),
        "ss_ext_list_price": rng.uniform(1, 3000, n_sales).round(2),
        "ss_ext_discount_amt": rng.uniform(0, 300, n_sales).round(2),
        "ss_ext_wholesale_cost": rng.uniform(1, 1500, n_sales).round(2),
        "ss_wholesale_cost": rng.uniform(1, 100, n_sales).round(2),
        "ss_ext_tax": rng.uniform(0, 200, n_sales).round(2),
        "ss_net_paid": rng.uniform(1, 2500, n_sales).round(2),
        "ss_net_profit": rng.uniform(-500, 1500, n_sales).round(2),
    })

    # store_returns: ~8% of sale lines come back, days after the sale.
    # (sr_item_sk, sr_ticket_number) is the spec's PK — dedupe candidate
    # lines on that pair (tickets often hold several lines of one item)
    cand = rng.choice(n_sales, max(n_sales // 10, 12), replace=False)
    pair = ss_item[cand].astype(np.int64) * (n_tickets + 2) + ticket[cand]
    _, first = np.unique(pair, return_index=True)
    ret_idx = cand[np.sort(first)]
    n_ret = len(ret_idx)
    store_returns = pa.table({
        "sr_returned_date_sk": np.minimum(
            t_date[ticket[ret_idx]] + rng.integers(1, 60, n_ret), n_days),
        "sr_item_sk": ss_item[ret_idx],
        "sr_customer_sk": t_cust[ticket[ret_idx]],
        "sr_store_sk": t_store[ticket[ret_idx]],
        "sr_ticket_number": ticket[ret_idx],
        "sr_reason_sk": rng.integers(1, n_reasons + 1, n_ret),
        "sr_return_quantity": rng.integers(1, 20, n_ret),
        "sr_return_amt": rng.uniform(1, 300, n_ret).round(2),
    })

    for name, t in (("date_dim", date_dim), ("item", item),
                    ("store", store), ("store_sales", store_sales),
                    ("customer", customer),
                    ("customer_address", customer_address),
                    ("customer_demographics", customer_demographics),
                    ("promotion", promotion),
                    ("household_demographics", household_demographics),
                    ("time_dim", time_dim), ("reason", reason),
                    ("store_returns", store_returns)):
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        pq.write_table(t, os.path.join(d, "part-0.parquet"))
