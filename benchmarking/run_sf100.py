"""TPC-H SF100 single-box suite runner (BASELINE.json's headline metric).

One measured run per query (no warm/hot pair — at SF100 a second pass
would double a multi-hour run; the reported number is a cold-cache
single pass, stated as such in the artifact). Results append to the
output JSON after EVERY query so a crash or timeout still leaves a
usable partial record.

r23: memory-governed. ``DAFT_TPU_MEMORY_LIMIT`` arms the process-wide
governor (execution/governor.py) — RSS watermarks back-pressure scan
prefetch and shrink spill fanout before the OS OOMs — and every query's
record carries its spill bytes (logical + post-codec disk), recursion
depth, governor actions, replan count, strategy picks, and peak RSS.
Skips are itemized, never silent: a query is recorded as
``{"skipped": "budget", ...}`` when the wall-clock budget ran out or
``{"skipped": "missing_table", ...}`` when its input isn't generated,
so partial-coverage runs state exactly what they didn't cover.

Usage:
    DAFT_TPU_MEMORY_LIMIT=64GB python -m benchmarking.run_sf100 \
        [--data .cache/tpch_sf100.0_v2] [--out benchmarking/results/...] \
        [--budget-s 7200]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: input tables per TPC-H query — the missing-table itemizer's map
QUERY_TABLES = {
    "q1": ["lineitem"],
    "q2": ["part", "supplier", "partsupp", "nation", "region"],
    "q3": ["customer", "orders", "lineitem"],
    "q4": ["orders", "lineitem"],
    "q5": ["customer", "orders", "lineitem", "supplier", "nation",
           "region"],
    "q6": ["lineitem"],
    "q7": ["supplier", "lineitem", "orders", "customer", "nation"],
    "q8": ["part", "supplier", "lineitem", "orders", "customer",
           "nation", "region"],
    "q9": ["part", "supplier", "lineitem", "partsupp", "orders",
           "nation"],
    "q10": ["customer", "orders", "lineitem", "nation"],
    "q11": ["partsupp", "supplier", "nation"],
    "q12": ["orders", "lineitem"],
    "q13": ["customer", "orders"],
    "q14": ["lineitem", "part"],
    "q15": ["supplier", "lineitem"],
    "q16": ["partsupp", "part", "supplier"],
    "q17": ["lineitem", "part"],
    "q18": ["customer", "orders", "lineitem"],
    "q19": ["lineitem", "part"],
    "q20": ["supplier", "nation", "partsupp", "part", "lineitem"],
    "q21": ["supplier", "lineitem", "orders", "nation"],
    "q22": ["customer", "orders"],
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=os.path.join(
        REPO, ".cache", "tpch_sf100.0_v2"))
    ap.add_argument("--out", default=os.path.join(
        REPO, "benchmarking", "results", "r23_sf100_host.json"))
    ap.add_argument("--queries", default=",".join(
        f"q{i}" for i in range(1, 23)))
    ap.add_argument("--budget-s", type=float, default=0.0,
                    help="wall-clock budget; 0 = unbounded. Queries past "
                         "it are itemized as skipped, not dropped.")
    ap.add_argument("--note", default="")
    args = ap.parse_args()

    # host tier unless the caller explicitly opted into the device tier:
    # the engine's gate reads this env var (device/runtime.py:36), and the
    # default-on device tier running over XLA-CPU pays a compile per
    # (shape-bucket, op) — at SF100's 50-file scans that is minutes of
    # native compile time invisible to the query
    os.environ.setdefault("DAFT_TPU_DEVICE", "0")
    import jax
    if os.environ.get("DAFT_TPU_DEVICE") == "0":
        jax.config.update("jax_platforms", "cpu")
    import daft_tpu as dt
    from benchmarking.tpch import queries as Q

    import bench as _bench
    from daft_tpu.execution import governor as gov

    def get_df(name):
        return dt.read_parquet(os.path.join(args.data, name, "*.parquet"))

    doc = {
        "run": os.path.basename(args.out).removesuffix(".json"),
        "note": args.note or (
            "single box, host tier, push executor, cold single-pass per "
            "query (no hot rerun at this scale); chunked spec-conformant "
            "datagen v2; memory-governed (r23): spill fast path + "
            "RSS-watermark backpressure"),
        "memory_limit": os.environ.get("DAFT_TPU_MEMORY_LIMIT"),
        "governor": {"enabled": gov.enabled(),
                     "watermarks": list(gov.watermarks())},
        "scale_factor": 100,
        "budget_s": args.budget_s or None,
        "per_query_s": {},
        "per_query": {},
        "total_s": 0.0,
    }

    present = {t for t in set(sum(QUERY_TABLES.values(), []))
               if os.path.isdir(os.path.join(args.data, t))}
    t_start = time.time()
    maxrss = 0
    for qn in args.queries.split(","):
        missing = [t for t in QUERY_TABLES.get(qn, []) if t not in present]
        if missing:
            doc["per_query_s"][qn] = {"skipped": "missing_table",
                                      "tables": missing}
            print(f"{qn}: SKIP missing {missing}", file=sys.stderr,
                  flush=True)
            continue
        if args.budget_s:
            remaining = args.budget_s - (time.time() - t_start)
            if remaining < 0:
                doc["per_query_s"][qn] = {
                    "skipped": "budget",
                    "remaining_s": round(remaining, 1)}
                print(f"{qn}: SKIP budget", file=sys.stderr, flush=True)
                continue
        s0 = _bench._rich_counters_start()
        t0 = time.time()
        try:
            out = getattr(Q, qn)(get_df).to_pydict()
            dt_s = round(time.time() - t0, 3)
            rec = _bench._rich_counters_finish(s0)
            rec["wall_s"] = dt_s
            doc["per_query_s"][qn] = dt_s
            doc["per_query"][qn] = rec
            doc["total_s"] = round(doc["total_s"] + dt_s, 3)
            rows = len(next(iter(out.values()))) if out else 0
            print(f"{qn}: {dt_s}s rows={rows} "
                  f"rss_peak={rec['rss_peak_bytes'] >> 20}MB",
                  file=sys.stderr, flush=True)
        except Exception as exc:
            doc["per_query_s"][qn] = {"error": str(exc)[:300]}
            print(f"{qn}: FAIL {exc}", file=sys.stderr, flush=True)
        # the per-query bookends reset the peak, so the run-wide max is
        # accumulated here, not read once at the end
        maxrss = max(maxrss, gov.peak_rss_bytes())
        doc["maxrss_gb"] = round(maxrss / 1e9, 2)
        doc["governor_totals"] = {
            k: int(v) for k, v in sorted(gov.counters_snapshot().items())}
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps({"total_s": doc["total_s"],
                      "maxrss_gb": doc.get("maxrss_gb")}))


if __name__ == "__main__":
    main()
