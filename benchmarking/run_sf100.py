"""TPC-H SF100 single-box suite runner (BASELINE.json's headline metric).

One measured run per query (no warm/hot pair — at SF100 a second pass
would double a multi-hour run; the reported number is a cold-cache
single pass, stated as such in the artifact). Results append to the
output JSON after EVERY query so a crash or timeout still leaves a
usable partial record.

Usage:
    DAFT_TPU_MEMORY_LIMIT=64GB python -m benchmarking.run_sf100 \
        [--data .cache/tpch_sf100.0_v2] [--out benchmarking/results/...]
"""
from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=os.path.join(
        REPO, ".cache", "tpch_sf100.0_v2"))
    ap.add_argument("--out", default=os.path.join(
        REPO, "benchmarking", "results", "r4_sf100_host.json"))
    ap.add_argument("--queries", default=",".join(
        f"q{i}" for i in range(1, 23)))
    ap.add_argument("--note", default="")
    args = ap.parse_args()

    # host tier unless the caller explicitly opted into the device tier:
    # the engine's gate reads this env var (device/runtime.py:36), and the
    # default-on device tier running over XLA-CPU pays a compile per
    # (shape-bucket, op) — at SF100's 50-file scans that is minutes of
    # native compile time invisible to the query
    os.environ.setdefault("DAFT_TPU_DEVICE", "0")
    import jax
    if os.environ.get("DAFT_TPU_DEVICE") == "0":
        jax.config.update("jax_platforms", "cpu")
    from benchmarking.tpch import queries as Q
    import daft_tpu as dt

    def get_df(name):
        return dt.read_parquet(os.path.join(args.data, name, "*.parquet"))

    doc = {
        "run": os.path.basename(args.out).removesuffix(".json"),
        "note": args.note or (
            "single box, host tier, push executor, cold single-pass per "
            "query (no hot rerun at this scale); chunked spec-conformant "
            "datagen v2"),
        "memory_limit": os.environ.get("DAFT_TPU_MEMORY_LIMIT"),
        "scale_factor": 100,
        "per_query_s": {},
        "total_s": 0.0,
    }

    for qn in args.queries.split(","):
        t0 = time.time()
        try:
            out = getattr(Q, qn)(get_df).to_pydict()
            dt_s = round(time.time() - t0, 3)
            doc["per_query_s"][qn] = dt_s
            doc["total_s"] = round(doc["total_s"] + dt_s, 3)
            rows = len(next(iter(out.values()))) if out else 0
            print(f"{qn}: {dt_s}s rows={rows}", file=sys.stderr, flush=True)
        except Exception as exc:
            doc["per_query_s"][qn] = {"error": str(exc)[:300]}
            print(f"{qn}: FAIL {exc}", file=sys.stderr, flush=True)
        doc["maxrss_gb"] = round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps({"total_s": doc["total_s"],
                      "maxrss_gb": doc.get("maxrss_gb")}))


if __name__ == "__main__":
    main()
