"""User-defined functions.

Reference: ``daft/udf.py`` — ``@daft.udf`` decorator → UDF dataclass with
return_dtype / resource requests / batch_size / concurrency / init_args;
batch slicing + scalar broadcasting + output coercion (``udf.py:91-200``).
Stateful (class) UDFs get a dedicated worker pool (the reference's actor
pools, ``SplitActorPoolProjects`` → ``ActorPoolProject``).
"""

from __future__ import annotations

import functools
import inspect
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np
import pyarrow as pa

from .datatype import DataType
from .expressions.expressions import Expression
from .series import Series


class UDF:
    def __init__(self, func: Callable, return_dtype: DataType,
                 num_cpus: Optional[float] = None,
                 num_gpus: Optional[float] = None,
                 memory_bytes: Optional[int] = None,
                 batch_size: Optional[int] = None,
                 concurrency: Optional[int] = None,
                 init_args: Optional[Tuple[tuple, dict]] = None):
        self.func = func
        self.return_dtype = return_dtype
        self.num_cpus = num_cpus
        self.num_gpus = num_gpus
        self.memory_bytes = memory_bytes
        self.batch_size = batch_size
        self.concurrency = concurrency
        self.init_args = init_args
        self.is_stateful = inspect.isclass(func)
        self._instance = None
        self._pool: Any = "unset"
        self._instance_lock = threading.Lock()
        functools.update_wrapper(self, func) if not self.is_stateful else None
        self.name = getattr(func, "__name__", type(func).__name__)

    def __call__(self, *args, **kwargs) -> Expression:
        exprs = []
        arg_spec: List[Tuple[str, Any]] = []  # ("expr", idx) | ("lit", value)
        for a in args:
            if isinstance(a, Expression):
                arg_spec.append(("expr", len(exprs)))
                exprs.append(a)
            else:
                arg_spec.append(("lit", a))
        kw_spec: Dict[str, Any] = {}
        for k, v in kwargs.items():
            if isinstance(v, Expression):
                kw_spec[k] = ("expr", len(exprs))
                exprs.append(v)
            else:
                kw_spec[k] = ("lit", v)
        return Expression("udf", tuple(exprs),
                          (self, tuple(arg_spec), tuple(sorted(kw_spec.items()))))

    def override_options(self, *, num_cpus=None, num_gpus=None,
                         memory_bytes=None, batch_size=None) -> "UDF":
        return UDF(self.func, self.return_dtype,
                   num_cpus if num_cpus is not None else self.num_cpus,
                   num_gpus if num_gpus is not None else self.num_gpus,
                   memory_bytes if memory_bytes is not None else self.memory_bytes,
                   batch_size if batch_size is not None else self.batch_size,
                   self.concurrency, self.init_args)

    def with_concurrency(self, concurrency: int) -> "UDF":
        return UDF(self.func, self.return_dtype, self.num_cpus, self.num_gpus,
                   self.memory_bytes, self.batch_size, concurrency,
                   self.init_args)

    def with_init_args(self, *args, **kwargs) -> "UDF":
        return UDF(self.func, self.return_dtype, self.num_cpus, self.num_gpus,
                   self.memory_bytes, self.batch_size, self.concurrency,
                   (args, kwargs))

    def _callable(self) -> Callable:
        if not self.is_stateful:
            return self.func
        with self._instance_lock:
            if self._instance is None:
                a, kw = self.init_args or ((), {})
                self._instance = self.func(*a, **kw)
            return self._instance

    def _get_pool(self):
        """Process actor pool for stateful UDFs (reference:
        ``daft/execution/actor_pool_udf.py`` OS-process actors). None →
        the shared in-process instance (unpicklable UDF or pool disabled)."""
        if not self.is_stateful:
            return None
        with self._instance_lock:
            if self._pool == "unset":
                from . import actor_pool
                self._pool = actor_pool.try_make_pool(self)
            return self._pool

    def run(self, evaluated: List[Series], arg_spec, kw_spec,
            length: int) -> Series:
        """Called per batch by the evaluator — slices into batch_size chunks,
        broadcasts scalars, coerces output (reference: run_udf). Stateful
        UDFs route through the actor pool so concurrency=N runs N real
        processes with independent instances."""
        pool = self._get_pool()
        if pool is not None:
            # Python-object columns can't cross the Arrow IPC boundary —
            # those batches (and python return dtypes) stay in-process
            ipc_ok = self.return_dtype.kind != "python" and \
                not any(s.is_pyobject() for s in evaluated)
            if ipc_ok:
                try:
                    return pool.call(evaluated, arg_spec, kw_spec, length)
                except RuntimeError:
                    # actor-side failure (e.g. unserializable payload):
                    # permanently fall back to the shared instance
                    with self._instance_lock:
                        self._pool = None
        return run_udf_batches(self._callable(), evaluated, arg_spec,
                               kw_spec, length, self.batch_size,
                               self.return_dtype, self.name)


def run_udf_batches(fn: Callable, evaluated: List[Series], arg_spec, kw_spec,
                    length: int, batch_size: Optional[int],
                    return_dtype: DataType, name: str) -> Series:
    """Batch-slicing + scalar-broadcast + output-coercion loop — shared by
    the in-process path and the actor-pool child (actor_pool._actor_main)."""
    chunks: List[Series] = []
    bs = batch_size or length or 1
    for start in range(0, max(length, 1), bs):
        end = min(start + bs, length)

        def materialize(spec):
            kind, v = spec
            if kind == "expr":
                s = evaluated[v]
                return s.slice(start, end) if len(s) == length else s
            return v

        call_args = [materialize(s) for s in arg_spec]
        call_kwargs = {k: materialize(s) for k, s in kw_spec}
        out = fn(*call_args, **call_kwargs)
        chunks.append(coerce_udf_output(out, return_dtype, end - start))
    if not chunks:
        return Series.empty(name, return_dtype)
    return Series.concat(chunks) if len(chunks) > 1 else chunks[0]


def coerce_udf_output(out: Any, dtype: DataType, length: int) -> Series:
    if isinstance(out, Series):
        return out.cast(dtype)
    if isinstance(out, (pa.Array, pa.ChunkedArray)):
        return Series.from_arrow(out).cast(dtype)
    if isinstance(out, np.ndarray):
        return Series.from_numpy(out).cast(dtype)
    if isinstance(out, list):
        return Series.from_pylist(out, "udf", dtype=dtype)
    # scalar -> broadcast
    return Series.from_pylist([out] * length, "udf", dtype=dtype)


def udf(*, return_dtype: DataType, num_cpus: Optional[float] = None,
        num_gpus: Optional[float] = None, memory_bytes: Optional[int] = None,
        batch_size: Optional[int] = None,
        concurrency: Optional[int] = None) -> Callable[[Callable], UDF]:
    """``@daft_tpu.udf(return_dtype=...)`` decorator
    (reference: ``daft/udf.py:201``)."""

    def wrap(fn: Callable) -> UDF:
        return UDF(fn, return_dtype, num_cpus, num_gpus, memory_bytes,
                   batch_size, concurrency)
    return wrap


def expr_has_stateful_udf(e: Expression) -> bool:
    if e.op == "udf" and e.params[0].is_stateful:
        return True
    return any(expr_has_stateful_udf(c) for c in e.args)


def stateful_udf_concurrency(exprs) -> Optional[int]:
    for e in exprs:
        if e.op == "udf" and e.params[0].is_stateful:
            return e.params[0].concurrency
        for c in e.args:
            r = stateful_udf_concurrency([c])
            if r is not None:
                return r
    return None
