"""Join kernels (host tier).

Reference capability: ``src/daft-recordbatch/src/ops/joins/mod.rs:78-195``
(hash_join / sort_merge_join / cross_join) and the probe-table machinery
(``probeable/probe_table.rs:19``). Here the host path factorizes join keys to
dense group ids (Arrow C++ dictionary encode + np.unique over code rows), then
runs a fully vectorized sort+searchsorted merge — the same sort-merge
formulation the TPU tier uses in ``device.kernels.join_fused_kernel``, so the
two tiers share one algorithm family.

Join semantics follow the reference: inner/left/right/outer/semi/anti; NULL
keys never match; right-side columns colliding with left names get a
``right.`` prefix; outer joins coalesce key columns.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .expressions import Expression
from .series import Series


def _factorize_pair(l_arrs: List[pa.Array], r_arrs: List[pa.Array]
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Map rows of (left, right) key columns to shared dense ids.

    Returns (l_gids, r_gids, l_valid, r_valid); gid comparisons implement
    multi-column key equality. NULL in any key column marks the row invalid.
    """
    n_l = len(l_arrs[0]) if l_arrs else 0
    n_r = len(r_arrs[0]) if r_arrs else 0
    code_cols = []
    l_valid = np.ones(n_l, dtype=bool)
    r_valid = np.ones(n_r, dtype=bool)
    for la, ra in zip(l_arrs, r_arrs):
        if la.type != ra.type:
            from .datatype import DataType
            from .expressions.typing import supertype
            st = supertype(DataType.from_arrow_type(la.type),
                           DataType.from_arrow_type(ra.type)).to_arrow()
            la, ra = la.cast(st), ra.cast(st)
        combined = pa.chunked_array([la, ra]).combine_chunks()
        if pa.types.is_integer(combined.type) \
                and not pa.types.is_uint64(combined.type):
            # integer keys: range-based codes (value - min) skip the
            # dictionary hash table entirely — O(n) with no table build.
            # TPC-H/TPC-DS keys are dense ints, so the range stays tight.
            # Validity comes from Arrow's null mask, never a value
            # sentinel (INT64_MIN is a legal key); uint64 keys ≥ 2^63
            # don't fit int64 and take the dictionary path below.
            valid = np.asarray(pc.is_valid(combined)
                               .to_numpy(zero_copy_only=False), dtype=bool)
            vals = np.asarray(pc.fill_null(combined.cast(pa.int64()), 0)
                              .to_numpy(zero_copy_only=False),
                              dtype=np.int64)
            live = vals[valid]
            lo = int(live.min()) if live.size else 0
            hi = int(live.max()) if live.size else 0
            if hi - lo < (1 << 40):
                codes = np.where(valid, vals - lo, -1)
                l_valid &= valid[:n_l]
                r_valid &= valid[n_l:]
                code_cols.append(codes)
                continue
        codes_arr = combined.dictionary_encode().indices
        codes = np.asarray(pc.fill_null(codes_arr, -1)
                           .to_numpy(zero_copy_only=False), dtype=np.int64)
        valid = codes >= 0
        l_valid &= valid[:n_l]
        r_valid &= valid[n_l:]
        code_cols.append(codes)
    if len(code_cols) == 1:
        gids = code_cols[0]
    else:
        # arithmetic packing: per-column codes are bounded, so
        # gid = ((c0 * card1 + c1) * card2 + c2)… fits int64 while the
        # cardinality product stays under 2^62 — the structured-void
        # np.unique fallback (memcmp sort, ~µs/row) only runs past that
        maxes = [int(c.max()) + 2 if c.size else 2 for c in code_cols]
        prod = 1
        for m in maxes:
            prod *= m
        if 0 < prod < (1 << 62):
            gids = code_cols[0].astype(np.int64, copy=True)
            for c, m in zip(code_cols[1:], maxes[1:]):
                gids *= m
                gids += c
        else:
            stacked = np.ascontiguousarray(
                np.stack(code_cols, axis=1).astype(np.int64))
            void = stacked.view([("", np.int64)] * stacked.shape[1]).ravel()
            _, gids = np.unique(void, return_inverse=True)
            gids = gids.astype(np.int64)
    return gids[:n_l], gids[n_l:], l_valid, r_valid


def match_indices(l_gids: np.ndarray, r_gids: np.ndarray,
                  l_valid: np.ndarray, r_valid: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized merge: for each left row, all matching right rows.

    Returns (li, ri, l_match_counts): parallel index arrays of the matching
    pairs plus per-left-row match counts.

    The device tier's FUSED sort/searchsorted/expand kernel
    (``device.kernels.join_fused_kernel`` — one dispatch, one packed
    result transfer) is chosen by the measured link cost model
    (``device.costmodel.join_wins``): the output is row-shaped (one
    index pair per match), so on a transfer-bound single-chip link the
    device loses to the host by >10× measured and the model picks numpy;
    on a local chip (or the CPU mesh in tests) the kernel wins and the
    model picks it. ``DAFT_TPU_DEVICE_JOIN=1/0`` force-overrides.
    """
    from .analysis import knobs
    env = knobs.env_raw("DAFT_TPU_DEVICE_JOIN")
    use_device = env == "1"
    if env is None:
        from .device import costmodel, runtime as drt
        n_l, n_r = len(l_gids), len(r_gids)
        # output estimate: FK-join shaped — about one match per probe row
        est_out = 2 * 8 * max(n_l, n_r)
        # priced SERIAL on purpose: the join dispatch runs inline on its
        # calling thread, not inside the r17 in-flight window, so there
        # are no neighbor dispatches to hide its transfer behind —
        # join_wins(window=) waits for the join path to ride the
        # pipeline before claiming the overlap discount
        use_device = (drt.device_enabled()
                      and n_l + n_r >= 8192
                      and costmodel.join_wins(
                          n_l, n_r,
                          l_gids.nbytes + r_gids.nbytes
                          + l_valid.nbytes + r_valid.nbytes, est_out))
    if use_device:
        out = _device_match_indices(l_gids, r_gids, l_valid, r_valid)
        if out is not None:
            return out
    n_l = len(l_gids)
    r_idx = np.flatnonzero(r_valid)
    r_vals = r_gids[r_idx]
    order = np.argsort(r_vals, kind="stable")
    r_sorted_vals = r_vals[order]
    r_sorted_idx = r_idx[order]

    starts = np.searchsorted(r_sorted_vals, l_gids, side="left")
    ends = np.searchsorted(r_sorted_vals, l_gids, side="right")
    counts = np.where(l_valid, ends - starts, 0)
    total = int(counts.sum())
    li = np.repeat(np.arange(n_l), counts)
    cum = np.cumsum(counts) - counts  # exclusive prefix, same length as counts
    offsets = np.arange(total) - np.repeat(cum, counts)
    ri = r_sorted_idx[np.repeat(starts, counts) + offsets]
    return li, ri, counts


def _take_nullable(s: Series, idx: np.ndarray, valid: np.ndarray) -> Series:
    if s.is_pyobject():
        out = np.empty(len(idx), dtype=object)
        vals = s._pyobjs
        for i, (j, v) in enumerate(zip(idx, valid)):
            out[i] = vals[j] if v else None
        return Series(s.name(), s.datatype(), pyobjs=out)
    ia = pa.array(idx, mask=~valid)
    return Series(s.name(), s.datatype(), arrow=s.to_arrow().take(ia))


def _device_match_indices(l_gids, r_gids, l_valid, r_valid):
    """Fused single-dispatch device join index generation, at the
    strategy the cost model picks per dispatch (round 12):

    - ``hash``: Pallas build/probe — ONE streaming pass per side through
      an HBM/VMEM-resident chained hash table
      (``pallas_kernels.hash_join_kernel``);
    - ``sort``: build-side sort + probe counts + prefix-sum expansion
      (``kernels.join_fused_kernel``, the r6 kernel).

    Either way it is ONE jit program returning ONE packed index matrix
    (r5's three-phase pipeline paid two host round-trips between phases).
    The output bucket is sized FK-shaped (≈ one match per probe row); a
    larger true total re-dispatches once at the fitting bucket, the
    grouped-agg overflow discipline. None on device-off."""
    from .device import runtime as drt
    if not drt.device_enabled():
        return None
    import time as _time

    import jax.numpy as jnp

    from .device import costmodel, kernels as K, mfu
    from .device import pallas_kernels as pk
    from .device.column import bucket_capacity

    def pad(a, cap, fill=0):
        out = np.full(cap, fill, dtype=a.dtype)
        out[:len(a)] = a
        return out

    n_l, n_r = len(l_gids), len(r_gids)
    c_l, c_r = bucket_capacity(n_l), bucket_capacity(n_r)
    lmask = np.zeros(c_l, bool)
    lmask[:n_l] = True
    rmask = np.zeros(c_r, bool)
    rmask[:n_r] = True
    strategy = costmodel.join_strategy(n_l, n_r)
    kernel = pk.hash_join_kernel if strategy == "hash" \
        else K.join_fused_kernel

    def dispatch(cap):
        # device arrays are rebuilt per dispatch: both kernels DONATE the
        # build side's buffers on real chips, so an overflow re-dispatch
        # cannot reuse them
        from .analysis import retrace_sanitizer
        site = "pallas.hash_join" if kernel is pk.hash_join_kernel \
            else "kernels.join_fused"
        # declared trace signature: build/probe capacity classes + the
        # out-capacity bucket; the same signature must re-enter the jit
        # cache, never re-trace
        from .device import pipeline as dpipe
        with retrace_sanitizer.dispatch_scope(site, (c_l, c_r, cap)):
            return np.asarray(dpipe.fetch_host(kernel(
                jnp.asarray(pad(l_gids.astype(np.int64), c_l)),
                jnp.asarray(pad(l_valid, c_l)), jnp.asarray(lmask),
                jnp.asarray(pad(r_gids.astype(np.int64), c_r)),
                jnp.asarray(pad(r_valid, c_r)), jnp.asarray(rmask),
                out_capacity=cap)))

    t0 = _time.perf_counter()
    cap = max(bucket_capacity(max(n_l, n_r, 1)), 1024)
    packed = dispatch(cap)
    counts = packed[2, :n_l].astype(np.int64)
    total = int(counts.sum())
    hist = [(strategy, cap)]  # one entry per dispatch that ran
    if total > cap:  # rare: many-to-many blowup past the FK estimate
        cap = bucket_capacity(total)
        if strategy == "hash" and cap > pk.max_table_slots():
            # the probe kernel pins two cap-sized output index planes
            # on-chip (whole-plane BlockSpecs); a many-to-many blowup
            # bucket past the slot ceiling belongs to the sort kernel,
            # whose buffers live in HBM
            strategy, kernel = "sort", K.join_fused_kernel
        packed = dispatch(cap)
        hist.append((strategy, cap))

    def _model(strat, c):
        return mfu.hash_join_bytes_model(c_l, c_r, c) if strat == "hash" \
            else mfu.join_bytes_model(c_l, c_r, c)

    # per-strategy accounting (the overflow re-dispatch can switch the
    # ladder to sort): each family record carries its own dispatch count
    # and byte model; the row count and whole-ladder wall go to the
    # completing strategy's record — the same discipline as the fused-agg
    # ladder in device/fragment.py
    secs = _time.perf_counter() - t0
    acct: dict = {}
    for s_, c_ in hist:
        d = acct.setdefault(s_, [0, 0])
        d[0] += 1
        d[1] += _model(s_, c_)
    for s_, (n_disp, nbytes) in acct.items():
        final = s_ == strategy
        # live build rows over the 2× build-capacity table: ≤ 0.5 by
        # construction (the table can never fill)
        lf = n_r / pk.join_table_capacity(c_r) if s_ == "hash" else None
        costmodel.ledger_record(
            "join", rows=(n_l + n_r) if final else 0, nbytes=nbytes,
            seconds=secs if final else 0.0, dispatches=n_disp,
            strategy=s_, load_factor=lf)
    return (packed[0, :total].astype(np.int64),
            packed[1, :total].astype(np.int64), counts)


def join_recordbatch(left, right, left_on: List[Expression],
                     right_on: List[Expression], how: str = "inner"):
    from .recordbatch import RecordBatch

    l_keys = [left.eval_expression(e) for e in left_on]
    r_keys = [right.eval_expression(e) for e in right_on]
    l_gids, r_gids, l_valid, r_valid = _factorize_pair(
        [k.to_arrow() for k in l_keys], [k.to_arrow() for k in r_keys])

    if how in ("semi", "anti"):
        matched_gids = np.unique(r_gids[r_valid])
        has = np.isin(l_gids, matched_gids) & l_valid
        mask = has if how == "semi" else ~has
        return RecordBatch(left.schema,
                           [c.filter(mask) for c in left.columns()],
                           int(mask.sum()))

    li, ri, counts = match_indices(l_gids, r_gids, l_valid, r_valid)
    l_matched_mask = np.ones(len(li), dtype=bool)
    r_matched_mask = np.ones(len(ri), dtype=bool)

    if how in ("left", "outer", "full"):
        unmatched_l = np.flatnonzero(counts == 0)
        li = np.concatenate([li, unmatched_l])
        ri = np.concatenate([ri, np.zeros(len(unmatched_l), dtype=ri.dtype)])
        l_matched_mask = np.concatenate(
            [l_matched_mask, np.ones(len(unmatched_l), dtype=bool)])
        r_matched_mask = np.concatenate(
            [r_matched_mask, np.zeros(len(unmatched_l), dtype=bool)])
    if how in ("right", "outer", "full"):
        r_hit = np.zeros(len(right), dtype=bool)
        r_hit[ri[r_matched_mask]] = True
        unmatched_r = np.flatnonzero(~r_hit)
        li = np.concatenate([li, np.zeros(len(unmatched_r), dtype=li.dtype)])
        ri = np.concatenate([ri, unmatched_r])
        l_matched_mask = np.concatenate(
            [l_matched_mask, np.zeros(len(unmatched_r), dtype=bool)])
        r_matched_mask = np.concatenate(
            [r_matched_mask, np.ones(len(unmatched_r), dtype=bool)])

    # column assembly --------------------------------------------------
    l_key_names = [e.name() for e in left_on]
    r_key_names = [e.name() for e in right_on]
    left_names = set(left.column_names())

    out_cols: List[Series] = []
    for c in left.columns():
        s = _take_nullable(c, li, l_matched_mask)
        if how in ("outer", "full") and c.name() in l_key_names:
            # coalesce join keys from both sides
            ki = l_key_names.index(c.name())
            r_key_taken = _take_nullable(r_keys[ki], ri, r_matched_mask)
            merged = pc.if_else(
                pa.array(l_matched_mask),
                s.to_arrow(),
                r_key_taken.cast(s.datatype()).to_arrow())
            s = Series(c.name(), s.datatype(), arrow=merged)
        out_cols.append(s)
    for c in right.columns():
        if c.name() in r_key_names:
            ki = r_key_names.index(c.name())
            # drop right key when it pairs with an identically-named left key
            if ki < len(l_key_names) and l_key_names[ki] == c.name():
                continue
        nm = c.name()
        if nm in left_names:
            nm = f"right.{nm}"
        out_cols.append(_take_nullable(c, ri, r_matched_mask).rename(nm))
    return RecordBatch.from_series(out_cols) if out_cols else RecordBatch.empty()
