"""Field and Schema (reference: ``src/daft-schema/src/{field.rs,schema.rs:26}``)."""

from __future__ import annotations

from typing import Iterator, List, Optional

import pyarrow as pa

from .datatype import DataType


class Field:
    __slots__ = ("name", "dtype", "metadata")

    def __init__(self, name: str, dtype: DataType, metadata: Optional[dict] = None):
        self.name = name
        self.dtype = dtype
        self.metadata = metadata or {}

    @classmethod
    def create(cls, name: str, dtype: DataType) -> "Field":
        return cls(name, dtype)

    def to_arrow(self) -> pa.Field:
        return pa.field(self.name, self.dtype.to_arrow())

    def rename(self, name: str) -> "Field":
        return Field(name, self.dtype, self.metadata)

    def __eq__(self, other):
        return (isinstance(other, Field) and self.name == other.name
                and self.dtype == other.dtype)

    def __hash__(self):
        return hash((self.name, self.dtype))

    def __repr__(self):
        return f"Field({self.name!r}, {self.dtype!r})"


class Schema:
    """An ordered mapping of column name → Field with O(1) lookup."""

    __slots__ = ("_fields", "_index")

    def __init__(self, fields: List[Field]):
        self._fields = list(fields)
        self._index = {}
        for i, f in enumerate(self._fields):
            if f.name in self._index:
                raise ValueError(f"duplicate column name in schema: {f.name!r}")
            self._index[f.name] = i

    # ---- constructors ----------------------------------------------------
    @classmethod
    def from_fields(cls, fields: List[Field]) -> "Schema":
        return cls(fields)

    @classmethod
    def from_pydict(cls, d: "dict[str, DataType]") -> "Schema":
        return cls([Field(n, t) for n, t in d.items()])

    @classmethod
    def from_arrow(cls, s: pa.Schema) -> "Schema":
        return cls([Field(f.name, DataType.from_arrow_type(f.type)) for f in s])

    @classmethod
    def empty(cls) -> "Schema":
        return cls([])

    # ---- access ----------------------------------------------------------
    def __len__(self) -> int:
        return len(self._fields)

    def __iter__(self) -> Iterator[Field]:
        return iter(self._fields)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __getitem__(self, key) -> Field:
        if isinstance(key, int):
            return self._fields[key]
        return self._fields[self._index[key]]

    def index_of(self, name: str) -> int:
        return self._index[name]

    @property
    def column_names(self) -> List[str]:
        return [f.name for f in self._fields]

    @property
    def fields(self) -> List[Field]:
        return list(self._fields)

    def to_pydict(self) -> "dict[str, DataType]":
        return {f.name: f.dtype for f in self._fields}

    def to_arrow(self) -> pa.Schema:
        return pa.schema([f.to_arrow() for f in self._fields])

    # ---- algebra ---------------------------------------------------------
    def union(self, other: "Schema") -> "Schema":
        """Disjoint union; raises on duplicate names."""
        return Schema(self._fields + other._fields)

    def non_distinct_union(self, other: "Schema") -> "Schema":
        """Union keeping left field on name clash (reference: schema.rs non_distinct_union)."""
        fields = list(self._fields)
        for f in other._fields:
            if f.name not in self._index:
                fields.append(f)
        return Schema(fields)

    def project(self, names: List[str]) -> "Schema":
        return Schema([self[n] for n in names])

    def exclude(self, names: List[str]) -> "Schema":
        drop = set(names)
        return Schema([f for f in self._fields if f.name not in drop])

    def estimate_row_size_bytes(self) -> float:
        """Rough per-row byte estimate for scan-task sizing."""
        total = 0.0
        for f in self._fields:
            d = f.dtype.device_repr()
            total += d.itemsize if d is not None else 32.0
        return max(total, 1.0)

    def __eq__(self, other):
        return isinstance(other, Schema) and self._fields == other._fields

    def __hash__(self):
        return hash(tuple(self._fields))

    def __repr__(self):
        inner = ", ".join(f"{f.name}: {f.dtype!r}" for f in self._fields)
        return f"Schema({inner})"

    def _repr_html_(self):
        rows = "".join(
            f"<tr><td>{f.name}</td><td>{f.dtype!r}</td></tr>" for f in self._fields)
        return f"<table><tr><th>name</th><th>dtype</th></tr>{rows}</table>"
