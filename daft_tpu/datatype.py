"""DataType system for the TPU-native dataframe engine.

Mirrors the capability surface of the reference's ``daft-schema`` crate
(``src/daft-schema/src/dtype.rs:13-157`` — the 34-variant ``DataType`` enum with
multimodal types, and ``dtype.rs:307-335`` — the logical→physical lowering where
``Image`` lowers to a struct of (data, channel, height, width, mode) and ``Tensor``
lowers to a struct of (data, shape)), but designed fresh for a JAX/XLA substrate:

- every type knows its **Arrow** representation (host columnar memory, pyarrow) and
  its **device** representation (how it lowers onto TPU HBM as fixed-width JAX
  arrays — fixed-width primitives map directly; strings/binary dictionary-encode to
  int32 codes; nested/multimodal types stay host-resident unless fixed-shape).
"""

from __future__ import annotations

import builtins
from enum import Enum
from typing import Any, Optional, Tuple

import numpy as np
import pyarrow as pa


class ImageMode(Enum):
    """Supported image modes (reference: ``src/daft-schema/src/image_mode.rs``)."""

    L = 1
    LA = 2
    RGB = 3
    RGBA = 4
    L16 = 5
    LA16 = 6
    RGB16 = 7
    RGBA16 = 8
    RGB32F = 9
    RGBA32F = 10

    @property
    def num_channels(self) -> int:
        return {
            ImageMode.L: 1, ImageMode.LA: 2, ImageMode.RGB: 3, ImageMode.RGBA: 4,
            ImageMode.L16: 1, ImageMode.LA16: 2, ImageMode.RGB16: 3,
            ImageMode.RGBA16: 4, ImageMode.RGB32F: 3, ImageMode.RGBA32F: 4,
        }[self]

    @property
    def np_dtype(self) -> np.dtype:
        if self in (ImageMode.L, ImageMode.LA, ImageMode.RGB, ImageMode.RGBA):
            return np.dtype(np.uint8)
        if self in (ImageMode.L16, ImageMode.LA16, ImageMode.RGB16, ImageMode.RGBA16):
            return np.dtype(np.uint16)
        return np.dtype(np.float32)

    @classmethod
    def from_mode_string(cls, s: str) -> "ImageMode":
        return cls[s.upper()]


class ImageFormat(Enum):
    PNG = "PNG"
    JPEG = "JPEG"
    TIFF = "TIFF"
    GIF = "GIF"
    BMP = "BMP"

    @classmethod
    def from_format_string(cls, s: str) -> "ImageFormat":
        return cls[s.upper()]


class TimeUnit(Enum):
    s = "s"
    ms = "ms"
    us = "us"
    ns = "ns"

    @classmethod
    def from_str(cls, s: str) -> "TimeUnit":
        return cls[s]


class _Kind(Enum):
    NULL = "null"
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    DECIMAL128 = "decimal128"
    STRING = "string"
    BINARY = "binary"
    FIXED_SIZE_BINARY = "fixed_size_binary"
    DATE = "date"
    TIME = "time"
    TIMESTAMP = "timestamp"
    DURATION = "duration"
    INTERVAL = "interval"
    LIST = "list"
    FIXED_SIZE_LIST = "fixed_size_list"
    STRUCT = "struct"
    MAP = "map"
    EMBEDDING = "embedding"
    IMAGE = "image"
    FIXED_SHAPE_IMAGE = "fixed_shape_image"
    TENSOR = "tensor"
    FIXED_SHAPE_TENSOR = "fixed_shape_tensor"
    SPARSE_TENSOR = "sparse_tensor"
    FIXED_SHAPE_SPARSE_TENSOR = "fixed_shape_sparse_tensor"
    PYTHON = "python"
    EXTENSION = "extension"
    UNKNOWN = "unknown"


_NUMERIC_KINDS = {
    _Kind.INT8, _Kind.INT16, _Kind.INT32, _Kind.INT64,
    _Kind.UINT8, _Kind.UINT16, _Kind.UINT32, _Kind.UINT64,
    _Kind.FLOAT32, _Kind.FLOAT64, _Kind.DECIMAL128,
}
_INTEGER_KINDS = {
    _Kind.INT8, _Kind.INT16, _Kind.INT32, _Kind.INT64,
    _Kind.UINT8, _Kind.UINT16, _Kind.UINT32, _Kind.UINT64,
}
_TEMPORAL_KINDS = {_Kind.DATE, _Kind.TIME, _Kind.TIMESTAMP, _Kind.DURATION}


class DataType:
    """A logical column datatype.

    Construct via classmethods: ``DataType.int64()``, ``DataType.list(inner)``,
    ``DataType.image("RGB")`` etc. Instances are immutable and hashable.
    """

    __slots__ = ("_kind", "_params")

    def __init__(self, kind: _Kind, params: Tuple = ()):  # internal
        object.__setattr__(self, "_kind", kind)
        object.__setattr__(self, "_params", params)

    def __setattr__(self, k, v):
        raise AttributeError("DataType is immutable")

    def __reduce__(self):
        # __slots__ + blocked __setattr__ breaks default (cloud)pickle
        # state restoration; rebuild through __init__ instead
        return (DataType, (self._kind, self._params))

    # ---- constructors ----------------------------------------------------
    @classmethod
    def null(cls): return cls(_Kind.NULL)
    @classmethod
    def bool(cls): return cls(_Kind.BOOL)
    @classmethod
    def int8(cls): return cls(_Kind.INT8)
    @classmethod
    def int16(cls): return cls(_Kind.INT16)
    @classmethod
    def int32(cls): return cls(_Kind.INT32)
    @classmethod
    def int64(cls): return cls(_Kind.INT64)
    @classmethod
    def uint8(cls): return cls(_Kind.UINT8)
    @classmethod
    def uint16(cls): return cls(_Kind.UINT16)
    @classmethod
    def uint32(cls): return cls(_Kind.UINT32)
    @classmethod
    def uint64(cls): return cls(_Kind.UINT64)
    @classmethod
    def float32(cls): return cls(_Kind.FLOAT32)
    @classmethod
    def float64(cls): return cls(_Kind.FLOAT64)

    @classmethod
    def decimal128(cls, precision: int, scale: int):
        return cls(_Kind.DECIMAL128, (precision, scale))

    @classmethod
    def string(cls): return cls(_Kind.STRING)
    @classmethod
    def binary(cls): return cls(_Kind.BINARY)

    @classmethod
    def fixed_size_binary(cls, size: int):
        return cls(_Kind.FIXED_SIZE_BINARY, (size,))

    @classmethod
    def date(cls): return cls(_Kind.DATE)

    @classmethod
    def time(cls, timeunit: "TimeUnit | str" = TimeUnit.us):
        tu = TimeUnit.from_str(timeunit) if isinstance(timeunit, str) else timeunit
        return cls(_Kind.TIME, (tu,))

    @classmethod
    def timestamp(cls, timeunit: "TimeUnit | str" = TimeUnit.us,
                  timezone: Optional[str] = None):
        tu = TimeUnit.from_str(timeunit) if isinstance(timeunit, str) else timeunit
        return cls(_Kind.TIMESTAMP, (tu, timezone))

    @classmethod
    def duration(cls, timeunit: "TimeUnit | str" = TimeUnit.us):
        tu = TimeUnit.from_str(timeunit) if isinstance(timeunit, str) else timeunit
        return cls(_Kind.DURATION, (tu,))

    @classmethod
    def interval(cls): return cls(_Kind.INTERVAL)

    @classmethod
    def list(cls, dtype: "DataType"):
        return cls(_Kind.LIST, (dtype,))

    @classmethod
    def fixed_size_list(cls, dtype: "DataType", size: int):
        return cls(_Kind.FIXED_SIZE_LIST, (dtype, size))

    @classmethod
    def struct(cls, fields: "dict[str, DataType]"):
        return cls(_Kind.STRUCT, (tuple(sorted_items(fields)),))

    @classmethod
    def map(cls, key_type: "DataType", value_type: "DataType"):
        return cls(_Kind.MAP, (key_type, value_type))

    @classmethod
    def embedding(cls, dtype: "DataType", size: int):
        return cls(_Kind.EMBEDDING, (dtype, size))

    @classmethod
    def image(cls, mode: "str | ImageMode | None" = None):
        m = ImageMode.from_mode_string(mode) if isinstance(mode, str) else mode
        return cls(_Kind.IMAGE, (m,))

    @classmethod
    def fixed_shape_image(cls, mode: "str | ImageMode", height: int, width: int):
        m = ImageMode.from_mode_string(mode) if isinstance(mode, str) else mode
        return cls(_Kind.FIXED_SHAPE_IMAGE, (m, height, width))

    @classmethod
    def tensor(cls, dtype: "DataType", shape: Optional[Tuple[int, ...]] = None):
        if shape is not None:
            return cls(_Kind.FIXED_SHAPE_TENSOR, (dtype, tuple(shape)))
        return cls(_Kind.TENSOR, (dtype,))

    @classmethod
    def sparse_tensor(cls, dtype: "DataType", shape: Optional[Tuple[int, ...]] = None,
                      use_offset_indices: builtins.bool = False):
        if shape is not None:
            return cls(_Kind.FIXED_SHAPE_SPARSE_TENSOR,
                       (dtype, tuple(shape), use_offset_indices))
        return cls(_Kind.SPARSE_TENSOR, (dtype, use_offset_indices))

    @classmethod
    def python(cls): return cls(_Kind.PYTHON)

    @classmethod
    def extension(cls, name: str, storage: "DataType", metadata: Optional[str] = None):
        return cls(_Kind.EXTENSION, (name, storage, metadata))

    # ---- inspection ------------------------------------------------------
    @property
    def kind(self) -> str:
        return self._kind.value

    def is_null(self): return self._kind == _Kind.NULL
    def is_boolean(self): return self._kind == _Kind.BOOL
    def is_numeric(self): return self._kind in _NUMERIC_KINDS
    def is_integer(self): return self._kind in _INTEGER_KINDS

    def is_signed_integer(self):
        return self._kind in (_Kind.INT8, _Kind.INT16, _Kind.INT32, _Kind.INT64)

    def is_unsigned_integer(self):
        return self._kind in (_Kind.UINT8, _Kind.UINT16, _Kind.UINT32, _Kind.UINT64)

    def is_floating(self):
        return self._kind in (_Kind.FLOAT32, _Kind.FLOAT64)

    def is_temporal(self): return self._kind in _TEMPORAL_KINDS
    def is_string(self): return self._kind == _Kind.STRING
    def is_binary(self): return self._kind == _Kind.BINARY
    def is_list(self): return self._kind in (_Kind.LIST, _Kind.FIXED_SIZE_LIST)
    def is_struct(self): return self._kind == _Kind.STRUCT
    def is_map(self): return self._kind == _Kind.MAP
    def is_python(self): return self._kind == _Kind.PYTHON
    def is_decimal(self): return self._kind == _Kind.DECIMAL128

    def is_image(self):
        return self._kind in (_Kind.IMAGE, _Kind.FIXED_SHAPE_IMAGE)

    def is_tensor(self):
        return self._kind in (_Kind.TENSOR, _Kind.FIXED_SHAPE_TENSOR)

    def is_sparse_tensor(self):
        return self._kind in (_Kind.SPARSE_TENSOR, _Kind.FIXED_SHAPE_SPARSE_TENSOR)

    def is_embedding(self): return self._kind == _Kind.EMBEDDING

    def is_nested(self):
        return self._kind in (
            _Kind.LIST, _Kind.FIXED_SIZE_LIST, _Kind.STRUCT, _Kind.MAP,
            _Kind.EMBEDDING, _Kind.IMAGE, _Kind.FIXED_SHAPE_IMAGE, _Kind.TENSOR,
            _Kind.FIXED_SHAPE_TENSOR, _Kind.SPARSE_TENSOR,
            _Kind.FIXED_SHAPE_SPARSE_TENSOR,
        )

    @property
    def inner(self) -> "DataType":
        """Element type of list/fixed-size-list/embedding/tensor types."""
        if self._kind in (_Kind.LIST, _Kind.FIXED_SIZE_LIST, _Kind.EMBEDDING,
                          _Kind.TENSOR, _Kind.FIXED_SHAPE_TENSOR,
                          _Kind.SPARSE_TENSOR, _Kind.FIXED_SHAPE_SPARSE_TENSOR):
            return self._params[0]
        raise ValueError(f"{self} has no inner type")

    @property
    def size(self) -> int:
        if self._kind in (_Kind.FIXED_SIZE_LIST, _Kind.EMBEDDING):
            return self._params[1]
        if self._kind == _Kind.FIXED_SIZE_BINARY:
            return self._params[0]
        raise ValueError(f"{self} has no fixed size")

    @property
    def shape(self) -> Tuple[int, ...]:
        if self._kind == _Kind.FIXED_SHAPE_TENSOR:
            return self._params[1]
        if self._kind == _Kind.FIXED_SHAPE_IMAGE:
            return self._params[1:]
        raise ValueError(f"{self} has no fixed shape")

    @property
    def image_mode(self) -> Optional[ImageMode]:
        if self._kind in (_Kind.IMAGE, _Kind.FIXED_SHAPE_IMAGE):
            return self._params[0]
        raise ValueError(f"{self} is not an image type")

    @property
    def precision(self) -> int:
        assert self._kind == _Kind.DECIMAL128
        return self._params[0]

    @property
    def scale(self) -> int:
        assert self._kind == _Kind.DECIMAL128
        return self._params[1]

    @property
    def timeunit(self) -> TimeUnit:
        assert self._kind in (_Kind.TIME, _Kind.TIMESTAMP, _Kind.DURATION)
        return self._params[0]

    @property
    def timezone(self) -> Optional[str]:
        assert self._kind == _Kind.TIMESTAMP
        return self._params[1]

    @property
    def fields(self) -> "dict[str, DataType]":
        assert self._kind == _Kind.STRUCT
        return dict(self._params[0])

    # ---- physical lowering ----------------------------------------------
    def to_physical(self) -> "DataType":
        """Lower a logical type to its physical storage type.

        Mirrors the mapping in the reference (``dtype.rs:307-335``): Image →
        Struct{data: List[u8|u16|f32], channel/height/width: u16, mode: u8};
        Tensor → Struct{data: List[inner], shape: List[u64]}; Embedding →
        FixedSizeList; Date → int32; Timestamp/Duration/Time → int64.
        """
        k = self._kind
        if k == _Kind.DATE:
            return DataType.int32()
        if k in (_Kind.TIMESTAMP, _Kind.DURATION, _Kind.TIME):
            return DataType.int64()
        if k == _Kind.EMBEDDING:
            return DataType.fixed_size_list(self._params[0].to_physical(), self._params[1])
        if k == _Kind.IMAGE:
            mode = self._params[0]
            data_dt = (DataType.from_numpy_dtype(mode.np_dtype)
                       if mode is not None else DataType.uint8())
            return DataType.struct({
                "data": DataType.list(data_dt),
                "channel": DataType.uint16(),
                "height": DataType.uint32(),
                "width": DataType.uint32(),
                "mode": DataType.uint8(),
            })
        if k == _Kind.FIXED_SHAPE_IMAGE:
            mode, h, w = self._params
            return DataType.fixed_size_list(
                DataType.from_numpy_dtype(mode.np_dtype), h * w * mode.num_channels)
        if k == _Kind.TENSOR:
            return DataType.struct({
                "data": DataType.list(self._params[0].to_physical()),
                "shape": DataType.list(DataType.uint64()),
            })
        if k == _Kind.FIXED_SHAPE_TENSOR:
            dt, shape = self._params
            n = int(np.prod(shape)) if shape else 1
            return DataType.fixed_size_list(dt.to_physical(), n)
        if k == _Kind.SPARSE_TENSOR:
            return DataType.struct({
                "values": DataType.list(self._params[0].to_physical()),
                "indices": DataType.list(DataType.uint64()),
                "shape": DataType.list(DataType.uint64()),
            })
        if k == _Kind.FIXED_SHAPE_SPARSE_TENSOR:
            return DataType.struct({
                "values": DataType.list(self._params[0].to_physical()),
                "indices": DataType.list(DataType.uint64()),
            })
        if k == _Kind.EXTENSION:
            return self._params[1].to_physical()
        return self

    # ---- device lowering -------------------------------------------------
    def device_repr(self) -> Optional[np.dtype]:
        """The JAX/numpy dtype this column uses on TPU, or None if host-only.

        Strings/binary lower to int32 dictionary codes; bool stays bool;
        temporal types lower via to_physical; nested/python stay on host
        (None) except fixed-shape tensors/embeddings which lower to [N, prod]
        arrays of their inner dtype.
        """
        k = self._kind
        if k in (_Kind.STRING, _Kind.BINARY):
            return np.dtype(np.int32)  # dictionary code plane
        if k == _Kind.BOOL:
            return np.dtype(np.bool_)
        if k == _Kind.NULL:
            return np.dtype(np.bool_)
        if self.is_numeric() and k != _Kind.DECIMAL128:
            return np.dtype(self.kind)
        if k == _Kind.DECIMAL128:
            return np.dtype(np.float64)  # approximate device compute plane
        if self.is_temporal():
            return self.to_physical().device_repr()
        if k in (_Kind.EMBEDDING, _Kind.FIXED_SHAPE_TENSOR, _Kind.FIXED_SHAPE_IMAGE):
            inner = self._params[0]
            if k == _Kind.FIXED_SHAPE_IMAGE:
                return self._params[0].np_dtype
            return inner.device_repr()
        return None

    def is_device_representable(self) -> builtins.bool:
        return self.device_repr() is not None

    # ---- arrow interop ---------------------------------------------------
    def to_arrow(self) -> pa.DataType:
        k = self._kind
        simple = {
            _Kind.NULL: pa.null(), _Kind.BOOL: pa.bool_(),
            _Kind.INT8: pa.int8(), _Kind.INT16: pa.int16(),
            _Kind.INT32: pa.int32(), _Kind.INT64: pa.int64(),
            _Kind.UINT8: pa.uint8(), _Kind.UINT16: pa.uint16(),
            _Kind.UINT32: pa.uint32(), _Kind.UINT64: pa.uint64(),
            _Kind.FLOAT32: pa.float32(), _Kind.FLOAT64: pa.float64(),
            _Kind.STRING: pa.large_string(), _Kind.BINARY: pa.large_binary(),
            _Kind.DATE: pa.date32(),
        }
        if k in simple:
            return simple[k]
        if k == _Kind.DECIMAL128:
            return pa.decimal128(*self._params)
        if k == _Kind.FIXED_SIZE_BINARY:
            return pa.binary(self._params[0])
        if k == _Kind.TIME:
            return pa.time64(self._params[0].value)
        if k == _Kind.TIMESTAMP:
            return pa.timestamp(self._params[0].value, tz=self._params[1])
        if k == _Kind.DURATION:
            return pa.duration(self._params[0].value)
        if k == _Kind.INTERVAL:
            return pa.month_day_nano_interval()
        if k == _Kind.LIST:
            return pa.large_list(self._params[0].to_arrow())
        if k == _Kind.FIXED_SIZE_LIST:
            return pa.list_(self._params[0].to_arrow(), self._params[1])
        if k == _Kind.STRUCT:
            return pa.struct([(n, t.to_arrow()) for n, t in self._params[0]])
        if k == _Kind.MAP:
            return pa.map_(self._params[0].to_arrow(), self._params[1].to_arrow())
        if k in (_Kind.EMBEDDING, _Kind.IMAGE, _Kind.FIXED_SHAPE_IMAGE, _Kind.TENSOR,
                 _Kind.FIXED_SHAPE_TENSOR, _Kind.SPARSE_TENSOR,
                 _Kind.FIXED_SHAPE_SPARSE_TENSOR):
            return self.to_physical().to_arrow()
        if k == _Kind.EXTENSION:
            return self._params[1].to_arrow()
        raise NotImplementedError(f"to_arrow for {self}")

    @classmethod
    def from_arrow_type(cls, t: pa.DataType) -> "DataType":
        if pa.types.is_null(t): return cls.null()
        if pa.types.is_boolean(t): return cls.bool()
        if pa.types.is_int8(t): return cls.int8()
        if pa.types.is_int16(t): return cls.int16()
        if pa.types.is_int32(t): return cls.int32()
        if pa.types.is_int64(t): return cls.int64()
        if pa.types.is_uint8(t): return cls.uint8()
        if pa.types.is_uint16(t): return cls.uint16()
        if pa.types.is_uint32(t): return cls.uint32()
        if pa.types.is_uint64(t): return cls.uint64()
        if pa.types.is_float16(t): return cls.float32()
        if pa.types.is_float32(t): return cls.float32()
        if pa.types.is_float64(t): return cls.float64()
        if pa.types.is_decimal(t): return cls.decimal128(t.precision, t.scale)
        if pa.types.is_string(t) or pa.types.is_large_string(t) or \
           pa.types.is_string_view(t):
            return cls.string()
        if pa.types.is_fixed_size_binary(t): return cls.fixed_size_binary(t.byte_width)
        if pa.types.is_binary(t) or pa.types.is_large_binary(t) or \
           pa.types.is_binary_view(t):
            return cls.binary()
        if pa.types.is_date32(t) or pa.types.is_date64(t): return cls.date()
        if pa.types.is_time32(t) or pa.types.is_time64(t):
            return cls.time(TimeUnit.from_str(t.unit) if t.unit in ("us", "ns") else TimeUnit.us)
        if pa.types.is_timestamp(t): return cls.timestamp(TimeUnit.from_str(t.unit), t.tz)
        if pa.types.is_duration(t): return cls.duration(TimeUnit.from_str(t.unit))
        if pa.types.is_interval(t): return cls.interval()
        if pa.types.is_fixed_size_list(t):
            return cls.fixed_size_list(cls.from_arrow_type(t.value_type), t.list_size)
        if pa.types.is_list(t) or pa.types.is_large_list(t) or pa.types.is_list_view(t):
            return cls.from_arrow_type(t.value_type).as_list()
        if pa.types.is_map(t):
            return cls.map(cls.from_arrow_type(t.key_type), cls.from_arrow_type(t.item_type))
        if pa.types.is_struct(t):
            return cls.struct({f.name: cls.from_arrow_type(f.type) for f in t})
        if pa.types.is_dictionary(t):
            return cls.from_arrow_type(t.value_type)
        raise NotImplementedError(f"from_arrow_type for {t}")

    def as_list(self) -> "DataType":
        return DataType.list(self)

    @classmethod
    def from_numpy_dtype(cls, dt) -> "DataType":
        dt = np.dtype(dt)
        m = {
            "b": cls.bool, "i1": cls.int8, "i2": cls.int16, "i4": cls.int32,
            "i8": cls.int64, "u1": cls.uint8, "u2": cls.uint16, "u4": cls.uint32,
            "u8": cls.uint64, "f4": cls.float32, "f8": cls.float64,
        }
        key = dt.kind if dt.kind == "b" else dt.kind + str(dt.itemsize)
        if key in m:
            return m[key]()
        if dt.kind == "U" or dt.kind == "O":
            return cls.string()
        if dt.kind == "M":
            return cls.timestamp(TimeUnit.us)
        raise NotImplementedError(f"from_numpy_dtype for {dt}")

    @classmethod
    def infer_from_pylist(cls, values) -> "DataType":
        arr = pa.array(values)
        return cls.from_arrow_type(arr.type)

    # ---- dunder ----------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, DataType) and self._kind == other._kind
                and self._params == other._params)

    def __hash__(self):
        return hash((self._kind, self._params))

    def __repr__(self):
        k = self._kind
        if not self._params:
            return k.value.capitalize() if k != _Kind.NULL else "Null"
        if k == _Kind.DECIMAL128:
            return f"Decimal128({self._params[0]}, {self._params[1]})"
        if k == _Kind.LIST:
            return f"List[{self._params[0]!r}]"
        if k == _Kind.FIXED_SIZE_LIST:
            return f"FixedSizeList[{self._params[0]!r}; {self._params[1]}]"
        if k == _Kind.STRUCT:
            inner = ", ".join(f"{n}: {t!r}" for n, t in self._params[0])
            return f"Struct[{inner}]"
        if k == _Kind.MAP:
            return f"Map[{self._params[0]!r}: {self._params[1]!r}]"
        if k == _Kind.EMBEDDING:
            return f"Embedding[{self._params[0]!r}; {self._params[1]}]"
        if k == _Kind.IMAGE:
            m = self._params[0]
            return f"Image[{m.name}]" if m else "Image[MIXED]"
        if k == _Kind.FIXED_SHAPE_IMAGE:
            m, h, w = self._params
            return f"Image[{m.name}; {h} x {w}]"
        if k == _Kind.TENSOR:
            return f"Tensor({self._params[0]!r})"
        if k == _Kind.FIXED_SHAPE_TENSOR:
            return f"FixedShapeTensor[{self._params[0]!r}; {self._params[1]}]"
        if k == _Kind.TIMESTAMP:
            return f"Timestamp({self._params[0].value}, {self._params[1]})"
        if k in (_Kind.TIME, _Kind.DURATION):
            return f"{k.value.capitalize()}({self._params[0].value})"
        return f"{k.value}({self._params})"


def sorted_items(d: "dict[str, DataType]"):
    # struct fields keep insertion order (like the reference's IndexMap)
    return tuple(d.items())
