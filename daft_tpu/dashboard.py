"""Embedded query dashboard.

Reference: ``src/daft-dashboard`` — a localhost HTTP server receiving
broadcast query plans + timings (``lib.rs:28-60``, launched via
``daft.dashboard.launch()`` / DAFT_DASHBOARD). Here the server renders the
engine's own runtime stats: recent queries with per-operator rows/timings
(observability.RuntimeStatsContext) and HBM/IO counters, as plain HTML —
no bundled frontend, same surface.
"""

from __future__ import annotations

import html
import http.server
import json
import threading
import time
from typing import List, Optional

DEFAULT_PORT = 3238

_history_lock = threading.Lock()
_history: List[dict] = []
_history_bytes: List[int] = []  # parallel to _history: entry JSON sizes
#: broadcast-history bounds — BOTH apply: a count cap and a byte cap
#: (one query with a giant explain must not let 49 more like it pin
#: hundreds of MB in a long-lived --serve process)
_MAX_HISTORY = 50
_MAX_HISTORY_BYTES = 4 << 20
_server: Optional[http.server.ThreadingHTTPServer] = None


def broadcast_query(stats) -> None:
    """Record a finished query's stats for the dashboard (called by the
    runner; reference hook: ``DataFrame._broadcast_query_plan``)."""
    try:
        entry = {
            "ts": time.strftime("%H:%M:%S"),
            "operators": stats.as_dict(),
            "explain": stats.render(getattr(stats, "plan", None)),
            # resilience plane: recovery events (retries, quarantines,
            # recomputed map tasks, speculative wins…) for this query
            "recovery": dict(getattr(stats, "recovery", {}) or {}),
            # shuffle data plane: bytes written/fetched, compression
            # ratio inputs, combine reduction, fetch overlap
            "shuffle": dict(getattr(stats, "shuffle", {}) or {}),
            # scan-side IO plane: GETs vs planned ranges (coalescing),
            # bytes fetched vs used, prefetch overlap
            "io": dict(getattr(stats, "io", {}) or {}),
            # device kernels: per-family dispatch/byte/MFU ledger delta,
            # incl. the hash-vs-sort strategy + table load factor (r12)
            "device_kernels": dict(
                getattr(stats, "device_kernels", {}) or {}),
            # self-tuning feedback plane (r20): calibration observations
            # + runtime re-plan decisions this query made
            "adaptive": dict(getattr(stats, "adaptive", {}) or {}),
            # lock-order sanitizer (DAFT_TPU_SANITIZE=1): graph size,
            # cycles, per-query contention/blocking events
            "sanitizer": dict(getattr(stats, "sanitizer", {}) or {}),
            # serving plane: session/priority/queue-wait/admission and
            # plan/result cache outcomes for scheduler-run queries
            "serving": dict(getattr(stats, "serving", {}) or {}),
            # tracing plane: merged-trace summary (id, span count)
            "trace": dict(getattr(stats, "trace_summary", {}) or {}),
        }
        size = len(json.dumps(entry, default=str))
    except Exception:
        return
    with _history_lock:
        _history.append(entry)
        _history_bytes.append(size)
        # count cap, then byte cap: evict oldest-first until both hold
        while len(_history) > _MAX_HISTORY \
                or (sum(_history_bytes) > _MAX_HISTORY_BYTES
                    and len(_history) > 1):
            _history.pop(0)
            _history_bytes.pop(0)


def _serving_view() -> dict:
    """Live scheduler state for the dashboard (never boots a scheduler,
    never raises — an idle process just shows an empty view)."""
    try:
        from . import serving
        sched = serving.shared_scheduler_if_running()
        if sched is None:
            return {}
        return sched.live_view()
    except Exception:
        return {}


def _fleet_view() -> dict:
    """Live fleet state when this process hosts the router: per-replica
    gauges, the aggregate, the autoscaling signal and the gossiped
    state-store generations (empty when no router is installed)."""
    try:
        from . import fleet
        router = fleet.installed_router()
        if router is None:
            return {}
        out = router.gauges()
        out["scale_signal"] = router.scale_signal()
        out["assignments"] = len(router.assignments())
        from .fleet import state_sync
        out["counters"] = state_sync.counters_snapshot()
        st = state_sync.installed()
        if st is not None:
            out["state"] = st.view()
        return out
    except Exception:
        return {}


class _Handler(http.server.BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _reply(self, body: bytes, ctype: str) -> None:
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.startswith("/metrics"):
            # Prometheus text-format scrape: process-wide serving /
            # shuffle / io / recovery / kernel counters + queue-depth
            # and cache-hit-rate gauges
            from . import tracing
            self._reply(tracing.prometheus_text().encode(),
                        "text/plain; version=0.0.4")
            return
        if self.path.startswith("/api/history"):
            # flight-recorder history (DAFT_TPU_QUERY_LOG JSONL)
            from . import tracing
            self._reply(json.dumps(tracing.flight_history()).encode(),
                        "application/json")
            return
        if self.path.startswith("/api/serving"):
            body = json.dumps(_serving_view()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.startswith("/api/fleet"):
            self._reply(json.dumps(_fleet_view()).encode(),
                        "application/json")
            return
        if self.path.startswith("/api/queries"):
            with _history_lock:
                body = json.dumps(_history).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        live = _serving_view()
        live_html = ""
        if live:
            sess = live.get("sessions") or {}
            sess_rows = "".join(
                f"<tr><td>{html.escape(str(n))}</td>"
                f"<td>{s.get('queued')}</td><td>{s.get('weight')}</td></tr>"
                for n, s in sorted(sess.items()))
            live_html = (
                "<h2>serving queue (live)</h2>"
                f"<p>running {live.get('running', 0)}/"
                f"{live.get('concurrency', 0)} · queued "
                f"{live.get('queued', 0)} · admitted "
                f"{live.get('admitted_bytes', 0)} / "
                f"{live.get('admission_budget')} bytes</p>"
                + ("<table border=1><tr><th>session</th><th>queued</th>"
                   "<th>weight</th></tr>" + sess_rows + "</table>"
                   if sess_rows else ""))
        rows = []
        with _history_lock:
            for i, q in enumerate(reversed(_history)):
                srv = q.get("serving") or {}
                srv_html = ("<p><b>serving:</b> "
                            + html.escape(json.dumps(srv, default=str))
                            + "</p>" if srv else "")
                rec = q.get("recovery") or {}
                rec_html = ("<p><b>recovery events:</b> "
                            + html.escape(json.dumps(rec)) + "</p>"
                            if rec else "")
                shf = q.get("shuffle") or {}
                shf_html = ("<p><b>shuffle:</b> "
                            + html.escape(json.dumps(
                                {k: round(v, 1) for k, v in shf.items()}))
                            + "</p>" if shf else "")
                sio = q.get("io") or {}
                io_html = ("<p><b>io:</b> "
                           + html.escape(json.dumps(
                               {k: round(v, 1) for k, v in sio.items()}))
                           + "</p>" if sio else "")
                san = q.get("sanitizer") or {}
                san_html = ("<p><b>lock sanitizer:</b> "
                            + html.escape(json.dumps(
                                {k: round(v, 1) for k, v in san.items()}))
                            + "</p>" if san else "")
                rows.append(
                    f"<h3>query {len(_history) - i} — {q['ts']}</h3>"
                    f"{srv_html}{rec_html}{shf_html}{io_html}{san_html}"
                    f"<pre>{html.escape(q['explain'])}</pre>")
        # flight-recorder history view (persisted across restarts, unlike
        # the in-memory broadcast list above)
        hist_html = ""
        try:
            from . import tracing
            entries = tracing.flight_history(limit=20)
        except Exception:
            entries = []
        if entries:
            hist_rows = "".join(
                f"<tr><td>{html.escape(str(e.get('ts')))}</td>"
                f"<td>{float(e.get('wall_us', 0)) / 1e3:.1f}ms</td>"
                f"<td>{'SLOW' if e.get('slow') else ''}</td>"
                f"<td>{html.escape(str((e.get('trace') or {}).get('trace_id', '')))}</td>"
                f"</tr>" for e in entries)
            hist_html = ("<h2>query history (flight recorder)</h2>"
                         "<table border=1><tr><th>ts</th><th>wall</th>"
                         "<th>slow</th><th>trace</th></tr>"
                         + hist_rows + "</table>")
        body = ("<html><head><title>daft-tpu dashboard</title></head><body>"
                "<h1>daft-tpu queries</h1>" + live_html + hist_html
                + ("".join(rows) or "<p>no queries yet</p>")
                + "</body></html>").encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


_server_lock = threading.Lock()


def launch(port: int = DEFAULT_PORT, block: bool = False) -> int:
    """Start the dashboard server; returns the bound port."""
    global _server
    with _server_lock:
        if _server is None:
            _server = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                      _Handler)
            t = threading.Thread(target=_server.serve_forever, daemon=True,
                                 name="daft-tpu-dashboard")
            t.start()
        srv = _server
    if block:
        try:
            while _server is srv:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return srv.server_port


def shutdown() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()  # release the listening socket
            _server = None
