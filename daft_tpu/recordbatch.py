"""RecordBatch: schema + equal-length Series, with the relational kernel surface.

Capability mirror of the reference's ``daft-recordbatch``
(``src/daft-recordbatch/src/lib.rs:63`` and kernels in ``ops/``: agg, joins,
sort, partition, explode, pivot/unpivot). Two execution tiers:

- host tier here, over Arrow C++ compute (``pa.TableGroupBy``, ``Table.join``,
  ``pc.sort_indices`` — all native C++);
- TPU tier in ``daft_tpu.device`` — jit-compiled XLA kernels used by the
  streaming executor for the device-representable hot path (project/filter,
  sort-based groupby-agg, sort, sort-merge join).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .datatype import DataType
from .expressions import Expression, col
from .expressions.evaluator import eval_expression
from .schema import Field, Schema
from .series import Series


class RecordBatch:
    __slots__ = ("_schema", "_columns", "_len")

    def __init__(self, schema: Schema, columns: List[Series], length: int):
        self._schema = schema
        self._columns = columns
        self._len = length

    # ---- constructors ----------------------------------------------------
    @classmethod
    def from_series(cls, columns: List[Series]) -> "RecordBatch":
        if not columns:
            return cls.empty()
        n = max(len(c) for c in columns)
        columns = [c.broadcast(n) if len(c) == 1 and n != 1 else c for c in columns]
        assert all(len(c) == n for c in columns), "column length mismatch"
        return cls(Schema([c.field() for c in columns]), columns, n)

    @classmethod
    def from_pydict(cls, data: Dict[str, Any]) -> "RecordBatch":
        cols = []
        for name, v in data.items():
            if isinstance(v, Series):
                cols.append(v.rename(name))
            elif isinstance(v, np.ndarray):
                cols.append(Series.from_numpy(v, name))
            elif isinstance(v, (pa.Array, pa.ChunkedArray)):
                cols.append(Series.from_arrow(v, name))
            else:
                cols.append(Series.from_pylist(list(v), name))
        return cls.from_series(cols)

    @classmethod
    def from_arrow_table(cls, t: pa.Table) -> "RecordBatch":
        cols = [Series.from_arrow(t.column(i), t.column_names[i])
                for i in range(t.num_columns)]
        if not cols:
            b = cls.empty()
            return cls(b._schema, b._columns, t.num_rows)
        return cls.from_series(cols)

    @classmethod
    def from_arrow_record_batch(cls, rb: pa.RecordBatch) -> "RecordBatch":
        return cls.from_arrow_table(pa.Table.from_batches([rb]))

    @classmethod
    def empty(cls, schema: Optional[Schema] = None) -> "RecordBatch":
        schema = schema or Schema.empty()
        return cls(schema, [Series.empty(f.name, f.dtype) for f in schema], 0)

    # ---- basic -----------------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._schema

    def __len__(self) -> int:
        return self._len

    def num_columns(self) -> int:
        return len(self._columns)

    def column_names(self) -> List[str]:
        return self._schema.column_names

    def get_column(self, name: str) -> Series:
        return self._columns[self._schema.index_of(name)]

    def columns(self) -> List[Series]:
        return list(self._columns)

    def size_bytes(self) -> int:
        total = 0
        for c in self._columns:
            if c.is_pyobject():
                total += len(c) * 64
            else:
                total += c.to_arrow().nbytes
        return total

    # ---- conversions -----------------------------------------------------
    def to_arrow_table(self) -> pa.Table:
        arrays, fields = [], []
        for c in self._columns:
            if c.is_pyobject():
                raise ValueError(
                    f"cannot convert Python-object column {c.name()!r} to arrow")
            arrays.append(c.to_arrow())
            fields.append(c.field().to_arrow())
        if not arrays:
            return pa.table({})
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))

    def to_pydict(self) -> Dict[str, list]:
        return {c.name(): c.to_pylist() for c in self._columns}

    def to_pandas(self):
        import pandas as pd
        data = {c.name(): (c.to_pylist() if c.is_pyobject()
                           else c.to_arrow().to_pandas()) for c in self._columns}
        return pd.DataFrame(data)

    # ---- expression eval -------------------------------------------------
    def _cols_dict(self) -> Dict[str, Series]:
        return {c.name(): c for c in self._columns}

    def eval_expression_list(self, exprs: Sequence[Expression]) -> "RecordBatch":
        """Evaluate a projection; uses the TPU tier when the whole projection
        is device-representable (see device.compiler), else Arrow host compute."""
        from .device import runtime as device_runtime
        out = device_runtime.try_eval_projection(self, list(exprs))
        if out is not None:
            return out
        cols = self._cols_dict()
        return RecordBatch.from_series(
            [eval_expression(e, cols, self._len) for e in exprs])

    def eval_expression(self, e: Expression) -> Series:
        return eval_expression(e, self._cols_dict(), self._len)

    # ---- row selection ---------------------------------------------------
    def filter(self, predicate: Union[Expression, Series]) -> "RecordBatch":
        if isinstance(predicate, Expression):
            from .device import runtime as device_runtime
            m_np = device_runtime.try_eval_predicate(self, predicate)
            if m_np is not None:
                mask = Series.from_arrow(pa.array(m_np), "mask")
            else:
                mask = self.eval_expression(predicate)
        else:
            mask = predicate
        m = pc.fill_null(mask.to_arrow().cast(pa.bool_()), False)
        return RecordBatch(self._schema,
                           [c.filter(Series.from_arrow(m, "m")) for c in self._columns],
                           int(pc.sum(m).as_py() or 0))

    def take(self, indices: Union[Series, np.ndarray]) -> "RecordBatch":
        idx = indices.to_numpy() if isinstance(indices, Series) else np.asarray(indices)
        return RecordBatch(self._schema, [c.take(idx) for c in self._columns],
                           len(idx))

    def slice(self, start: int, end: int) -> "RecordBatch":
        cols = [c.slice(start, end) for c in self._columns]
        return RecordBatch(self._schema, cols, len(cols[0]) if cols else 0)

    def head(self, n: int) -> "RecordBatch":
        return self.slice(0, n)

    def sample(self, fraction: Optional[float] = None, size: Optional[int] = None,
               with_replacement: bool = False, seed: Optional[int] = None) -> "RecordBatch":
        k = int(self._len * fraction) if fraction is not None else int(size or 0)
        rng = np.random.default_rng(seed)
        if with_replacement:
            idx = rng.integers(0, max(self._len, 1), size=k)
        else:
            k = min(k, self._len)
            idx = rng.permutation(self._len)[:k]
        return self.take(np.sort(idx))

    @classmethod
    def concat(cls, batches: List["RecordBatch"]) -> "RecordBatch":
        batches = [b for b in batches]
        assert batches, "concat of empty list"
        first = batches[0]
        if len(batches) == 1:
            return first
        cols = []
        for i, f in enumerate(first._schema):
            cols.append(Series.concat([b._columns[b._schema.index_of(f.name)]
                                       for b in batches]))
        return cls(first._schema, cols, sum(len(b) for b in batches))

    def union(self, other: "RecordBatch") -> "RecordBatch":
        assert len(self) == len(other)
        return RecordBatch.from_series(self._columns + other._columns)

    # ---- sort ------------------------------------------------------------
    def argsort(self, sort_keys: Sequence[Expression],
                descending: Optional[Sequence[bool]] = None,
                nulls_first: Optional[Sequence[bool]] = None) -> np.ndarray:
        ks = [self.eval_expression(e) for e in sort_keys]
        descending = descending or [False] * len(ks)
        nulls_first = nulls_first or list(descending)
        from .device import runtime as device_runtime
        idx = device_runtime.try_argsort(ks, descending, nulls_first)
        if idx is not None:
            return idx
        # emulate per-key null placement with an explicit null-rank plane per key
        cols, keys = {}, []
        for i, (k, d, nf) in enumerate(zip(ks, descending, nulls_first)):
            arr = k.to_arrow()
            cols[f"n{i}"] = pc.if_else(pc.is_valid(arr),
                                       pa.scalar(1 if nf else 0, pa.int8()),
                                       pa.scalar(0 if nf else 1, pa.int8()))
            cols[f"k{i}"] = arr
            keys.append((f"n{i}", "ascending"))
            keys.append((f"k{i}", "descending" if d else "ascending"))
        tbl = pa.table(cols)
        out = pc.sort_indices(tbl, sort_keys=keys, null_placement="at_end")
        return out.to_numpy()

    def sort(self, sort_keys: Sequence[Expression],
             descending: Optional[Sequence[bool]] = None,
             nulls_first: Optional[Sequence[bool]] = None) -> "RecordBatch":
        return self.take(self.argsort(sort_keys, descending, nulls_first))

    def top_n(self, sort_keys: Sequence[Expression], n: int,
              descending: Optional[Sequence[bool]] = None,
              nulls_first: Optional[Sequence[bool]] = None) -> "RecordBatch":
        idx = self.argsort(sort_keys, descending, nulls_first)[:n]
        return self.take(idx)

    # ---- aggregation -----------------------------------------------------
    def agg(self, to_agg: Sequence[Expression],
            group_by: Sequence[Expression] = ()) -> "RecordBatch":
        """Global or grouped aggregation.

        Device path: sort-based segment aggregation (device.kernels.groupby).
        Host path: Arrow C++ ``TableGroupBy``.
        Mirrors ``src/daft-recordbatch/src/ops/agg.rs:12-29``.
        """
        from .aggs import agg_recordbatch
        return agg_recordbatch(self, list(to_agg), list(group_by))

    def distinct(self, on: Optional[Sequence[Expression]] = None) -> "RecordBatch":
        on = list(on) if on else [col(n) for n in self.column_names()]
        keys = RecordBatch.from_series(
            [self.eval_expression(e) for e in on])
        tbl = keys.to_arrow_table()
        # group-by all key cols with a first-row index agg
        tbl = tbl.append_column("__row__", pa.array(np.arange(self._len)))
        g = tbl.group_by([c for c in tbl.column_names if c != "__row__"],
                         use_threads=False)
        first = g.aggregate([("__row__", "min")])
        idx = first.column("__row___min").to_numpy()
        return self.take(np.sort(idx))

    def pivot(self, group_by: Sequence[Expression], pivot_col: Expression,
              value_col: Expression, names: List[str]) -> "RecordBatch":
        from .aggs import pivot_recordbatch
        return pivot_recordbatch(self, list(group_by), pivot_col, value_col, names)

    def unpivot(self, ids: Sequence[Expression], values: Sequence[Expression],
                variable_name: str = "variable",
                value_name: str = "value") -> "RecordBatch":
        id_batch = RecordBatch.from_series([self.eval_expression(e) for e in ids])
        val_series = [self.eval_expression(e) for e in values]
        out_dt = val_series[0].datatype()
        for v in val_series[1:]:
            from .expressions.typing import supertype
            out_dt = supertype(out_dt, v.datatype())
        parts = []
        for v in val_series:
            b = RecordBatch.from_series(
                id_batch._columns
                + [Series.from_pylist([v.name()] * self._len, variable_name),
                   v.cast(out_dt).rename(value_name)])
            parts.append(b)
        return RecordBatch.concat(parts)

    # ---- explode ---------------------------------------------------------
    def explode(self, exprs: Sequence[Expression]) -> "RecordBatch":
        """Explode list columns to one row per element
        (reference: ``src/daft-recordbatch/src/ops/explode.rs``)."""
        exploded = []
        for e in exprs:
            inner = e._unalias()
            assert inner.op == "explode", "explode expects .explode() expressions"
            s = self.eval_expression(inner.args[0]).rename(e.name())
            exploded.append(s)
        arr0 = exploded[0].to_arrow()
        lengths = pc.list_value_length(arr0)
        lengths_np = pc.fill_null(lengths, 1).to_numpy().astype(np.int64)
        lengths_np = np.maximum(lengths_np, 1)  # null/empty lists -> 1 null row
        repeat_idx = np.repeat(np.arange(self._len), lengths_np)
        out_cols = []
        for c in self._columns:
            match = next((s for s in exploded if s.name() == c.name()), None)
            if match is not None:
                out_cols.append(_explode_series(match, lengths_np))
            else:
                out_cols.append(c.take(repeat_idx))
        for s in exploded:
            if s.name() not in self._schema:
                out_cols.append(_explode_series(s, lengths_np))
        return RecordBatch.from_series(out_cols)

    # ---- joins -----------------------------------------------------------
    def hash_join(self, right: "RecordBatch", left_on: Sequence[Expression],
                  right_on: Sequence[Expression], how: str = "inner",
                  null_equals_nulls: Optional[List[bool]] = None) -> "RecordBatch":
        from .joins import join_recordbatch
        return join_recordbatch(self, right, list(left_on), list(right_on), how)

    def sort_merge_join(self, right: "RecordBatch", left_on, right_on,
                        is_sorted: bool = False) -> "RecordBatch":
        from .joins import join_recordbatch
        return join_recordbatch(self, right, list(left_on), list(right_on), "inner")

    def cross_join(self, right: "RecordBatch") -> "RecordBatch":
        n_l, n_r = len(self), len(right)
        li = np.repeat(np.arange(n_l), n_r)
        ri = np.tile(np.arange(n_r), n_l)
        lcols = [c.take(li) for c in self._columns]
        rcols = [c.take(ri) for c in right._columns]
        return RecordBatch.from_series(lcols + rcols)

    # ---- partitioning ----------------------------------------------------
    def partition_by_hash(self, exprs: Sequence[Expression],
                          num_partitions: int) -> List["RecordBatch"]:
        """Reference: ``ops/partition.rs:53-104``."""
        if self._len == 0:
            return [self.slice(0, 0) for _ in range(num_partitions)]
        keys = [self.eval_expression(e) for e in exprs]
        h = keys[0].hash()
        for k in keys[1:]:
            h = k.hash(seed=h)
        pid = (h.to_numpy() % np.uint64(num_partitions)).astype(np.int64)
        return self._split_by_pid(pid, num_partitions)

    def partition_by_random(self, num_partitions: int, seed: int) -> List["RecordBatch"]:
        rng = np.random.default_rng(seed)
        pid = rng.integers(0, num_partitions, size=self._len)
        return self._split_by_pid(pid, num_partitions)

    def partition_by_range(self, partition_keys: Sequence[Expression],
                           boundaries: "RecordBatch",
                           descending: List[bool]) -> List["RecordBatch"]:
        keys = [self.eval_expression(e) for e in partition_keys]
        nparts = len(boundaries) + 1
        if self._len == 0:
            return [self.slice(0, 0) for _ in range(nparts)]
        pid = np.zeros(self._len, dtype=np.int64)
        for i in range(len(boundaries)):
            cmp_ge = np.zeros(self._len, dtype=bool)
            decided = np.zeros(self._len, dtype=bool)
            for j, k in enumerate(keys):
                bval = boundaries._columns[j].to_pylist()[i]
                kv = k.to_pylist()
                gt = np.array([_cmp_vals(v, bval, descending[j]) > 0 for v in kv])
                eq = np.array([_cmp_vals(v, bval, descending[j]) == 0 for v in kv])
                cmp_ge |= (~decided) & gt
                decided |= ~eq
            pid[cmp_ge] = i + 1
        return self._split_by_pid(pid, nparts)

    def partition_by_value(self, exprs: Sequence[Expression]) \
            -> Tuple[List["RecordBatch"], "RecordBatch"]:
        keys = RecordBatch.from_series([self.eval_expression(e) for e in exprs])
        tbl = keys.to_arrow_table().append_column(
            "__row__", pa.array(np.arange(self._len)))
        g = tbl.group_by([c for c in tbl.column_names if c != "__row__"],
                         use_threads=False).aggregate([("__row__", "list")])
        parts = []
        for i in range(g.num_rows):
            idx = np.asarray(g.column("__row___list")[i].as_py())
            parts.append(self.take(idx))
        pvalues = RecordBatch.from_arrow_table(g.drop_columns(["__row___list"]))
        return parts, pvalues

    def _split_by_pid(self, pid: np.ndarray, n: int) -> List["RecordBatch"]:
        from . import native
        if native.AVAILABLE:
            # single-pass C++ counting sort → gather list (stable)
            counts, order = native.fanout_pid(pid, n)
        else:
            order = np.argsort(pid, kind="stable")
            counts = np.bincount(pid, minlength=n)
        sorted_batch = self.take(order)
        offsets = np.concatenate([[0], np.cumsum(counts)])
        return [sorted_batch.slice(int(offsets[i]), int(offsets[i + 1]))
                for i in range(n)]

    # ---- misc ------------------------------------------------------------
    def add_monotonically_increasing_id(self, partition_num: int,
                                        column_name: str) -> "RecordBatch":
        """64-bit ids: upper 28 bits partition, lower 36 row index
        (reference: daft-recordbatch monotonically_increasing_id)."""
        ids = (np.uint64(partition_num) << np.uint64(36)) + \
            np.arange(self._len, dtype=np.uint64)
        s = Series.from_arrow(pa.array(ids), column_name)
        return RecordBatch.from_series([s] + self._columns)

    def cast_to_schema(self, schema: Schema) -> "RecordBatch":
        cols = []
        for f in schema:
            if f.name in self._schema:
                cols.append(self.get_column(f.name).cast(f.dtype))
            else:
                cols.append(Series.full_null(f.name, f.dtype, self._len))
        return RecordBatch(schema, cols, self._len)

    def __repr__(self):
        return repr(self.to_pandas()) if self._len <= 20 else \
            repr(self.head(10).to_pandas()) + f"\n… ({self._len} rows)"


def _explode_series(s: Series, lengths: np.ndarray) -> Series:
    arr = s.to_arrow()
    vals = arr.to_pylist()
    out = []
    for v in vals:
        if not v:
            out.append(None)
        else:
            out.extend(v)
    inner_dt = s.datatype().inner if s.datatype().is_list() else s.datatype()
    return Series.from_pylist(out, s.name(), dtype=inner_dt)


def _cmp_vals(a, b, desc: bool) -> int:
    if a is None and b is None:
        return 0
    if a is None:
        return 1 if not desc else -1
    if b is None:
        return -1 if not desc else 1
    r = (a > b) - (a < b)
    return -r if desc else r
