"""daft_tpu.catalog — Catalog / Table / Identifier abstractions.

Parity target: the reference's catalog layer (``daft/catalog/__init__.py``:
``Catalog`` ABC :74-494, ``Identifier`` :498-611, ``Table`` ABC :613-814) and
the Rust bindings registry (``src/daft-catalog``). This build keeps the whole
catalog layer host-side Python: catalogs only resolve *names* to lazy
DataFrames; all compute stays in the XLA/streaming execution tiers.

External catalog formats (Iceberg / Delta / Unity / Glue / S3 Tables) are
constructed through the same ``from_*`` factories as the reference; they are
gated on their optional client libraries being importable.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Sequence, Union


class NotFoundError(Exception):
    """Raised when a catalog object (namespace/table) is not found."""


Properties = Dict[str, Any]


class Identifier(Sequence):
    """A dot-separated, possibly-qualified object name (``cat.ns.table``).

    Reference: ``daft/catalog/__init__.py:498-611``.
    """

    def __init__(self, *parts: str):
        if not parts:
            raise ValueError("Identifier requires at least one part")
        self._parts = tuple(str(p) for p in parts)

    @staticmethod
    def from_str(input: str) -> "Identifier":
        return Identifier(*str(input).split("."))

    @staticmethod
    def from_sql(input: str, normalize: bool = False) -> "Identifier":
        parts = []
        for raw in str(input).split("."):
            if len(raw) >= 2 and raw[0] == '"' and raw[-1] == '"':
                parts.append(raw[1:-1].replace('""', '"'))
            else:
                parts.append(raw.lower() if normalize else raw)
        return Identifier(*parts)

    def drop(self, n: int = 1) -> "Identifier":
        if n >= len(self._parts):
            raise ValueError(f"cannot drop {n} parts from {self}")
        return Identifier(*self._parts[n:])

    @property
    def parts(self) -> tuple:
        return self._parts

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Identifier):
            return self._parts == other._parts
        if isinstance(other, str):
            return str(self) == other
        return NotImplemented

    def __hash__(self):
        return hash(self._parts)

    def __getitem__(self, index):
        return self._parts[index]

    def __len__(self) -> int:
        return len(self._parts)

    def __add__(self, suffix: "Identifier") -> "Identifier":
        return Identifier(*(self._parts + tuple(suffix)))

    def __repr__(self) -> str:
        return f"Identifier('{self}')"

    def __str__(self) -> str:
        return ".".join(self._parts)


def _to_ident(identifier: Union[Identifier, str]) -> Identifier:
    return identifier if isinstance(identifier, Identifier) \
        else Identifier.from_str(identifier)


class Table(ABC):
    """A named, readable (and optionally writable) dataset.

    Reference: ``daft/catalog/__init__.py:613-814``.
    """

    @property
    @abstractmethod
    def name(self) -> str: ...

    @abstractmethod
    def schema(self): ...

    @abstractmethod
    def read(self, **options: Any): ...

    @staticmethod
    def from_pydict(name: str, data: Dict[str, Any]) -> "Table":
        from . import dataframe as _df
        return MemTable(name, _df.from_pydict(data))

    @staticmethod
    def from_df(name: str, dataframe) -> "Table":
        return MemTable(name, dataframe)

    def select(self, *columns):
        return self.read().select(*columns)

    def show(self, n: int = 8) -> None:
        self.read().show(n)

    def write(self, df, mode: str = "append", **options: Any) -> None:
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def append(self, df, **options: Any) -> None:
        self.write(df, mode="append", **options)

    def overwrite(self, df, **options: Any) -> None:
        self.write(df, mode="overwrite", **options)

    def __repr__(self) -> str:
        return f"Table('{self.name}')"


class MemTable(Table):
    """In-memory table over a (lazy) DataFrame; append/overwrite rebind it."""

    def __init__(self, name: str, df):
        self._name = name
        self._df = df

    @property
    def name(self) -> str:
        return self._name

    def schema(self):
        return self._df.schema()

    def read(self, **options: Any):
        return self._df

    def write(self, df, mode: str = "append", **options: Any) -> None:
        if mode == "overwrite":
            self._df = df
        elif mode == "append":
            self._df = self._df.concat(df)
        else:
            raise ValueError(f"unsupported write mode {mode!r}")


class Catalog(ABC):
    """A named collection of namespaces and tables.

    Reference: ``daft/catalog/__init__.py:74-494`` (``_create_table`` etc.
    underscore-method provider SPI + public convenience verbs).
    """

    @property
    @abstractmethod
    def name(self) -> str: ...

    # -- provider SPI ------------------------------------------------------
    def _create_namespace(self, ident: Identifier) -> None:
        raise NotImplementedError(f"{type(self).__name__}: create_namespace")

    def _create_table(self, ident: Identifier, schema,
                      properties: Optional[Properties] = None) -> Table:
        raise NotImplementedError(f"{type(self).__name__}: create_table")

    def _drop_namespace(self, ident: Identifier) -> None:
        raise NotImplementedError(f"{type(self).__name__}: drop_namespace")

    def _drop_table(self, ident: Identifier) -> None:
        raise NotImplementedError(f"{type(self).__name__}: drop_table")

    @abstractmethod
    def _get_table(self, ident: Identifier) -> Table: ...

    def _has_namespace(self, ident: Identifier) -> bool:
        return any(ns == ident for ns in self._list_namespaces())

    def _has_table(self, ident: Identifier) -> bool:
        try:
            self._get_table(ident)
            return True
        except NotFoundError:
            return False

    def _list_namespaces(self, pattern: Optional[str] = None) -> List[Identifier]:
        raise NotImplementedError(f"{type(self).__name__}: list_namespaces")

    @abstractmethod
    def _list_tables(self, pattern: Optional[str] = None) -> List[Identifier]: ...

    # -- factories ---------------------------------------------------------
    @staticmethod
    def from_pydict(tables: Dict[Union[Identifier, str], Any],
                    name: str = "default") -> "Catalog":
        cat = InMemoryCatalog(name)
        for ident, source in tables.items():
            cat._put(_to_ident(ident), _as_table(_to_ident(ident)[-1], source))
        return cat

    @staticmethod
    def from_iceberg(catalog: Any) -> "Catalog":
        raise ImportError(
            "Iceberg catalogs require the 'pyiceberg' package, which is not "
            "available in this environment")

    @staticmethod
    def from_unity(catalog: Any) -> "Catalog":
        raise ImportError(
            "Unity catalogs require the 'unitycatalog' package, which is not "
            "available in this environment")

    @staticmethod
    def _from_obj(obj: Any) -> "Catalog":
        if isinstance(obj, Catalog):
            return obj
        if isinstance(obj, dict):
            return Catalog.from_pydict(obj)
        raise ValueError(f"cannot construct a Catalog from {type(obj).__name__}")

    # -- public verbs ------------------------------------------------------
    def create_namespace(self, identifier: Union[Identifier, str]) -> None:
        self._create_namespace(_to_ident(identifier))

    def create_namespace_if_not_exists(self, identifier) -> None:
        if not self.has_namespace(identifier):
            self.create_namespace(identifier)

    def create_table(self, identifier, source, properties=None, **kw) -> Table:
        ident = _to_ident(identifier)
        from .schema import Schema
        if isinstance(source, Schema):
            return self._create_table(ident, source, properties)
        # DataFrame source: create from its schema then overwrite with data
        tbl = self._create_table(ident, source.schema(), properties)
        tbl.write(source, mode="overwrite")
        return tbl

    def create_table_if_not_exists(self, identifier, source, **kw) -> Table:
        if self.has_table(identifier):
            return self.get_table(identifier)
        return self.create_table(identifier, source, **kw)

    def has_namespace(self, identifier) -> bool:
        return self._has_namespace(_to_ident(identifier))

    def has_table(self, identifier) -> bool:
        return self._has_table(_to_ident(identifier))

    def drop_namespace(self, identifier) -> None:
        self._drop_namespace(_to_ident(identifier))

    def drop_table(self, identifier) -> None:
        self._drop_table(_to_ident(identifier))

    def get_table(self, identifier) -> Table:
        return self._get_table(_to_ident(identifier))

    def list_namespaces(self, pattern: Optional[str] = None) -> List[Identifier]:
        return self._list_namespaces(pattern)

    def list_tables(self, pattern: Optional[str] = None) -> List[Identifier]:
        return self._list_tables(pattern)

    def read_table(self, identifier, **options):
        return self.get_table(identifier).read(**options)

    def write_table(self, identifier, df, mode: str = "append", **options) -> None:
        self.get_table(identifier).write(df, mode=mode, **options)

    def __repr__(self) -> str:
        return f"Catalog('{self.name}')"


def _as_table(name: str, source: Any) -> Table:
    from .dataframe import DataFrame
    if isinstance(source, Table):
        return source
    if isinstance(source, DataFrame):
        return MemTable(name, source)
    if isinstance(source, dict):
        return Table.from_pydict(name, source)
    raise ValueError(f"cannot make a table from {type(source).__name__}")


class InMemoryCatalog(Catalog):
    """Process-local catalog: dict of Identifier → Table plus namespace set.

    Reference: the Rust in-memory impl in ``src/daft-catalog/src/catalog.rs``.
    """

    def __init__(self, name: str = "default"):
        self._name = name
        self._tables: Dict[Identifier, Table] = {}
        self._namespaces: set = set()

    @property
    def name(self) -> str:
        return self._name

    def _put(self, ident: Identifier, table: Table) -> None:
        self._tables[ident] = table
        if len(ident) > 1:
            self._namespaces.add(Identifier(*ident[:-1]))

    def _create_namespace(self, ident: Identifier) -> None:
        if ident in self._namespaces:
            raise ValueError(f"namespace {ident} already exists")
        self._namespaces.add(ident)

    def _create_table(self, ident: Identifier, schema, properties=None) -> Table:
        if ident in self._tables:
            raise ValueError(f"table {ident} already exists")
        from . import dataframe as _df
        empty = _df.from_pydict(
            {f.name: _empty_column(f.dtype) for f in schema})
        tbl = MemTable(str(ident[-1]), empty)
        self._put(ident, tbl)
        return tbl

    def _drop_namespace(self, ident: Identifier) -> None:
        if ident not in self._namespaces:
            raise NotFoundError(f"namespace {ident} not found")
        # drop the namespace, any child namespaces, and all tables under them
        pfx = tuple(ident)
        self._namespaces = {ns for ns in self._namespaces
                            if tuple(ns[:len(pfx)]) != pfx}
        self._tables = {k: v for k, v in self._tables.items()
                        if tuple(k[:len(pfx)]) != pfx}

    def _drop_table(self, ident: Identifier) -> None:
        if ident not in self._tables:
            raise NotFoundError(f"table {ident} not found")
        del self._tables[ident]

    def _get_table(self, ident: Identifier) -> Table:
        if ident in self._tables:
            return self._tables[ident]
        raise NotFoundError(f"table {ident} not found in catalog {self._name}")

    def _has_namespace(self, ident: Identifier) -> bool:
        return ident in self._namespaces

    def _list_namespaces(self, pattern: Optional[str] = None) -> List[Identifier]:
        out = sorted(self._namespaces, key=str)
        if pattern:
            out = [n for n in out if str(n).startswith(pattern)]
        return out

    def _list_tables(self, pattern: Optional[str] = None) -> List[Identifier]:
        out = sorted(self._tables, key=str)
        if pattern:
            out = [t for t in out if str(t).startswith(pattern)]
        return out


def _empty_column(dtype):
    import pyarrow as pa
    try:
        return pa.array([], type=dtype.to_arrow())
    except Exception:
        return pa.array([], type=pa.null())
