"""Free-function expression constructors (reference: ``daft/functions/``)."""

from ..expressions.expressions import Expression, col, lit


def row_number() -> Expression:
    return Expression("winfn.row_number", ())


def rank() -> Expression:
    return Expression("winfn.rank", ())


def dense_rank() -> Expression:
    return Expression("winfn.dense_rank", ())


def monotonically_increasing_id() -> Expression:
    """Routed to a MonotonicallyIncreasingId plan node by the builder
    (reference: DetectMonotonicId rule)."""
    return Expression("monotonically_increasing_id", ())


def _cols(exprs):
    # reference accepts Expression | str column names
    return [col(e) if isinstance(e, str) else Expression._to_expression(e)
            for e in exprs]


def columns_sum(*exprs) -> Expression:
    """Row-wise sum skipping nulls (reference: list_(...).list.sum())."""
    from ..expressions.expressions import list_
    return list_(*_cols(exprs)).list.sum()


def columns_mean(*exprs) -> Expression:
    from ..expressions.expressions import list_
    return list_(*_cols(exprs)).list.mean()


def columns_min(*exprs) -> Expression:
    from ..expressions.expressions import list_
    return list_(*_cols(exprs)).list.min()


def columns_max(*exprs) -> Expression:
    from ..expressions.expressions import list_
    return list_(*_cols(exprs)).list.max()


def columns_avg(*exprs) -> Expression:
    return columns_mean(*exprs)


__all__ = ["row_number", "rank", "dense_rank", "monotonically_increasing_id",
           "columns_sum", "columns_mean", "columns_avg", "columns_min",
           "columns_max"]
