"""Tokenization kernels: byte-pair encoding over tiktoken-format vocabs.

Capability mirror of the reference's tokenize crate
(``src/daft-functions-tokenize``: tiktoken-based ``tokenize_encode`` /
``tokenize_decode`` expressions) implemented as a dependency-free BPE.
Vocabularies load from local tiktoken-format files (one
``base64(token) rank`` pair per line — the public format of cl100k_base
etc.); the builtin ``"bytes"`` tokenizer (ids = raw utf-8 bytes) works with
no vocab file, keeping the surface usable in zero-egress environments.
"""

from __future__ import annotations

import base64
import functools
import threading
from typing import Dict, List, Optional

try:
    import regex as _re  # \p{L} classes like the reference's pretokenizer
except ImportError:  # pragma: no cover
    import re as _re

# GPT-2-family pretokenization pattern (the published tiktoken pattern for
# r50k/p50k vocabs; pure interop constant)
_DEFAULT_PATTERN = (
    r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+"
    r"|\s+(?!\S)|\s+")
if _re.__name__ == "re":  # pragma: no cover - ascii approximation
    _DEFAULT_PATTERN = (
        r"'s|'t|'re|'ve|'m|'ll|'d| ?[A-Za-z]+| ?[0-9]+| ?[^\sA-Za-z0-9]+"
        r"|\s+(?!\S)|\s+")


class BPETokenizer:
    """Greedy lowest-rank byte-pair merges (the tiktoken algorithm). The
    merge loop runs in the native C++ kernel library when available
    (``native/src/kernels.cpp`` dn_bpe_*), with this module's pure-python
    implementation as the fallback — both produce identical ids."""

    def __init__(self, ranks: Dict[bytes, int],
                 pattern: Optional[str] = None):
        self.ranks = ranks
        self.decoder = {v: k for k, v in ranks.items()}
        self._rx = _re.compile(pattern or _DEFAULT_PATTERN)
        self._native = None
        from .. import native
        if native.AVAILABLE:
            toks = list(ranks)
            self._native = native.BpeVocab(toks,
                                           [ranks[t] for t in toks])

    # ------------------------------------------------------------ encode
    def _bpe(self, piece: bytes) -> List[int]:
        if piece in self.ranks:
            return [self.ranks[piece]]
        parts = [piece[i:i + 1] for i in range(len(piece))]
        while len(parts) > 1:
            best_rank = None
            best_i = -1
            for i in range(len(parts) - 1):
                r = self.ranks.get(parts[i] + parts[i + 1])
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank = r
                    best_i = i
            if best_rank is None:
                break
            parts[best_i:best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        out = []
        for p in parts:
            r = self.ranks.get(p)
            if r is None:
                raise ValueError(
                    f"byte sequence {p!r} not in vocabulary (vocab lacks "
                    f"single-byte tokens?)")
            out.append(r)
        return out

    def encode(self, text: str) -> List[int]:
        pieces = [m.group().encode("utf-8")
                  for m in self._rx.finditer(text)]
        if self._native is not None:
            # all pieces in one native call — FFI overhead amortizes
            id_arrays = self._native.encode_batch(pieces)
            if id_arrays is None:
                raise ValueError(
                    "text not fully covered by the vocabulary (vocab "
                    "lacks single-byte tokens?)")
            if not id_arrays:
                return []
            import numpy as np
            return np.concatenate(id_arrays).tolist()
        out = []
        for piece in pieces:
            out.extend(self._bpe(piece))
        return out

    def decode(self, ids: List[int]) -> str:
        buf = bytearray()
        for i in ids:
            tok = self.decoder.get(int(i))
            if tok is None:
                raise ValueError(f"token id {i} not in vocabulary")
            buf += tok
        return buf.decode("utf-8", errors="replace")


def _load_tiktoken_file(path: str) -> Dict[bytes, int]:
    ranks: Dict[bytes, int] = {}
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            tok_b64, rank = line.split()
            ranks[base64.b64decode(tok_b64)] = int(rank)
    return ranks


_cache: Dict[str, BPETokenizer] = {}
_cache_lock = threading.Lock()


def get_tokenizer(tokens_path: Optional[str],
                  pattern: Optional[str] = None) -> BPETokenizer:
    """``None``/``"bytes"`` → builtin byte-level tokenizer; otherwise a
    local tiktoken-format vocab file path."""
    key = f"{tokens_path}\x00{pattern}"
    with _cache_lock:
        tk = _cache.get(key)
        if tk is None:
            if tokens_path in (None, "bytes"):
                ranks = {bytes([i]): i for i in range(256)}
            else:
                # daft-lint: allow(blocking-under-lock) -- load-once
                # dedupe is the point: holding the cache lock during the
                # vocab read stops N threads doing N expensive loads
                ranks = _load_tiktoken_file(tokens_path)
            tk = BPETokenizer(ranks, pattern)
            _cache[key] = tk
    return tk


def eval_tokenize(fn: str, e, kids, out_field):
    """Expression entry: ``str.tokenize_encode`` / ``str.tokenize_decode``."""
    from ..datatype import DataType
    from ..series import Series
    s = kids[0]
    name = s.name()
    tokens_path, pattern = e.params
    tk = get_tokenizer(tokens_path, pattern)
    if fn == "tokenize_encode":
        out = [None if v is None else tk.encode(v) for v in s.to_pylist()]
        return Series.from_pylist(out, name,
                                  dtype=DataType.list(DataType.uint32()))
    if fn == "tokenize_decode":
        out = [None if v is None else tk.decode(v) for v in s.to_pylist()]
        return Series.from_pylist(out, name, dtype=DataType.string())
    raise NotImplementedError(f"str.{fn}")
