"""Image kernels (reference: ``src/daft-image/src/{image_buffer.rs:109-174,series.rs:72-156}``).

Decode/encode ride on Pillow when available (host); resize/crop/to_mode run as
vectorized numpy for fixed-shape images and can batch onto TPU via
``daft_tpu.device`` for `fixed_shape_image` columns.
"""

from __future__ import annotations

import io
from typing import List

import numpy as np
import pyarrow as pa

from ..datatype import DataType, ImageFormat, ImageMode
from ..schema import Field
from ..series import Series

try:
    from PIL import Image as _PILImage
    _HAS_PIL = True
except ImportError:
    _HAS_PIL = False


_MODE_TO_PIL = {"L": "L", "LA": "LA", "RGB": "RGB", "RGBA": "RGBA"}


def _decode_one(buf, mode):
    img = _PILImage.open(io.BytesIO(buf))
    if mode is not None:
        img = img.convert(_MODE_TO_PIL[mode.name])
    return np.asarray(img)


_RESIZE_BATCH_MIN = 8  # below this, per-image PIL beats a device round-trip
_resize_jit = None


def _get_resize_jit():
    """One module-level jitted program, (h, w, lo, hi, out_dtype) static —
    reused across batches so only genuinely new shapes compile. The cast
    back to the output dtype happens ON-DEVICE: an f32 result fetched and
    cast host-side made the real download 4× what the cost model priced
    for uint8 images (the r5 advisory), and 4× what it needed to be."""
    global _resize_jit
    if _resize_jit is None:
        import jax
        import jax.numpy as jnp

        def fn(x, h, w, lo, hi, out_dtype):
            y = jax.image.resize(x.astype(jnp.float32),
                                 (x.shape[0], h, w, x.shape[3]),
                                 method="bilinear")
            if lo is not None:
                y = jnp.clip(y, lo, hi)
            return y.astype(out_dtype)

        # daft-lint: allow(unguarded-global-mutation) -- benign last-wins
        # memo: jax.jit wrapper construction is cheap (compiles lazily),
        # a racing duplicate is discarded and both are usable
        _resize_jit = jax.jit(fn, static_argnums=(1, 2, 3, 4, 5))
    return _resize_jit


def _device_batch_resize(imgs, w: int, h: int):
    """Uniform-shape image batch → ONE jit bilinear resize on the device
    tier — (N,H,W,C) in a single transfer instead of N PIL calls (the
    TPU-first path; XLA lowers jax.image.resize to gathers/matmuls that
    tile onto the MXU). Returns None when the batch is ragged/small/
    device-off, falling back to the per-image host path."""
    from ..device import runtime as drt
    if not drt.device_enabled():
        return None
    real = [im for im in imgs if im is not None]
    if len(real) < _RESIZE_BATCH_MIN:
        return None
    arrs = [np.asarray(im) for im in real]
    shape = arrs[0].shape
    dtype = arrs[0].dtype
    if any(a.shape != shape or a.dtype != dtype for a in arrs) \
            or len(shape) not in (2, 3):
        return None
    stack = np.stack(arrs)
    if len(shape) == 2:
        stack = stack[..., None]
    from ..device import costmodel
    ch = stack.shape[-1] if len(stack.shape) == 4 else 1
    if not costmodel.image_resize_wins(
            stack.nbytes, len(real) * h * w * ch * stack.dtype.itemsize):
        return None
    import jax
    import jax.numpy as jnp
    if dtype.kind in "ui":
        info = np.iinfo(dtype)
        lo, hi = float(info.min), float(info.max)
    else:
        lo = hi = None  # float images: no clamp, match PIL/NumPy behavior
    from ..analysis import retrace_sanitizer
    # declared trace signature (dispatch_registry: image.resize): the
    # batch shape + static resize spec — the jit cache key, spelled out
    with retrace_sanitizer.dispatch_scope(
            "image.resize", (stack.shape, str(dtype), h, w, lo, hi)):
        out = _get_resize_jit()(jnp.asarray(stack), h, w, lo, hi,
                                jnp.dtype(dtype))
    res = np.asarray(jax.device_get(out))
    if len(shape) == 2:
        res = res[..., 0]
    it = iter(res)
    return [None if im is None else next(it) for im in imgs]


def eval_image_fn(fn: str, e, kids: List[Series], out_field: Field) -> Series:
    s = kids[0]
    name = s.name()
    if fn == "decode":
        if not _HAS_PIL:
            raise RuntimeError("image.decode requires Pillow")
        on_error, mode = e.params
        m = ImageMode.from_mode_string(mode) if isinstance(mode, str) else mode
        out = []
        for buf in s.to_pylist():
            if buf is None:
                out.append(None)
                continue
            try:
                out.append(_decode_one(buf, m))
            except Exception:
                if on_error == "raise":
                    raise
                out.append(None)
        return Series.from_pyobjects(out, name)  # ndarray images; struct-encode later
    if fn == "encode":
        if not _HAS_PIL:
            raise RuntimeError("image.encode requires Pillow")
        image_format = e.params[0]
        f = ImageFormat.from_format_string(image_format) \
            if isinstance(image_format, str) else image_format
        out = []
        for img in s.to_pylist():
            if img is None:
                out.append(None)
                continue
            arr = np.asarray(img)
            bio = io.BytesIO()
            _PILImage.fromarray(arr).save(bio, format=f.value)
            out.append(bio.getvalue())
        return Series.from_pylist(out, name, dtype=DataType.binary())
    if fn == "resize":
        w, h = e.params
        imgs = s.to_pylist()
        batched = _device_batch_resize(imgs, w, h)
        if batched is not None:
            return Series.from_pyobjects(batched, name)
        out = []
        for img in imgs:
            if img is None:
                out.append(None)
                continue
            arr = np.asarray(img)
            if _HAS_PIL:
                out.append(np.asarray(_PILImage.fromarray(arr).resize((w, h))))
            else:
                ys = (np.linspace(0, arr.shape[0] - 1, h)).astype(int)
                xs = (np.linspace(0, arr.shape[1] - 1, w)).astype(int)
                out.append(arr[ys][:, xs])
        return Series.from_pyobjects(out, name)
    if fn == "crop":
        bbox = kids[1].to_pylist()
        if len(bbox) == 1:
            bbox = bbox * len(s)
        out = []
        for img, bb in zip(s.to_pylist(), bbox):
            if img is None or bb is None:
                out.append(None)
                continue
            x, y, w, h = bb
            out.append(np.asarray(img)[y:y + h, x:x + w])
        return Series.from_pyobjects(out, name)
    if fn == "to_mode":
        mode = ImageMode.from_mode_string(e.params[0])
        if not _HAS_PIL:
            raise RuntimeError("image.to_mode requires Pillow")
        out = []
        for img in s.to_pylist():
            if img is None:
                out.append(None)
                continue
            out.append(np.asarray(
                _PILImage.fromarray(np.asarray(img)).convert(_MODE_TO_PIL[mode.name])))
        return Series.from_pyobjects(out, name)
    raise NotImplementedError(f"image.{fn}")
