"""daft_tpu: a TPU-native distributed dataframe / query engine.

Same capability surface as the reference engine (see SURVEY.md), built
TPU-first: Arrow C++ host columns, jit-compiled XLA relational operators,
ICI-collective shuffles over a jax device Mesh.
"""

from .datatype import DataType, ImageFormat, ImageMode, TimeUnit
from .expressions import (
    Expression, col, lit, element, coalesce, interval, list_, struct,
)
from .schema import Field, Schema
from .series import Series
from .recordbatch import RecordBatch
from .udf import udf  # after submodule import, rebind name to the decorator

__version__ = "0.1.0"

__all__ = [
    "DataType", "ImageFormat", "ImageMode", "TimeUnit",
    "Expression", "col", "lit", "element", "coalesce", "interval",
    "list_", "struct", "Field", "Schema", "Series", "RecordBatch",
]


def __getattr__(name):
    # heavier subsystems load lazily to keep `import daft_tpu` fast
    if name in ("DataFrame",):
        from .dataframe import DataFrame
        return DataFrame
    if name in ("from_pydict", "from_arrow", "from_pandas", "from_pylist",
                "from_glob_path", "range"):
        from . import dataframe as _df
        return getattr(_df, name)
    if name in ("read_parquet", "read_csv", "read_json"):
        from . import io as _io
        return getattr(_io, name)
    if name == "sql":
        from .sql import sql
        return sql
    if name == "sql_expr":
        from .sql import sql_expr
        return sql_expr
    if name == "udf":
        from .udf import udf
        return udf
    if name == "context":
        from . import context
        return context
    if name in ("set_execution_config", "set_planning_config", "execution_config_ctx",
                "get_context", "set_runner_native", "set_runner_tpu_distributed"):
        from . import context as _ctx
        return getattr(_ctx, name)
    if name == "Window":
        from .window import Window
        return Window
    if name == "Catalog":
        from .catalog import Catalog
        return Catalog
    if name == "Session":
        from .session import Session
        return Session
    raise AttributeError(f"module 'daft_tpu' has no attribute {name!r}")
