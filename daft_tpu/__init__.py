"""daft_tpu: a TPU-native distributed dataframe / query engine.

Same capability surface as the reference engine (see SURVEY.md), built
TPU-first: Arrow C++ host columns, jit-compiled XLA relational operators,
ICI-collective shuffles over a jax device Mesh.
"""

# the runtime lock-order sanitizer must patch the lock factories BEFORE
# the engine modules below create their module-level locks — this block
# stays first (analysis.knobs / lock_sanitizer are import-light)
from .analysis import knobs as _knobs
if _knobs.env_bool("DAFT_TPU_SANITIZE"):
    from .analysis import lock_sanitizer as _lock_sanitizer
    _lock_sanitizer.enable()
    # …and the retrace sanitizer hooks jax's trace/compile events the
    # same way, so even import-time jit constructions are accounted
    from .analysis import retrace_sanitizer as _retrace_sanitizer
    if _retrace_sanitizer.enabled_by_env():
        _retrace_sanitizer.enable()
# the plan sanitizer hooks the optimizer loop and executor node streams
# (no factory patching), so it arms on its own knob independent of the
# DAFT_TPU_SANITIZE umbrella
from .analysis import plan_sanitizer as _plan_sanitizer
if _plan_sanitizer.enabled_by_env():
    _plan_sanitizer.enable()

from .datatype import DataType, ImageFormat, ImageMode, TimeUnit
from .expressions import (
    Expression, col, lit, element, coalesce, interval, list_, struct,
)
from .schema import Field, Schema
from .series import Series
from .recordbatch import RecordBatch
from .udf import udf  # after submodule import, rebind name to the decorator

# Eager: the from-import must run at package init so the function binding
# lands *after* the import machinery sets the `sql` submodule attribute
# (otherwise `daft_tpu.sql` resolves to the module, not the callable).
from .sql import sql, sql_expr

__version__ = "0.1.0"

__all__ = [
    "DataType", "ImageFormat", "ImageMode", "TimeUnit",
    "Expression", "col", "lit", "element", "coalesce", "interval",
    "list_", "struct", "Field", "Schema", "Series", "RecordBatch",
]


def __getattr__(name):
    # heavier subsystems load lazily to keep `import daft_tpu` fast
    if name in ("DataFrame",):
        from .dataframe import DataFrame
        return DataFrame
    if name in ("from_pydict", "from_arrow", "from_pandas", "from_pylist",
                "from_glob_path", "range"):
        from . import dataframe as _df
        return getattr(_df, name)
    if name in ("read_parquet", "read_csv", "read_json", "read_warc",
                "read_deltalake", "read_iceberg", "read_hudi", "read_lance",
                "read_sql"):
        from . import io as _io
        return getattr(_io, name)
    if name in ("IOConfig", "S3Config", "GCSConfig", "AzureConfig",
                "HTTPConfig"):
        from .io import object_io as _oio
        return getattr(_oio, name)
    if name == "sql":
        from .sql import sql
        return sql
    if name == "sql_expr":
        from .sql import sql_expr
        return sql_expr
    if name == "udf":
        from .udf import udf
        return udf
    # NB: `from . import context` here would recurse — _handle_fromlist
    # probes hasattr(package, "context") first, which re-enters this
    # __getattr__ before the submodule ever imports. importlib avoids it.
    if name == "context":
        import importlib
        return importlib.import_module(".context", __name__)
    if name in ("set_execution_config", "set_planning_config", "execution_config_ctx",
                "get_context", "set_runner_native", "set_runner_tpu_distributed"):
        import importlib
        return getattr(importlib.import_module(".context", __name__), name)
    if name == "Window":
        from .window import Window
        return Window
    if name in ("Catalog", "Table", "Identifier", "NotFoundError"):
        from . import catalog as _cat
        return getattr(_cat, name)
    if name == "Session":
        from .session import Session
        return Session
    if name in _SESSION_VERBS:
        from . import session as _sess
        return getattr(_sess, name)
    raise AttributeError(f"module 'daft_tpu' has no attribute {name!r}")


_SESSION_VERBS = frozenset((
    "attach", "attach_catalog", "attach_table", "attach_function",
    "detach_catalog", "detach_table", "detach_function", "create_namespace",
    "create_namespace_if_not_exists", "create_table",
    "create_table_if_not_exists", "create_temp_table", "drop_namespace",
    "drop_table", "current_catalog", "current_namespace", "current_session",
    "get_catalog", "get_table", "has_catalog", "has_namespace", "has_table",
    "list_catalogs", "list_namespaces", "list_tables", "read_table",
    "write_table", "set_catalog", "set_namespace", "use",
))
