from .expressions import (
    Expression,
    ExpressionsProjection,
    col,
    lit,
    element,
    coalesce,
    interval,
    list_,
    struct,
)

__all__ = [
    "Expression", "ExpressionsProjection", "col", "lit", "element",
    "coalesce", "interval", "list_", "struct",
]
