"""Expression type inference: Expr × Schema → Field.

Mirrors the reference's ``Expr::to_field`` (``src/daft-dsl/src/expr/mod.rs``)
and its type-promotion matrix (``daft-schema`` ``try_get_supertype``).
"""

from __future__ import annotations

import datetime
from typing import Optional

from ..datatype import DataType, TimeUnit
from ..schema import Field, Schema

_INT_ORDER = ["int8", "int16", "int32", "int64"]
_UINT_ORDER = ["uint8", "uint16", "uint32", "uint64"]
_FLOAT_ORDER = ["float32", "float64"]


def supertype(a: DataType, b: DataType) -> DataType:
    """Smallest common supertype for binary ops (reference: try_get_supertype)."""
    if a == b:
        return a
    if a.is_null():
        return b
    if b.is_null():
        return a
    ks, ko = a.kind, b.kind
    if a.is_numeric() and b.is_numeric():
        if a.is_decimal() or b.is_decimal():
            return DataType.float64()
        if a.is_floating() or b.is_floating():
            if "float64" in (ks, ko):
                return DataType.float64()
            # int64/uint64 + float32 -> float64 to preserve magnitude
            for t in (a, b):
                if t.is_integer() and t.kind in ("int64", "uint64", "int32", "uint32"):
                    return DataType.float64()
            return DataType.float32()
        if a.is_signed_integer() and b.is_signed_integer():
            return DataType(
                a._kind if _INT_ORDER.index(ks) >= _INT_ORDER.index(ko) else b._kind)
        if a.is_unsigned_integer() and b.is_unsigned_integer():
            return DataType(
                a._kind if _UINT_ORDER.index(ks) >= _UINT_ORDER.index(ko) else b._kind)
        # mixed signedness: smallest signed type that holds both, capped at int64
        u, s = (a, b) if a.is_unsigned_integer() else (b, a)
        idx = max(_UINT_ORDER.index(u.kind) + 1, _INT_ORDER.index(s.kind))
        return [DataType.int8, DataType.int16, DataType.int32,
                DataType.int64][min(idx, 3)]()
    if a.is_boolean() and b.is_numeric():
        return b
    if b.is_boolean() and a.is_numeric():
        return a
    if (a.is_string() and b.is_numeric()) or (b.is_string() and a.is_numeric()):
        return DataType.string()
    if a.is_temporal() and b.is_temporal():
        if "timestamp" in (ks, ko):
            ts = a if ks == "timestamp" else b
            return ts
        return a
    raise TypeError(f"no supertype for {a!r} and {b!r}")


def _lit_field(value) -> Field:
    if value is None:
        return Field("literal", DataType.null())
    if isinstance(value, bool):
        return Field("literal", DataType.bool())
    if isinstance(value, int):
        return Field("literal", DataType.int32()
                     if -(2**31) <= value < 2**31 else DataType.int64())
    if isinstance(value, float):
        return Field("literal", DataType.float64())
    if isinstance(value, str):
        return Field("literal", DataType.string())
    if isinstance(value, bytes):
        return Field("literal", DataType.binary())
    if isinstance(value, datetime.datetime):
        return Field("literal", DataType.timestamp(TimeUnit.us))
    if isinstance(value, datetime.date):
        return Field("literal", DataType.date())
    if isinstance(value, datetime.time):
        return Field("literal", DataType.time(TimeUnit.us))
    if isinstance(value, datetime.timedelta):
        return Field("literal", DataType.duration(TimeUnit.us))
    from ..series import Series
    if isinstance(value, Series):
        return Field("literal", value.datatype())
    try:
        return Field("literal", DataType.infer_from_pylist([value]))
    except Exception:
        return Field("literal", DataType.python())


def infer_field(e, schema: Schema) -> Field:
    op = e.op
    if op == "col":
        name = e.params[0]
        if name not in schema:
            raise ValueError(
                f"unresolved column {name!r}; available: {schema.column_names}")
        return schema[name]
    if op == "outer_col":
        raise ValueError(
            f"outer_col({e.params[0]!r}): a correlated outer-scope "
            "reference escaped its subquery's WHERE clause — only "
            "equality correlation in WHERE is supported")
    if op in ("subquery", "in_subquery", "exists"):
        raise ValueError(
            f"{op} expression must be unnested into a join before execution "
            "(logical/subquery.py apply_where); it reached evaluation "
            "unsupported — e.g. a subquery in a SELECT list or HAVING")
    if op == "lit":
        return _lit_field(e.params[0])
    if op == "lit_interval":
        return Field("literal", DataType.interval())
    if op == "alias":
        inner = infer_field(e.args[0], schema)
        return Field(e.params[0], inner.dtype)
    if op == "cast":
        inner = infer_field(e.args[0], schema)
        return Field(inner.name, e.params[0])

    child_fields = [infer_field(a, schema) for a in e.args]
    name = child_fields[0].name if child_fields else op

    if op in ("add", "sub", "mul", "div", "floordiv", "mod", "pow"):
        l, r = child_fields[0].dtype, child_fields[1].dtype
        if op == "add" and l.is_string() and r.is_string():
            return Field(name, DataType.string())
        # temporal arithmetic
        if l.is_temporal() or r.is_temporal():
            return Field(name, _temporal_arith(op, l, r))
        st = supertype(l, r)
        if op == "div":
            st = DataType.float64() if st.kind == "float64" or \
                (st.is_integer() and st.kind in ("int64", "uint64")) else \
                (st if st.is_floating() else DataType.float64())
        return Field(name, st)
    if op in ("lt", "le", "gt", "ge", "eq", "neq", "eq_null_safe", "is_in",
              "between", "and", "or", "xor", "not", "is_null", "not_null"):
        if op in ("and", "or", "xor") and child_fields[0].dtype.is_integer():
            return Field(name, supertype(child_fields[0].dtype, child_fields[1].dtype))
        return Field(name, DataType.bool())
    if op in ("negate", "abs"):
        return Field(name, child_fields[0].dtype)
    if op in ("ceil", "floor", "round", "clip", "sign"):
        return Field(name, child_fields[0].dtype)
    if op in ("sqrt", "cbrt", "exp", "log", "log2", "log10", "ln", "sin", "cos",
              "tan", "arcsin", "arccos", "arctan", "arctan2", "sinh", "cosh",
              "tanh", "degrees", "radians", "arcsinh", "arccosh", "arctanh",
              "cot", "csc", "sec", "expm1", "log1p"):
        d = child_fields[0].dtype
        return Field(name, DataType.float32() if d.kind == "float32"
                     else DataType.float64())
    if op in ("shift_left", "shift_right", "bitwise_and", "bitwise_or",
              "bitwise_xor"):
        return Field(name, child_fields[0].dtype)
    if op in ("deserialize", "try_deserialize"):
        return Field(name, e.params[1])
    if op == "fill_null":
        base = child_fields[0].dtype
        if base.is_null():
            return Field(name, child_fields[1].dtype)
        return Field(name, base)
    if op == "if_else":
        if child_fields[1].dtype.is_null():
            return Field(child_fields[1].name, child_fields[2].dtype)
        if child_fields[2].dtype.is_null():
            return Field(child_fields[1].name, child_fields[1].dtype)
        return Field(child_fields[1].name,
                     supertype(child_fields[1].dtype, child_fields[2].dtype))
    if op == "coalesce":
        dt = child_fields[0].dtype
        for f in child_fields[1:]:
            dt = f.dtype if dt.is_null() else supertype(dt, f.dtype)
        return Field(name, dt)
    if op == "hash":
        return Field(name, DataType.uint64())
    if op == "udf":
        u = e.params[0]
        nm = child_fields[0].name if child_fields else u.name
        return Field(nm, u.return_dtype)
    if op == "window":
        from ..window_exec import window_field
        return window_field(e, schema)
    if op in ("winfn.row_number", "winfn.rank", "winfn.dense_rank"):
        return Field(op[6:], DataType.uint64())
    if op in ("winfn.lag", "winfn.lead"):
        return Field(child_fields[0].name, child_fields[0].dtype)
    if op == "minhash":
        return Field(name, DataType.fixed_size_list(DataType.uint32(), e.params[0]))
    if op == "py_apply":
        return Field(name, e.params[1])
    if op == "explode":
        d = child_fields[0].dtype
        return Field(name, d.inner if d.is_list() else d)
    if op == "list":
        dt = DataType.null()
        for f in child_fields:
            dt = f.dtype if dt.is_null() else supertype(dt, f.dtype)
        return Field("list", DataType.list(dt))
    if op == "struct_make":
        return Field("struct", DataType.struct(
            {f.name: f.dtype for f in child_fields}))

    # aggregations -------------------------------------------------------
    if op.startswith("agg."):
        return _agg_field(op[4:], e, child_fields[0] if child_fields else None)

    # namespaced functions ----------------------------------------------
    if "." in op:
        return _function_field(op, e, child_fields, schema)

    raise NotImplementedError(f"type inference for {op}")


def _temporal_arith(op: str, l: DataType, r: DataType) -> DataType:
    if op == "sub":
        if l.kind == "date" and r.kind == "date":
            return DataType.duration(TimeUnit.s)
        if l.kind == "timestamp" and r.kind == "timestamp":
            return DataType.duration(l.timeunit)
        if l.is_temporal() and r.kind == "duration":
            return l
        if l.kind == "date" and r.is_integer():
            return l
    if op == "add":
        if l.kind == "duration" and r.is_temporal():
            return r
        if l.is_temporal() and r.kind == "duration":
            return l
        if l.kind == "date" and r.is_integer():
            return l
        if l.is_integer() and r.kind == "date":
            return r
        if l.kind == "duration" and r.kind == "duration":
            return l
    if l.kind == "interval" or r.kind == "interval":
        return l if r.kind == "interval" else r
    raise TypeError(f"invalid temporal arithmetic: {l!r} {op} {r!r}")


def _agg_field(agg: str, e, f: Optional[Field]) -> Field:
    if agg == "count":
        return Field(f.name if f else "count", DataType.uint64())
    if agg in ("count_distinct", "approx_count_distinct"):
        return Field(f.name, DataType.uint64())
    if agg == "sum":
        d = f.dtype
        if d.is_signed_integer() or d.is_boolean():
            return Field(f.name, DataType.int64())
        if d.is_unsigned_integer():
            return Field(f.name, DataType.uint64())
        return Field(f.name, d)
    if agg in ("mean", "stddev", "var", "skew"):
        return Field(f.name, DataType.float64())
    if agg in ("min", "max", "any_value"):
        return Field(f.name, f.dtype)
    if agg in ("list", "set"):
        return Field(f.name, DataType.list(f.dtype))
    if agg == "concat":
        d = f.dtype
        return Field(f.name, d if d.is_list() or d.is_string() else DataType.list(d))
    if agg in ("bool_and", "bool_or"):
        return Field(f.name, DataType.bool())
    if agg == "approx_percentiles":
        ps = e.params[0]
        return Field(f.name, DataType.fixed_size_list(DataType.float64(), len(ps)))
    raise NotImplementedError(f"agg type inference for {agg}")


def _function_field(op: str, e, child_fields, schema: Schema) -> Field:
    ns, fn = op.split(".", 1)
    f = child_fields[0]
    name = f.name
    if ns == "str":
        if fn in ("contains", "startswith", "endswith", "match"):
            return Field(name, DataType.bool())
        if fn in ("length", "length_bytes", "find"):
            return Field(name, DataType.uint64() if fn != "find" else DataType.int64())
        if fn in ("split", "extract_all"):
            return Field(name, DataType.list(DataType.string()))
        if fn == "to_date":
            return Field(name, DataType.date())
        if fn == "to_datetime":
            return Field(name, DataType.timestamp(TimeUnit.us, e.params[1]))
        if fn == "count_matches":
            return Field(name, DataType.uint64())
        if fn == "tokenize_encode":
            return Field(name, DataType.list(DataType.uint32()))
        if fn == "tokenize_decode":
            return Field(name, DataType.string())
        return Field(name, DataType.string())
    if ns == "dt":
        if fn in ("day", "hour", "minute", "second", "month", "quarter",
                  "day_of_week", "day_of_year", "week_of_year", "millisecond",
                  "microsecond", "nanosecond"):
            return Field(name, DataType.uint32())
        if fn == "year":
            return Field(name, DataType.int32())
        if fn == "date":
            return Field(name, DataType.date())
        if fn == "time":
            return Field(name, DataType.time(TimeUnit.us))
        if fn == "truncate":
            return Field(name, f.dtype)
        if fn in ("to_unix_epoch", "total_seconds"):
            return Field(name, DataType.int64())
        if fn == "strftime":
            return Field(name, DataType.string())
        raise NotImplementedError(f"dt.{fn}")
    if ns == "float":
        if fn in ("is_nan", "is_inf", "not_nan"):
            return Field(name, DataType.bool())
        return Field(name, f.dtype)
    if ns == "list":
        d = f.dtype
        if fn in ("length", "count"):
            return Field(name, DataType.uint64())
        if fn == "join":
            return Field(name, DataType.string())
        if fn in ("get",):
            return Field(name, d.inner)
        if fn in ("slice", "chunk", "sort", "distinct"):
            return Field(name, DataType.list(d.inner) if fn != "chunk"
                         else DataType.list(DataType.list(d.inner)))
        if fn in ("sum", "mean", "min", "max"):
            inner = d.inner
            if fn == "mean":
                return Field(name, DataType.float64())
            return Field(name, inner)
        if fn in ("bool_and", "bool_or"):
            return Field(name, DataType.bool())
        if fn == "value_counts":
            return Field(name, DataType.map(d.inner, DataType.uint64()))
        raise NotImplementedError(f"list.{fn}")
    if ns == "struct":
        if fn == "get":
            fld = e.params[0]
            return Field(fld, f.dtype.fields[fld])
    if ns == "map":
        if fn == "get":
            return Field("value", f.dtype._params[1])
    if ns == "embedding":
        if fn == "cosine_distance":
            return Field(name, DataType.float64())
    if ns == "image":
        if fn == "decode":
            mode = e.params[1]
            return Field(name, DataType.image(mode))
        if fn == "encode":
            return Field(name, DataType.binary())
        if fn == "resize":
            d = f.dtype
            if d.kind == "fixed_shape_image":
                m = d.image_mode
                return Field(name, DataType.fixed_shape_image(m, e.params[1], e.params[0]))
            return Field(name, d)
        if fn in ("crop", "to_mode"):
            if fn == "to_mode":
                return Field(name, DataType.image(e.params[0]))
            return Field(name, DataType.image(f.dtype.image_mode
                                              if f.dtype.is_image() else None))
    if ns == "binary":
        if fn == "length":
            return Field(name, DataType.uint64())
        if fn in ("encode", "try_encode"):
            # utf-8 "encodes" bytes→text in the reference's codec table
            return Field(name, DataType.binary())
        if fn in ("decode", "try_decode"):
            # both aliases map to Codec::Utf8 → Utf8 in the reference
            from .fn_host import norm_codec
            codec = norm_codec(e.params[0])
            return Field(name, DataType.string() if codec in ("utf-8", "utf8")
                         else DataType.binary())
        return Field(name, DataType.binary())
    if ns == "json":
        if fn == "query":
            return Field(name, DataType.string())
    if ns == "url":
        if fn == "download":
            return Field(name, DataType.binary())
        if fn == "upload":
            return Field(name, DataType.string())
        if fn == "parse":
            return Field(name, DataType.struct({
                "scheme": DataType.string(), "host": DataType.string(),
                "port": DataType.int32(), "path": DataType.string(),
                "query": DataType.string(), "fragment": DataType.string()}))
    if ns == "partitioning":
        if fn in ("days",):
            return Field(name, DataType.date())
        if fn in ("hours", "months", "years", "iceberg_bucket"):
            return Field(name, DataType.int32())
        if fn == "iceberg_truncate":
            return Field(name, f.dtype)
    raise NotImplementedError(f"type inference for {op}")
