"""Expression DSL.

Capability mirror of the reference's ``daft-dsl`` crate
(``src/daft-dsl/src/expr/mod.rs:213-292`` — the ``Expr`` enum with
Column/Alias/Agg/BinaryOp/Cast/Not/IsNull/FillNull/IsIn/Between/Literal/IfElse/
ScalarFunction variants) and the Python expression surface
(``daft/expressions/expressions.py:287`` and its 14 namespaces at ``:1877-5136``).

Designed fresh: expressions are immutable trees that know how to
(1) infer their output ``Field`` against a ``Schema``,
(2) evaluate on the host against a ``RecordBatch`` (Arrow C++ compute), and
(3) compile to a fused JAX function for the TPU path
    (see ``daft_tpu.device.compiler``).
"""

from __future__ import annotations

import datetime
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple, Union

from ..datatype import DataType, TimeUnit
from ..schema import Field, Schema

# ---------------------------------------------------------------------------
# node kinds


class Expression:
    """An expression over columns, evaluable to a Series."""

    __slots__ = ("op", "args", "params")

    def __init__(self, op: str, args: Tuple["Expression", ...] = (),
                 params: Tuple = ()):
        self.op = op          # node kind, e.g. "col", "lit", "add", "agg.sum"
        self.args = args      # child expressions
        self.params = params  # non-expression parameters (names, dtypes, fns)

    # -- constructors -----------------------------------------------------
    @staticmethod
    def _col(name: str) -> "Expression":
        return Expression("col", (), (name,))

    @staticmethod
    def _lit(value: Any) -> "Expression":
        return Expression("lit", (), (value,))

    @staticmethod
    def _to_expression(obj: Any) -> "Expression":
        if isinstance(obj, Expression):
            return obj
        return Expression._lit(obj)

    # -- naming / structure ------------------------------------------------
    def alias(self, name: str) -> "Expression":
        return Expression("alias", (self,), (name,))

    def name(self) -> str:
        """The output column name of this expression."""
        if self.op == "alias":
            return self.params[0]
        if self.op == "col":
            return self.params[0]
        if self.op == "lit":
            return "literal"
        if self.op == "list":
            return "list"
        if self.op == "if_else":
            # matches typing's infer_field: the value (THEN) branch names
            # the output, not the condition
            return self.args[1].name()
        if self.args:
            return self.args[0].name()
        return self.op

    def _unalias(self) -> "Expression":
        return self.args[0]._unalias() if self.op == "alias" else self

    def children(self) -> Tuple["Expression", ...]:
        return self.args

    def with_children(self, children: Sequence["Expression"]) -> "Expression":
        return Expression(self.op, tuple(children), self.params)

    def column_names(self) -> List[str]:
        """All input column names referenced (deduped, in order)."""
        out: List[str] = []

        def walk(e: "Expression"):
            if e.op == "col":
                if e.params[0] not in out:
                    out.append(e.params[0])
            for c in e.args:
                walk(c)
        walk(self)
        return out

    def has_agg(self) -> bool:
        if self.op.startswith("agg."):
            return True
        return any(c.has_agg() for c in self.args)

    def is_column(self) -> bool:
        return self.op == "col"

    def is_literal(self) -> bool:
        return self.op == "lit"

    def structurally_eq(self, other: "Expression") -> bool:
        return self._key() == other._key()

    def _key(self) -> Tuple:
        return (self.op, tuple(a._key() for a in self.args),
                tuple(_param_key(p) for p in self.params))

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        """NOTE: `==` builds an equality *expression* (like the reference).

        Use ``structurally_eq`` for structural comparison.
        """
        return Expression("eq", (self, Expression._to_expression(other)))

    def __ne__(self, other):
        return Expression("neq", (self, Expression._to_expression(other)))

    # -- operators ---------------------------------------------------------
    def __add__(self, other): return Expression("add", (self, Expression._to_expression(other)))
    def __radd__(self, other): return Expression("add", (Expression._to_expression(other), self))
    def __sub__(self, other): return Expression("sub", (self, Expression._to_expression(other)))
    def __rsub__(self, other): return Expression("sub", (Expression._to_expression(other), self))
    def __mul__(self, other): return Expression("mul", (self, Expression._to_expression(other)))
    def __rmul__(self, other): return Expression("mul", (Expression._to_expression(other), self))
    def __truediv__(self, other): return Expression("div", (self, Expression._to_expression(other)))
    def __rtruediv__(self, other): return Expression("div", (Expression._to_expression(other), self))
    def __floordiv__(self, other): return Expression("floordiv", (self, Expression._to_expression(other)))
    def __rfloordiv__(self, other): return Expression("floordiv", (Expression._to_expression(other), self))
    def __mod__(self, other): return Expression("mod", (self, Expression._to_expression(other)))
    def __rmod__(self, other): return Expression("mod", (Expression._to_expression(other), self))
    def __pow__(self, other): return Expression("pow", (self, Expression._to_expression(other)))
    def __lt__(self, other): return Expression("lt", (self, Expression._to_expression(other)))
    def __le__(self, other): return Expression("le", (self, Expression._to_expression(other)))
    def __gt__(self, other): return Expression("gt", (self, Expression._to_expression(other)))
    def __ge__(self, other): return Expression("ge", (self, Expression._to_expression(other)))
    def __and__(self, other): return Expression("and", (self, Expression._to_expression(other)))
    def __rand__(self, other): return Expression("and", (Expression._to_expression(other), self))
    def __or__(self, other): return Expression("or", (self, Expression._to_expression(other)))
    def __ror__(self, other): return Expression("or", (Expression._to_expression(other), self))
    def __xor__(self, other): return Expression("xor", (self, Expression._to_expression(other)))
    def __invert__(self): return Expression("not", (self,))
    def __neg__(self): return Expression("negate", (self,))
    def __abs__(self): return Expression("abs", (self,))

    def eq(self, other): return self == other
    def not_eq(self, other): return self != other

    def eq_null_safe(self, other):
        return Expression("eq_null_safe", (self, Expression._to_expression(other)))

    # -- null / conditional ------------------------------------------------
    def is_null(self) -> "Expression":
        return Expression("is_null", (self,))

    def not_null(self) -> "Expression":
        return Expression("not_null", (self,))

    def fill_null(self, fill_value) -> "Expression":
        return Expression("fill_null", (self, Expression._to_expression(fill_value)))

    def is_in(self, other: Iterable) -> "Expression":
        if isinstance(other, Expression):
            items: Tuple = (other,)
        else:
            items = tuple(Expression._to_expression(v) for v in other)
        return Expression("is_in", (self,) + items)

    def between(self, lower, upper) -> "Expression":
        return Expression("between", (self, Expression._to_expression(lower),
                                      Expression._to_expression(upper)))

    def if_else(self, if_true, if_false) -> "Expression":
        return Expression("if_else", (self, Expression._to_expression(if_true),
                                      Expression._to_expression(if_false)))

    # -- casting -----------------------------------------------------------
    def cast(self, dtype: DataType) -> "Expression":
        return Expression("cast", (self,), (dtype,))

    # -- aggregations ------------------------------------------------------
    def sum(self): return Expression("agg.sum", (self,))
    def mean(self): return Expression("agg.mean", (self,))
    def avg(self): return self.mean()
    def min(self): return Expression("agg.min", (self,))
    def max(self): return Expression("agg.max", (self,))
    def count(self, mode: str = "valid"): return Expression("agg.count", (self,), (mode,))
    def count_distinct(self): return Expression("agg.count_distinct", (self,))
    def any_value(self, ignore_nulls: bool = False):
        return Expression("agg.any_value", (self,), (ignore_nulls,))
    def agg_list(self): return Expression("agg.list", (self,))
    def agg_set(self): return Expression("agg.set", (self,))
    def agg_concat(self): return Expression("agg.concat", (self,))
    def stddev(self): return Expression("agg.stddev", (self,))
    def var(self): return Expression("agg.var", (self,))
    def skew(self): return Expression("agg.skew", (self,))
    def bool_and(self): return Expression("agg.bool_and", (self,))
    def bool_or(self): return Expression("agg.bool_or", (self,))
    def approx_count_distinct(self): return Expression("agg.approx_count_distinct", (self,))

    def approx_percentiles(self, percentiles):
        ps = tuple(percentiles) if isinstance(percentiles, (list, tuple)) else (percentiles,)
        return Expression("agg.approx_percentiles", (self,), (ps,))

    # -- scalar functions --------------------------------------------------
    def abs(self): return Expression("abs", (self,))
    def ceil(self): return Expression("ceil", (self,))
    def floor(self): return Expression("floor", (self,))
    def round(self, decimals: int = 0): return Expression("round", (self,), (decimals,))
    def sign(self): return Expression("sign", (self,))
    def sqrt(self): return Expression("sqrt", (self,))
    def cbrt(self): return Expression("cbrt", (self,))
    def exp(self): return Expression("exp", (self,))
    def log(self, base: float = 2.718281828459045): return Expression("log", (self,), (base,))
    def log2(self): return Expression("log2", (self,))
    def log10(self): return Expression("log10", (self,))
    def ln(self): return Expression("ln", (self,))
    def sin(self): return Expression("sin", (self,))
    def cos(self): return Expression("cos", (self,))
    def tan(self): return Expression("tan", (self,))
    def arcsin(self): return Expression("arcsin", (self,))
    def arccos(self): return Expression("arccos", (self,))
    def arctan(self): return Expression("arctan", (self,))
    def arctan2(self, other): return Expression("arctan2", (self, Expression._to_expression(other)))
    def sinh(self): return Expression("sinh", (self,))
    def cosh(self): return Expression("cosh", (self,))
    def tanh(self): return Expression("tanh", (self,))
    def arcsinh(self): return Expression("arcsinh", (self,))
    def arccosh(self): return Expression("arccosh", (self,))
    def arctanh(self): return Expression("arctanh", (self,))
    def cot(self): return Expression("cot", (self,))
    def csc(self): return Expression("csc", (self,))
    def sec(self): return Expression("sec", (self,))
    def expm1(self): return Expression("expm1", (self,))
    def log1p(self): return Expression("log1p", (self,))
    def signum(self): return Expression("sign", (self,))
    def negate(self): return -self
    def negative(self): return -self
    def degrees(self): return Expression("degrees", (self,))
    def radians(self): return Expression("radians", (self,))
    def bitwise_and(self, other):
        return Expression("bitwise_and", (self, Expression._to_expression(other)))
    def bitwise_or(self, other):
        return Expression("bitwise_or", (self, Expression._to_expression(other)))
    def bitwise_xor(self, other):
        return Expression("bitwise_xor", (self, Expression._to_expression(other)))

    # top-level codec / serde surface (reference: Expression.encode/decode/
    # try_* + deserialize; rides the binary-namespace codec machinery)
    def encode(self, codec: str): return Expression("binary.encode", (self,), (codec,))
    def decode(self, codec: str): return Expression("binary.decode", (self,), (codec,))
    def try_encode(self, codec: str):
        return Expression("binary.try_encode", (self,), (codec,))
    def try_decode(self, codec: str):
        return Expression("binary.try_decode", (self,), (codec,))
    def deserialize(self, format: str, dtype):
        return Expression("deserialize", (self,), (format, dtype))
    def try_deserialize(self, format: str, dtype):
        return Expression("try_deserialize", (self,), (format, dtype))
    def jq(self, filter: str):
        """jq-style JSON query (reference: Expression.jq over the jaq
        crate; same surface as ``.json.query``)."""
        return Expression("json.query", (self,), (filter,))
    def clip(self, min=None, max=None):
        return Expression("clip", (self, Expression._to_expression(min),
                                   Expression._to_expression(max)))

    def shift_left(self, other): return Expression("shift_left", (self, Expression._to_expression(other)))
    def shift_right(self, other): return Expression("shift_right", (self, Expression._to_expression(other)))

    def hash(self, seed=None) -> "Expression":
        args = (self,) if seed is None else (self, Expression._to_expression(seed))
        return Expression("hash", args)

    def minhash(self, num_hashes: int, ngram_size: int, seed: int = 1) -> "Expression":
        return Expression("minhash", (self,), (num_hashes, ngram_size, seed))

    def apply(self, func: Callable, return_dtype: DataType) -> "Expression":
        return Expression("py_apply", (self,), (func, return_dtype))

    # -- window ------------------------------------------------------------
    def over(self, window) -> "Expression":
        """Attach a window spec (reference: ``Expr::Over``)."""
        return Expression("window", (self,), (window,))

    def lag(self, offset: int = 1, default=None) -> "Expression":
        args = (self,) if default is None else (self, Expression._to_expression(default))
        return Expression("winfn.lag", args, (offset,))

    def lead(self, offset: int = 1, default=None) -> "Expression":
        args = (self,) if default is None else (self, Expression._to_expression(default))
        return Expression("winfn.lead", args, (offset,))

    def explode(self) -> "Expression":
        return Expression("explode", (self,))

    # -- namespaces --------------------------------------------------------
    @property
    def str(self) -> "ExpressionStringNamespace":
        return ExpressionStringNamespace(self)

    @property
    def dt(self) -> "ExpressionDatetimeNamespace":
        return ExpressionDatetimeNamespace(self)

    @property
    def float(self) -> "ExpressionFloatNamespace":
        return ExpressionFloatNamespace(self)

    @property
    def list(self) -> "ExpressionListNamespace":
        return ExpressionListNamespace(self)

    @property
    def struct(self) -> "ExpressionStructNamespace":
        return ExpressionStructNamespace(self)

    @property
    def map(self) -> "ExpressionMapNamespace":
        return ExpressionMapNamespace(self)

    @property
    def embedding(self) -> "ExpressionEmbeddingNamespace":
        return ExpressionEmbeddingNamespace(self)

    @property
    def image(self) -> "ExpressionImageNamespace":
        return ExpressionImageNamespace(self)

    @property
    def partitioning(self) -> "ExpressionPartitioningNamespace":
        return ExpressionPartitioningNamespace(self)

    @property
    def binary(self) -> "ExpressionBinaryNamespace":
        return ExpressionBinaryNamespace(self)

    @property
    def json(self) -> "ExpressionJsonNamespace":
        return ExpressionJsonNamespace(self)

    @property
    def url(self) -> "ExpressionUrlNamespace":
        return ExpressionUrlNamespace(self)

    # -- schema ------------------------------------------------------------
    def to_field(self, schema: Schema) -> Field:
        from .typing import infer_field
        return infer_field(self, schema)

    def __repr__(self):
        return _repr_expr(self)

    def __bool__(self):
        raise ValueError(
            "Expressions don't have a truth value; use & | ~ for boolean logic")


# ---------------------------------------------------------------------------
# namespaces


class _Ns:
    __slots__ = ("_e",)

    def __init__(self, e: Expression):
        self._e = e

    def _f(self, op: str, args: Tuple = (), params: Tuple = ()) -> Expression:
        return Expression(op, (self._e,) + tuple(
            Expression._to_expression(a) for a in args), params)


class ExpressionStringNamespace(_Ns):
    """Reference surface: ~50 utf8 fns in ``src/daft-functions-utf8``."""

    def contains(self, pattern): return self._f("str.contains", (pattern,))
    def startswith(self, prefix): return self._f("str.startswith", (prefix,))
    def endswith(self, suffix): return self._f("str.endswith", (suffix,))
    def concat(self, other): return self._f("str.concat", (other,))
    def length(self): return self._f("str.length")
    def length_bytes(self): return self._f("str.length_bytes")
    def lower(self): return self._f("str.lower")
    def upper(self): return self._f("str.upper")
    def lstrip(self): return self._f("str.lstrip")
    def rstrip(self): return self._f("str.rstrip")
    def strip(self): return self._f("str.strip")
    def reverse(self): return self._f("str.reverse")
    def capitalize(self): return self._f("str.capitalize")
    def left(self, n): return self._f("str.left", (n,))
    def right(self, n): return self._f("str.right", (n,))
    def repeat(self, n): return self._f("str.repeat", (n,))
    def split(self, pattern, regex: bool = False):
        return self._f("str.split", (pattern,), (regex,))
    def match(self, pattern): return self._f("str.match", (pattern,))
    def extract(self, pattern, index: int = 0):
        return self._f("str.extract", (pattern,), (index,))
    def extract_all(self, pattern, index: int = 0):
        return self._f("str.extract_all", (pattern,), (index,))
    def replace(self, pattern, replacement, regex: bool = False):
        return self._f("str.replace", (pattern, replacement), (regex,))
    def find(self, substr): return self._f("str.find", (substr,))
    def rpad(self, length, pad): return self._f("str.rpad", (length, pad))
    def lpad(self, length, pad): return self._f("str.lpad", (length, pad))
    def substr(self, start, length=None):
        return self._f("str.substr", (start, length))
    def to_date(self, format: str): return self._f("str.to_date", (), (format,))
    def to_datetime(self, format: str, timezone: Optional[str] = None):
        return self._f("str.to_datetime", (), (format, timezone))
    def normalize(self, remove_punct=False, lowercase=False, nfd_unicode=False,
                  white_space=False):
        return self._f("str.normalize", (),
                       (remove_punct, lowercase, nfd_unicode, white_space))
    def count_matches(self, patterns, whole_words=False, case_sensitive=True):
        pats = tuple(patterns) if isinstance(patterns, (list, tuple)) else (patterns,)
        return self._f("str.count_matches", (), (pats, whole_words, case_sensitive))
    def tokenize_encode(self, tokens_path: Optional[str] = None,
                        pattern: Optional[str] = None):
        return self._f("str.tokenize_encode", (), (tokens_path, pattern))
    def tokenize_decode(self, tokens_path: Optional[str] = None,
                        pattern: Optional[str] = None):
        return self._f("str.tokenize_decode", (), (tokens_path, pattern))


class ExpressionDatetimeNamespace(_Ns):
    """Reference surface: ``src/daft-functions-temporal``."""

    def date(self): return self._f("dt.date")
    def day(self): return self._f("dt.day")
    def hour(self): return self._f("dt.hour")
    def minute(self): return self._f("dt.minute")
    def second(self): return self._f("dt.second")
    def millisecond(self): return self._f("dt.millisecond")
    def microsecond(self): return self._f("dt.microsecond")
    def nanosecond(self): return self._f("dt.nanosecond")
    def time(self): return self._f("dt.time")
    def month(self): return self._f("dt.month")
    def quarter(self): return self._f("dt.quarter")
    def year(self): return self._f("dt.year")
    def day_of_week(self): return self._f("dt.day_of_week")
    def day_of_month(self): return self._f("dt.day")
    def day_of_year(self): return self._f("dt.day_of_year")
    def week_of_year(self): return self._f("dt.week_of_year")
    def truncate(self, interval: str, relative_to=None):
        return self._f("dt.truncate", (relative_to,) if relative_to is not None else (),
                       (interval,))
    def to_unix_epoch(self, timeunit: str = "s"):
        return self._f("dt.to_unix_epoch", (), (timeunit,))
    def strftime(self, format: Optional[str] = None):
        return self._f("dt.strftime", (), (format,))
    def total_seconds(self): return self._f("dt.total_seconds")


class ExpressionFloatNamespace(_Ns):
    def is_nan(self): return self._f("float.is_nan")
    def is_inf(self): return self._f("float.is_inf")
    def not_nan(self): return self._f("float.not_nan")
    def fill_nan(self, fill_value): return self._f("float.fill_nan", (fill_value,))


class ExpressionListNamespace(_Ns):
    """Reference surface: ``src/daft-functions-list``."""

    def join(self, delimiter): return self._f("list.join", (delimiter,))
    def value_counts(self): return self._f("list.value_counts")
    def count(self, mode: str = "valid"): return self._f("list.count", (), (mode,))
    def lengths(self): return self._f("list.length")
    def length(self): return self._f("list.length")
    def get(self, idx, default=None):
        return self._f("list.get", (idx, default))
    def slice(self, start, end=None): return self._f("list.slice", (start, end))
    def chunk(self, size: int): return self._f("list.chunk", (), (size,))
    def sum(self): return self._f("list.sum")
    def mean(self): return self._f("list.mean")
    def min(self): return self._f("list.min")
    def max(self): return self._f("list.max")
    def bool_and(self): return self._f("list.bool_and")
    def bool_or(self): return self._f("list.bool_or")
    def sort(self, desc=False, nulls_first=None):
        return self._f("list.sort", (), (bool(_const(desc)), nulls_first))
    def distinct(self): return self._f("list.distinct")
    def unique(self): return self.distinct()


class ExpressionStructNamespace(_Ns):
    def get(self, name: str): return self._f("struct.get", (), (name,))


class ExpressionMapNamespace(_Ns):
    def get(self, key): return self._f("map.get", (key,))


class ExpressionEmbeddingNamespace(_Ns):
    def cosine_distance(self, other):
        return self._f("embedding.cosine_distance", (other,))


class ExpressionImageNamespace(_Ns):
    """Reference surface: ``src/daft-image`` kernels."""

    def decode(self, on_error: str = "raise", mode: Optional[str] = None):
        return self._f("image.decode", (), (on_error, mode))
    def encode(self, image_format): return self._f("image.encode", (), (image_format,))
    def resize(self, w: int, h: int): return self._f("image.resize", (), (w, h))
    def crop(self, bbox): return self._f("image.crop", (Expression._to_expression(bbox),))
    def to_mode(self, mode: str): return self._f("image.to_mode", (), (mode,))


class ExpressionBinaryNamespace(_Ns):
    """Reference surface: ``src/daft-functions-binary`` (concat/slice/encode)."""

    def concat(self, other): return self._f("binary.concat", (other,))
    def length(self): return self._f("binary.length")
    def slice(self, start, length=None):
        return self._f("binary.slice", (start, length))
    def encode(self, codec: str): return self._f("binary.encode", (), (codec,))
    def decode(self, codec: str): return self._f("binary.decode", (), (codec,))
    def try_encode(self, codec: str):
        return self._f("binary.try_encode", (), (codec,))
    def try_decode(self, codec: str):
        return self._f("binary.try_decode", (), (codec,))


class ExpressionJsonNamespace(_Ns):
    """Reference surface: ``src/daft-functions-json`` (jq-style ``query``)."""

    def query(self, jq: str): return self._f("json.query", (), (jq,))


class ExpressionUrlNamespace(_Ns):
    """Reference surface: ``src/daft-functions-uri`` (url.download / url.upload)."""

    def download(self, max_connections: int = 32, on_error: str = "raise",
                 io_config=None):
        return self._f("url.download", (), (max_connections, on_error, io_config))

    def upload(self, location, max_connections: int = 32, on_error: str = "raise",
               io_config=None):
        return self._f("url.upload", (Expression._to_expression(location),),
                       (max_connections, on_error, io_config))

    def parse(self):
        return self._f("url.parse")


class ExpressionPartitioningNamespace(_Ns):
    def days(self): return self._f("partitioning.days")
    def hours(self): return self._f("partitioning.hours")
    def months(self): return self._f("partitioning.months")
    def years(self): return self._f("partitioning.years")
    def iceberg_bucket(self, n: int): return self._f("partitioning.iceberg_bucket", (), (n,))
    def iceberg_truncate(self, w: int): return self._f("partitioning.iceberg_truncate", (), (w,))


def _const(v):
    return v.params[0] if isinstance(v, Expression) and v.op == "lit" else v


# ---------------------------------------------------------------------------
# free functions


def col(name: str) -> Expression:
    return Expression._col(name)


def element() -> Expression:
    """Placeholder for the current list element in list.map-style exprs."""
    return Expression("element", ())


def lit(value: Any) -> Expression:
    return Expression._lit(value)


def list_(*exprs) -> Expression:
    return Expression("list", tuple(Expression._to_expression(e) for e in exprs))


def struct(*exprs) -> Expression:
    return Expression("struct_make", tuple(Expression._to_expression(e) for e in exprs))


def coalesce(*exprs) -> Expression:
    return Expression("coalesce", tuple(Expression._to_expression(e) for e in exprs))


def interval(years=0, months=0, days=0, hours=0, minutes=0, seconds=0,
             millis=0, nanos=0) -> Expression:
    months_total = years * 12 + months
    nanos_total = (((hours * 60 + minutes) * 60 + seconds) * 1000 + millis) \
        * 1_000_000 + nanos
    return Expression("lit_interval", (), (months_total, days, nanos_total))


# ---------------------------------------------------------------------------
# projections


class ExpressionsProjection:
    """An ordered list of expressions with unique output names."""

    def __init__(self, exprs: List[Expression]):
        seen = set()
        for e in exprs:
            n = e.name()
            if n in seen:
                raise ValueError(f"duplicate output name in projection: {n}")
            seen.add(n)
        self._exprs = list(exprs)

    @classmethod
    def from_schema(cls, schema: Schema) -> "ExpressionsProjection":
        return cls([col(f.name) for f in schema])

    def __iter__(self):
        return iter(self._exprs)

    def __len__(self):
        return len(self._exprs)

    def to_name_set(self):
        return {e.name() for e in self._exprs}

    def input_mapping(self) -> "dict[str, str]":
        """output name -> input column name for passthrough (possibly aliased) cols."""
        out = {}
        for e in self._exprs:
            inner = e._unalias()
            if inner.op == "col":
                out[e.name()] = inner.params[0]
        return out

    def to_inner_py_exprs(self):
        return self._exprs


def _param_key(p):
    if callable(p) and not isinstance(p, (DataType,)):
        return ("callable", id(p))
    if isinstance(p, (list, dict, set)):
        return repr(p)
    return p


def _repr_expr(e: Expression) -> str:
    binops = {"add": "+", "sub": "-", "mul": "*", "div": "/", "floordiv": "//",
              "mod": "%", "lt": "<", "le": "<=", "gt": ">", "ge": ">=",
              "eq": "==", "neq": "!=", "and": "&", "or": "|", "xor": "^",
              "pow": "**"}
    if e.op == "col":
        return f"col({e.params[0]})"
    if e.op == "lit":
        return f"lit({e.params[0]!r})"
    if e.op == "alias":
        return f"{e.args[0]!r}.alias({e.params[0]!r})"
    if e.op in binops:
        return f"({e.args[0]!r} {binops[e.op]} {e.args[1]!r})"
    if e.op == "not":
        return f"~{e.args[0]!r}"
    inner = ", ".join(repr(a) for a in e.args)
    if e.params:
        inner += (", " if inner else "") + ", ".join(repr(p) for p in e.params)
    return f"{e.op}({inner})"
