"""Host implementations of namespaced scalar functions (str/dt/float/list/…).

Capability mirror of the reference's function crates
(``src/daft-functions-utf8``, ``-temporal``, ``-list``, ``daft-image`` …),
implemented over Arrow C++ compute + numpy.
"""

from __future__ import annotations

import datetime
import re
from typing import List

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..datatype import DataType, TimeUnit
from ..schema import Field
from ..series import Series


def _sa(s: Series) -> pa.Array:
    return s.to_arrow().cast(pa.large_string())


def eval_function(op: str, e, kids: List[Series], b, out_field: Field) -> Series:
    ns, fn = op.split(".", 1)
    s = kids[0]
    name = s.name()

    if ns == "str":
        return _str_fn(fn, e, kids, b, out_field)
    if ns == "dt":
        return _dt_fn(fn, e, kids, b, out_field)
    if ns == "float":
        arr = s.to_arrow()
        if fn == "is_nan":
            return Series.from_arrow(pc.is_nan(arr), name)
        if fn == "is_inf":
            return Series.from_arrow(pc.is_inf(arr), name)
        if fn == "not_nan":
            return Series.from_arrow(pc.invert(pc.is_nan(arr)), name)
        if fn == "fill_nan":
            fill = b(kids[1]).cast(s.datatype())
            mask = pc.fill_null(pc.is_nan(arr), False)
            return Series.from_arrow(
                pc.if_else(mask, fill.to_arrow(), arr), name)
    if ns == "list":
        return _list_fn(fn, e, kids, b, out_field)
    if ns == "struct":
        if fn == "get":
            sa = s.to_arrow()
            child = sa.field(e.params[0])
            return Series.from_arrow(child, e.params[0])
    if ns == "map":
        if fn == "get":
            key = kids[1].to_pylist()[0]
            out = []
            for m in s.to_pylist():
                if m is None:
                    out.append(None)
                else:
                    d = dict(m) if not isinstance(m, dict) else m
                    out.append(d.get(key))
            return Series.from_pylist(out, "value", dtype=out_field.dtype)
    if ns == "embedding":
        if fn == "cosine_distance":
            a = s.to_numpy().astype(np.float64)
            o = b(kids[1]).to_numpy().astype(np.float64)
            if o.ndim == 1:
                o = np.broadcast_to(o[None, :], a.shape)
            num = (a * o).sum(axis=1)
            den = np.linalg.norm(a, axis=1) * np.linalg.norm(o, axis=1)
            with np.errstate(all="ignore"):
                out = 1.0 - num / den
            return Series.from_arrow(pa.array(out), name)
    if ns == "image":
        from ..functions.image import eval_image_fn
        return eval_image_fn(fn, e, kids, out_field)
    if ns == "partitioning":
        return _partitioning_fn(fn, e, s, out_field)
    if ns == "binary":
        return _binary_fn(fn, e, kids, b, out_field)
    if ns == "json":
        return _json_fn(fn, e, s, out_field)
    if ns == "url":
        return _url_fn(fn, e, kids, b, out_field)
    raise NotImplementedError(f"host function {op}")


def _binary_fn(fn, e, kids, b, out_field) -> Series:
    """Reference: ``src/daft-functions-binary`` (concat/slice/encode/decode)."""
    s = kids[0]
    name = s.name()
    arr = s.to_arrow().cast(pa.large_binary())
    if fn == "concat":
        other = b(kids[1]).to_arrow().cast(pa.large_binary())
        return Series.from_arrow(
            pc.binary_join_element_wise(
                arr, other, pa.scalar(b"", type=pa.large_binary())), name)
    if fn == "length":
        return Series.from_arrow(pc.binary_length(arr).cast(pa.uint64()), name)
    if fn == "slice":
        start = b(kids[1]).to_pylist()
        length = b(kids[2]).to_pylist() if len(kids) > 2 else [None] * len(s)
        out = []
        for v, st, ln in zip(s.to_pylist(), start, length):
            if v is None or st is None:
                out.append(None)
            else:
                end = None if ln is None else st + ln
                out.append(bytes(v)[st:end])
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    if fn in ("encode", "decode", "try_encode", "try_decode"):
        codec = e.params[0]
        lenient = fn.startswith("try_")
        decode = "decode" in fn
        out = []
        for v in s.to_pylist():
            if v is None:
                out.append(None)
                continue
            try:
                out.append(_codec_apply(bytes(v), codec, decode))
            except Exception:
                if lenient:
                    out.append(None)
                else:
                    raise
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    raise NotImplementedError(f"binary.{fn}")


def norm_codec(codec) -> str:
    """Canonical codec spelling — shared by typing (typing.py binary rules)
    and evaluation so schema and execution never disagree."""
    return str(codec).lower().replace("_", "-")


def _codec_apply(data: bytes, codec: str, decode: bool):
    codec = norm_codec(codec)
    import base64
    import gzip
    import zlib
    if codec == "base64":
        return (base64.b64decode(data, validate=True) if decode
                else base64.b64encode(data))
    if codec == "hex":
        return bytes.fromhex(data.decode()) if decode else data.hex().encode()
    if codec == "gzip":
        return gzip.decompress(data) if decode else gzip.compress(data)
    if codec == "zlib":
        return zlib.decompress(data) if decode else zlib.compress(data)
    if codec == "deflate":
        if decode:
            return zlib.decompress(data, wbits=-zlib.MAX_WBITS)
        c = zlib.compressobj(wbits=-zlib.MAX_WBITS)
        return c.compress(data) + c.flush()
    if codec in ("utf-8", "utf8"):
        return data.decode("utf-8") if decode else data
    raise ValueError(f"unsupported codec {codec!r}")


def _json_fn(fn, e, s: Series, out_field) -> Series:
    """jq-style path queries (reference: ``src/daft-functions-json`` via jaq).

    Supported filter subset: ``.``, ``.field``, ``.field1.field2``,
    ``.field[idx]``, ``.[idx]``, ``.field[]`` (array iteration → JSON array),
    and pipes ``f1 | f2``.
    """
    import json as _json
    if fn != "query":
        raise NotImplementedError(f"json.{fn}")
    query = e.params[0]
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        try:
            doc = _json.loads(v)
            results, iterated = _jq_apply(doc, query)
        except Exception:
            out.append(None)
            continue
        if iterated:
            # array iteration contract: always a JSON array, even for 0/1 hits
            out.append(_json.dumps(results))
        elif not results:
            out.append(None)
        else:
            r = results[0]
            out.append(r if isinstance(r, str)
                       else (None if r is None else _json.dumps(r)))
    return Series.from_pylist(out, s.name(), dtype=out_field.dtype)


def _jq_apply(doc, query: str):
    values = [doc]
    iterated = "[]" in query
    for stage in (p.strip() for p in query.split("|")):
        if stage in (".", ""):
            continue
        next_vals = []
        for val in values:
            next_vals.extend(_jq_stage(val, stage))
        values = next_vals
    return values, iterated


def _jq_stage(val, stage: str):
    # tokenize a path like .a.b[0].c[] into steps
    steps = re.findall(r"\.(?:[A-Za-z_][A-Za-z0-9_]*)?|\[-?\d*\]", stage)
    cur = [val]
    for step in steps:
        nxt = []
        for v in cur:
            if v is None:
                nxt.append(None)
            elif step == ".":
                nxt.append(v)
            elif step.startswith("."):
                key = step[1:]
                nxt.append(v.get(key) if isinstance(v, dict) else None)
            elif step == "[]":
                if isinstance(v, list):
                    nxt.extend(v)
            else:
                idx = int(step[1:-1])
                nxt.append(v[idx] if isinstance(v, list)
                           and -len(v) <= idx < len(v) else None)
        cur = nxt
    return cur


def _url_fn(fn, e, kids, b, out_field) -> Series:
    """Reference: ``src/daft-functions-uri`` — async multi-get through
    daft-io inside expression eval. Host equivalent: IOClient + thread pool
    bounded at ``max_connections``."""
    import concurrent.futures as cf
    import urllib.parse as _up

    from ..io.object_io import get_io_client

    s = kids[0]
    name = s.name()
    if fn == "parse":
        out = []
        for v in s.to_pylist():
            if v is None:
                out.append(None)
                continue
            try:
                p = _up.urlparse(v)
                out.append({"scheme": p.scheme, "host": p.hostname,
                            "port": p.port, "path": p.path,
                            "query": p.query, "fragment": p.fragment})
            except ValueError:  # e.g. non-numeric port — null the row
                out.append(None)
        return Series.from_pylist(out, name, dtype=out_field.dtype)

    max_conn, on_error, io_config = e.params[0], e.params[1], e.params[2]
    client = get_io_client(io_config)

    if fn == "download":
        urls = s.to_pylist()

        def fetch(u):
            if u is None:
                return None
            try:
                return client.get(u)
            except Exception:
                if on_error == "null":
                    return None
                raise

        with cf.ThreadPoolExecutor(max_workers=max(1, max_conn)) as pool:
            out = list(pool.map(fetch, urls))
        return Series.from_pylist(out, name, dtype=out_field.dtype)

    if fn == "upload":
        data = s.to_pylist()
        locations = b(kids[1]).to_pylist()

        import uuid

        def push(args):
            i, (blob, loc) = args
            if blob is None or loc is None:
                return None
            if isinstance(blob, str):
                blob = blob.encode()
            # uuid per row: unique across partitions/workers (the reference
            # names uploaded objects the same way)
            path = loc.rstrip("/") + f"/{uuid.uuid4().hex}"
            try:
                client.put(path, bytes(blob))
            except Exception:
                if on_error == "null":
                    return None
                raise
            return path

        with cf.ThreadPoolExecutor(max_workers=max(1, max_conn)) as pool:
            out = list(pool.map(push, enumerate(zip(data, locations))))
        return Series.from_pylist(out, name, dtype=out_field.dtype)

    raise NotImplementedError(f"url.{fn}")


def _str_fn(fn, e, kids, b, out_field) -> Series:
    s = kids[0]
    name = s.name()
    if fn in ("tokenize_encode", "tokenize_decode"):
        # decode's input is a token-id list column, not a string array
        from ..functions.tokenize import eval_tokenize
        return eval_tokenize(fn, e, kids, out_field)
    arr = _sa(s)
    if fn == "contains":
        pat = kids[1].to_pylist()[0]
        return Series.from_arrow(pc.match_substring(arr, pat), name)
    if fn == "startswith":
        return Series.from_arrow(pc.starts_with(arr, kids[1].to_pylist()[0]), name)
    if fn == "endswith":
        return Series.from_arrow(pc.ends_with(arr, kids[1].to_pylist()[0]), name)
    if fn == "concat":
        other = b(kids[1])
        return Series.from_arrow(
            pc.binary_join_element_wise(arr, _sa(other),
                                        pa.scalar("", type=pa.large_string())),
            name)
    if fn == "length":
        return Series.from_arrow(pc.utf8_length(arr), name).cast(DataType.uint64())
    if fn == "length_bytes":
        return Series.from_arrow(pc.binary_length(arr), name).cast(DataType.uint64())
    if fn == "lower":
        return Series.from_arrow(pc.utf8_lower(arr), name)
    if fn == "upper":
        return Series.from_arrow(pc.utf8_upper(arr), name)
    if fn == "lstrip":
        return Series.from_arrow(pc.utf8_ltrim_whitespace(arr), name)
    if fn == "rstrip":
        return Series.from_arrow(pc.utf8_rtrim_whitespace(arr), name)
    if fn == "strip":
        return Series.from_arrow(pc.utf8_trim_whitespace(arr), name)
    if fn == "reverse":
        return Series.from_arrow(pc.utf8_reverse(arr), name)
    if fn == "capitalize":
        return Series.from_arrow(pc.utf8_capitalize(arr), name)
    if fn == "left":
        n = kids[1].to_pylist()[0]
        return Series.from_arrow(pc.utf8_slice_codeunits(arr, 0, n), name)
    if fn == "right":
        n = kids[1].to_pylist()[0]
        vals = arr.to_pylist()
        return Series.from_pylist(
            [None if v is None else v[-n:] if n else "" for v in vals], name)
    if fn == "repeat":
        n = b(kids[1]).to_pylist()
        vals = arr.to_pylist()
        return Series.from_pylist(
            [None if v is None or c is None else v * c
             for v, c in zip(vals, n)], name)
    if fn == "split":
        pat = kids[1].to_pylist()[0]
        regex = e.params[0]
        out = (pc.split_pattern_regex if regex else pc.split_pattern)(arr, pat)
        return Series.from_arrow(out, name)
    if fn == "match":
        return Series.from_arrow(
            pc.match_substring_regex(arr, kids[1].to_pylist()[0]), name)
    if fn == "extract":
        pat, idx = kids[1].to_pylist()[0], e.params[0]
        rx = re.compile(pat)
        out = []
        for v in arr.to_pylist():
            if v is None:
                out.append(None)
                continue
            m = rx.search(v)
            out.append(m.group(idx) if m else None)
        return Series.from_pylist(out, name, dtype=DataType.string())
    if fn == "extract_all":
        pat, idx = kids[1].to_pylist()[0], e.params[0]
        rx = re.compile(pat)
        out = []
        for v in arr.to_pylist():
            if v is None:
                out.append(None)
            else:
                out.append([(m.group(idx)) for m in rx.finditer(v)])
        return Series.from_pylist(out, name, dtype=DataType.list(DataType.string()))
    if fn == "replace":
        pat, rep = kids[1].to_pylist()[0], kids[2].to_pylist()[0]
        regex = e.params[0]
        fnc = pc.replace_substring_regex if regex else pc.replace_substring
        return Series.from_arrow(fnc(arr, pattern=pat, replacement=rep), name)
    if fn == "find":
        sub = kids[1].to_pylist()[0]
        return Series.from_arrow(pc.find_substring(arr, sub), name) \
            .cast(DataType.int64())
    if fn in ("rpad", "lpad"):
        length = b(kids[1]).to_pylist()
        pad = b(kids[2]).to_pylist()
        vals = arr.to_pylist()
        out = []
        for v, L, p in zip(vals, length, pad):
            if v is None or L is None or p is None:
                out.append(None)
            elif len(v) >= L:
                out.append(v[:L])
            else:
                padstr = (p * L)[: L - len(v)]
                out.append(v + padstr if fn == "rpad" else padstr + v)
        return Series.from_pylist(out, name)
    if fn == "substr":
        start = b(kids[1]).to_pylist()
        lens = b(kids[2]).to_pylist() if len(kids) > 2 else [None] * len(arr)
        vals = arr.to_pylist()
        out = []
        for v, st, ln in zip(vals, start, lens):
            if v is None or st is None:
                out.append(None)
            else:
                out.append(v[st:] if ln is None else v[st:st + ln])
        return Series.from_pylist(out, name)
    if fn == "to_date":
        fmt = e.params[0]
        out = pc.strptime(arr, format=fmt, unit="us", error_is_null=True)
        return Series.from_arrow(out, name).cast(DataType.date())
    if fn == "to_datetime":
        fmt, tz = e.params
        out = pc.strptime(arr, format=fmt, unit="us", error_is_null=True)
        s2 = Series.from_arrow(out, name)
        return s2.cast(DataType.timestamp(TimeUnit.us, tz))
    if fn == "normalize":
        remove_punct, lowercase, nfd_unicode, white_space = e.params
        import string as _string
        import unicodedata
        vals = arr.to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            if nfd_unicode:
                v = unicodedata.normalize("NFD", v)
            if lowercase:
                v = v.lower()
            if remove_punct:
                v = v.translate(str.maketrans("", "", _string.punctuation))
            if white_space:
                v = " ".join(v.split())
            out.append(v)
        return Series.from_pylist(out, name)
    if fn == "count_matches":
        pats, whole_words, case_sensitive = e.params
        flags = 0 if case_sensitive else re.IGNORECASE
        parts = [re.escape(p) for p in pats]
        pat = "|".join(rf"\b(?:{p})\b" if whole_words else f"(?:{p})" for p in parts)
        rx = re.compile(pat, flags)
        out = [None if v is None else len(rx.findall(v)) for v in arr.to_pylist()]
        return Series.from_pylist(out, name, dtype=DataType.uint64())
    raise NotImplementedError(f"str.{fn}")


_EPOCH = datetime.date(1970, 1, 1)


def _dt_fn(fn, e, kids, b, out_field) -> Series:
    s = kids[0]
    name = s.name()
    arr = s.to_arrow()
    if fn == "date":
        return Series.from_arrow(arr.cast(pa.date32()), name)
    simple = {"day": pc.day, "hour": pc.hour, "minute": pc.minute,
              "second": pc.second, "millisecond": pc.millisecond,
              "microsecond": pc.microsecond, "nanosecond": pc.nanosecond,
              "month": pc.month, "quarter": pc.quarter, "year": pc.year,
              "day_of_year": pc.day_of_year}
    if fn in simple:
        out = simple[fn](arr)
        return Series.from_arrow(out, name).cast(out_field.dtype)
    if fn == "day_of_week":
        return Series.from_arrow(pc.day_of_week(arr), name).cast(out_field.dtype)
    if fn == "week_of_year":
        return Series.from_arrow(pc.iso_week(arr), name).cast(out_field.dtype)
    if fn == "time":
        return Series.from_arrow(arr.cast(pa.time64("us")), name)
    if fn == "truncate":
        interval = e.params[0]
        qty, unit = interval.split(" ", 1) if " " in interval else ("1", interval)
        unit = unit.rstrip("s")
        mapping = {"day": "day", "hour": "hour", "minute": "minute",
                   "second": "second", "week": "week", "month": "month",
                   "year": "year", "millisecond": "millisecond",
                   "microsecond": "microsecond"}
        out = pc.floor_temporal(arr, multiple=int(qty), unit=mapping[unit])
        return Series.from_arrow(out, name)
    if fn == "to_unix_epoch":
        tu = e.params[0]
        ts = arr.cast(pa.timestamp("us")) if not pa.types.is_timestamp(arr.type) else arr
        us = ts.cast(pa.int64())
        div = {"s": 1_000_000, "ms": 1_000, "us": 1, "ns": 1}[tu]
        if tu == "ns":
            out = pc.multiply(us, 1000)
        else:
            out = pc.divide(us, div)
        return Series.from_arrow(out, name).cast(DataType.int64())
    if fn == "strftime":
        fmt = e.params[0] or ("%Y-%m-%d" if pa.types.is_date(arr.type)
                              else "%Y-%m-%d %H:%M:%S.%f")
        return Series.from_arrow(pc.strftime(arr, format=fmt), name)
    if fn == "total_seconds":
        dur = arr.cast(pa.duration("us")).cast(pa.int64())
        return Series.from_arrow(pc.divide(dur, 1_000_000), name)
    raise NotImplementedError(f"dt.{fn}")


def _list_fn(fn, e, kids, b, out_field) -> Series:
    s = kids[0]
    name = s.name()
    arr = s.to_arrow()
    if fn == "length":
        return Series.from_arrow(pc.list_value_length(arr), name) \
            .cast(DataType.uint64())
    if fn == "count":
        mode = e.params[0]
        vals = arr.to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append(0 if mode != "null" else 0)
            elif mode == "valid":
                out.append(sum(1 for x in v if x is not None))
            elif mode == "null":
                out.append(sum(1 for x in v if x is None))
            else:
                out.append(len(v))
        return Series.from_pylist(out, name, dtype=DataType.uint64())
    if fn == "join":
        delim = b(kids[1]).to_pylist()
        vals = arr.to_pylist()
        out = []
        for v, d in zip(vals, delim if len(delim) == len(vals) else delim * len(vals)):
            if v is None or d is None:
                out.append(None)
            else:
                out.append(d.join(x for x in v if x is not None))
        return Series.from_pylist(out, name)
    if fn == "get":
        idx = b(kids[1]).to_pylist()
        default = kids[2].to_pylist()[0] if len(kids) > 2 and len(kids[2]) else None
        vals = arr.to_pylist()
        if len(idx) == 1:
            idx = idx * len(vals)
        out = []
        for v, i in zip(vals, idx):
            if v is None or i is None:
                out.append(default)
            elif -len(v) <= i < len(v):
                out.append(v[i])
            else:
                out.append(default)
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    if fn == "slice":
        start = b(kids[1]).to_pylist()
        end = b(kids[2]).to_pylist() if len(kids) > 2 else None
        vals = arr.to_pylist()
        if len(start) == 1:
            start = start * len(vals)
        out = []
        for i, v in enumerate(vals):
            if v is None:
                out.append(None)
                continue
            st = start[i]
            en = end[i] if end is not None and end[i] is not None else len(v)
            out.append(v[st:en])
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    if fn == "chunk":
        size = e.params[0]
        vals = arr.to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            else:
                out.append([v[i:i + size] for i in range(0, len(v) - size + 1, size)])
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    if fn in ("sum", "mean", "min", "max", "bool_and", "bool_or"):
        vals = arr.to_pylist()
        out = []
        for v in vals:
            xs = [x for x in (v or []) if x is not None]
            if not xs:
                out.append(None)
            elif fn == "sum":
                out.append(sum(xs))
            elif fn == "mean":
                out.append(sum(xs) / len(xs))
            elif fn == "min":
                out.append(min(xs))
            elif fn == "max":
                out.append(max(xs))
            elif fn == "bool_and":
                out.append(all(xs))
            else:
                out.append(any(xs))
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    if fn == "sort":
        desc, nulls_first = e.params
        vals = arr.to_pylist()
        nf = nulls_first if nulls_first is not None else desc
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            nn = sorted((x for x in v if x is not None), reverse=bool(desc))
            nulls = [None] * (len(v) - len(nn))
            out.append(nulls + nn if nf else nn + nulls)
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    if fn == "distinct":
        vals = arr.to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            seen, d = set(), []
            for x in v:
                if x is not None and x not in seen:
                    seen.add(x)
                    d.append(x)
            out.append(d)
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    if fn == "value_counts":
        vals = arr.to_pylist()
        out = []
        for v in vals:
            counts = {}
            for x in (v or []):
                if x is not None:
                    counts[x] = counts.get(x, 0) + 1
            out.append(list(counts.items()))
        return Series.from_pylist(out, name, dtype=out_field.dtype)
    raise NotImplementedError(f"list.{fn}")


def _partitioning_fn(fn, e, s: Series, out_field) -> Series:
    name = s.name()
    arr = s.to_arrow()
    if fn == "days":
        return Series.from_arrow(arr.cast(pa.date32()), name)
    if fn == "hours":
        us = arr.cast(pa.timestamp("us")).cast(pa.int64())
        return Series.from_arrow(pc.divide(us, 3600 * 1_000_000), name) \
            .cast(DataType.int32())
    if fn == "months":
        y = pc.year(arr)
        m = pc.month(arr)
        out = pc.add(pc.multiply(pc.subtract(y, 1970), 12), pc.subtract(m, 1))
        return Series.from_arrow(out, name).cast(DataType.int32())
    if fn == "years":
        return Series.from_arrow(pc.subtract(pc.year(arr), 1970), name) \
            .cast(DataType.int32())
    if fn == "iceberg_bucket":
        n = e.params[0]
        h = s.hash().to_numpy()
        return Series.from_arrow(pa.array((h % np.uint64(n)).astype(np.int32)), name)
    if fn == "iceberg_truncate":
        w = e.params[0]
        if s.datatype().is_string():
            vals = [None if v is None else v[:w] for v in arr.to_pylist()]
            return Series.from_pylist(vals, name)
        v = s.to_numpy()
        return Series.from_arrow(pa.array(v - (v % w)), name)
    raise NotImplementedError(f"partitioning.{fn}")
