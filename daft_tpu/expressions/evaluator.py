"""Host expression evaluation over Arrow C++ compute.

This is the complete-coverage tier; the TPU tier
(``daft_tpu.device.compiler``) accelerates the device-representable subset.
Reference capability: ``eval_expression_list``
(``src/daft-recordbatch/src/lib.rs:755``).
"""

from __future__ import annotations

import datetime
import math
from typing import Any, Dict, List

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..datatype import DataType, TimeUnit
from ..schema import Schema
from ..series import Series
from .expressions import Expression


def eval_expression(e: Expression, columns: Dict[str, Series], length: int) -> Series:
    """Evaluate ``e`` against named input columns; result broadcast to ``length``."""
    s = _eval(e, columns, length)
    out_name = e.name()
    if s.name() != out_name:
        s = s.rename(out_name)
    if len(s) == 1 and length != 1:
        s = s.broadcast(length)
    return s


def _arrow(s: Series) -> pa.Array:
    return s.to_arrow()


def _bin_numeric(op, l: Series, r: Series, out_dtype: DataType) -> Series:
    if len(l) == 1 and len(r) != 1:
        l = l.broadcast(len(r))
    if len(r) == 1 and len(l) != 1:
        r = r.broadcast(len(l))
    la, ra = l.to_arrow(), r.to_arrow()
    fn = {"add": pc.add, "sub": pc.subtract, "mul": pc.multiply,
          "div": pc.divide, "pow": pc.power}[op]
    if op == "div":
        la = la.cast(pa.float64())
        ra = ra.cast(pa.float64())
    out = fn(la, ra)
    res = Series.from_arrow(out, l.name())
    return res.cast(out_dtype) if res.datatype() != out_dtype else res


_CMP = {"lt": pc.less, "le": pc.less_equal, "gt": pc.greater,
        "ge": pc.greater_equal, "eq": pc.equal, "neq": pc.not_equal}


def _interval_shift(s: Series, months: int, days: int, nanos: int) -> Series:
    """Shift a date/timestamp series by a calendar interval. Month shifts
    clamp to month length (SQL calendar arithmetic); day/nano-only shifts
    over columns run vectorized — the interpreted loop only survives for
    the month-shift-over-column case (rare; literals are 1-row)."""
    import calendar
    import datetime as _dt
    if months == 0 and len(s) > 1:
        arr = s.to_arrow()
        td = pa.scalar(_dt.timedelta(days=days, microseconds=nanos // 1000))
        if pa.types.is_date32(arr.type) or pa.types.is_date64(arr.type):
            out = pc.add(arr.cast(pa.timestamp("us")), td).cast(arr.type)
        else:
            out = pc.add(arr, td)
        return Series.from_arrow(out, s.name())
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        if months:
            y = v.year + (v.month - 1 + months) // 12
            m = (v.month - 1 + months) % 12 + 1
            d = min(v.day, calendar.monthrange(y, m)[1])
            v = v.replace(year=y, month=m, day=d)
        if days:
            v = v + _dt.timedelta(days=days)
        if nanos:
            v = v + _dt.timedelta(microseconds=nanos // 1000)
        out.append(v)
    return Series.from_pylist(out, s.name(), dtype=s.datatype())


def _eval(e: Expression, cols: Dict[str, Series], n: int) -> Series:
    op = e.op

    if op == "col":
        name = e.params[0]
        if name not in cols:
            raise ValueError(f"unresolved column {name!r}; "
                             f"available: {list(cols.keys())}")
        return cols[name]
    if op == "lit":
        v = e.params[0]
        if isinstance(v, Series):
            return v
        dt = DataType.null() if v is None else None
        return Series.from_pylist([v], "literal", dtype=dt)
    if op == "alias":
        return _eval(e.args[0], cols, n).rename(e.params[0])
    if op == "cast":
        return _eval(e.args[0], cols, n).cast(e.params[0])
    if op in ("add", "sub") and any(a.op == "lit_interval" for a in e.args):
        # date/timestamp ± INTERVAL: calendar-aware shift (months clamp to
        # month length per SQL, days/nanos are exact)
        iv = next(a for a in e.args if a.op == "lit_interval")
        other = next(a for a in e.args if a.op != "lit_interval")
        base = _eval(other, cols, n)
        months, days, nanos = iv.params
        sign = 1 if op == "add" else -1
        return _interval_shift(base, sign * months, sign * days,
                               sign * nanos)

    # evaluate children
    kids = [_eval(a, cols, n) for a in e.args]
    # broadcast scalars to the non-scalar operand length (0-length included)
    max_len = next((len(k) for k in kids if len(k) != 1), 1)

    def b(s: Series) -> Series:
        return s.broadcast(max_len) if len(s) == 1 and max_len != 1 else s

    schema = Schema([c.field() for c in cols.values()])
    out_field = e.to_field(schema)

    if op in ("add", "sub", "mul", "div", "pow"):
        l, r = kids
        if op == "add" and l.datatype().is_string():
            return Series.from_arrow(
                pc.binary_join_element_wise(
                    b(l).to_arrow().cast(pa.large_string()),
                    b(r).to_arrow().cast(pa.large_string()),
                    pa.scalar("", type=pa.large_string())), l.name())
        if l.datatype().is_temporal() or r.datatype().is_temporal():
            return _temporal_arith(op, b(l), b(r), out_field.dtype)
        return _bin_numeric(op, l, r, out_field.dtype)
    if op == "floordiv":
        l, r = b(kids[0]), b(kids[1])
        la, ra = l.to_arrow().cast(pa.float64()), r.to_arrow().cast(pa.float64())
        out = pc.floor(pc.divide(la, ra))
        return Series.from_arrow(out, l.name()).cast(out_field.dtype)
    if op == "mod":
        l, r = b(kids[0]), b(kids[1])
        lv, rv = l.to_numpy(), r.to_numpy()
        valid = ~(pd_isnull(lv) | pd_isnull(rv))
        with np.errstate(all="ignore"):
            res = np.where(valid, np.mod(np.nan_to_num(lv.astype(np.float64)),
                                         np.where(rv == 0, 1, rv).astype(np.float64)),
                           np.nan)
        arr = pa.array(res, from_pandas=True)
        return Series.from_arrow(arr, l.name()).cast(out_field.dtype)

    if op in _CMP:
        l, r = b(kids[0]), b(kids[1])
        la, ra = l.to_arrow(), r.to_arrow()
        if la.type != ra.type:
            st = DataType.from_arrow_type(la.type) if not l.datatype().is_null() \
                else r.datatype()
            try:
                from .typing import supertype
                stt = supertype(l.datatype(), r.datatype()).to_arrow()
                la, ra = la.cast(stt), ra.cast(stt)
            except Exception:
                pass
        return Series.from_arrow(_CMP[op](la, ra), l.name())
    if op == "eq_null_safe":
        l, r = b(kids[0]), b(kids[1])
        eqv = pc.equal(l.to_arrow(), r.to_arrow())
        both_null = pc.and_(pc.is_null(l.to_arrow()), pc.is_null(r.to_arrow()))
        either_null = pc.or_(pc.is_null(l.to_arrow()), pc.is_null(r.to_arrow()))
        filled = pc.fill_null(eqv, False)
        out = pc.if_else(either_null, both_null, filled)
        return Series.from_arrow(out, l.name())

    if op in ("and", "or", "xor"):
        l, r = b(kids[0]), b(kids[1])
        if l.datatype().is_integer():
            fn = {"and": pc.bit_wise_and, "or": pc.bit_wise_or,
                  "xor": pc.bit_wise_xor}[op]
            return Series.from_arrow(fn(l.to_arrow(), r.to_arrow()), l.name())
        fn = {"and": pc.and_kleene, "or": pc.or_kleene, "xor": pc.xor}[op]
        return Series.from_arrow(fn(l.to_arrow().cast(pa.bool_()),
                                    r.to_arrow().cast(pa.bool_())), l.name())
    if op == "not":
        return Series.from_arrow(pc.invert(kids[0].to_arrow().cast(pa.bool_())),
                                 kids[0].name())
    if op == "negate":
        return Series.from_arrow(pc.negate(kids[0].to_arrow()), kids[0].name())
    if op == "abs":
        return Series.from_arrow(pc.abs(kids[0].to_arrow()), kids[0].name())
    if op == "is_null":
        return kids[0].is_null()
    if op == "not_null":
        return kids[0].not_null()
    if op == "fill_null":
        l, r = kids
        if len(r) == 1:
            return Series.from_arrow(
                pc.fill_null(l.to_arrow(), r.to_arrow()[0]), l.name()) \
                if not l.datatype().is_null() else b(r).rename(l.name())
        return Series.from_arrow(
            pc.if_else(pc.is_valid(l.to_arrow()), l.to_arrow(),
                       b(r).to_arrow().cast(l.to_arrow().type)), l.name())
    if op == "is_in":
        l = kids[0]
        items = kids[1:]
        if len(items) == 1 and items[0].datatype().is_list():
            vals = items[0].to_pylist()[0]
            value_set = pa.array(vals)
        else:
            value_set = pa.array([i.to_pylist()[0] for i in items])
        try:
            value_set = value_set.cast(l.to_arrow().type)
        except Exception:
            pass
        raw = pc.is_in(l.to_arrow(), value_set=value_set)
        out = pc.if_else(pc.is_valid(l.to_arrow()), raw,
                         pa.nulls(len(l), type=pa.bool_()))
        return Series.from_arrow(out, l.name())
    if op == "between":
        v, lo, hi = b(kids[0]), b(kids[1]), b(kids[2])
        out = pc.and_(pc.greater_equal(v.to_arrow(), lo.to_arrow()),
                      pc.less_equal(v.to_arrow(), hi.to_arrow()))
        return Series.from_arrow(out, v.name())
    if op == "if_else":
        pred, t, f = b(kids[0]), b(kids[1]), b(kids[2])
        if t.is_pyobject() or f.is_pyobject():
            pm = pred.to_pylist()
            tv, fv = t.to_pylist(), f.to_pylist()
            return Series.from_pyobjects(
                [tv[i] if pm[i] else (fv[i] if pm[i] is not None else None)
                 for i in range(max_len)], t.name())
        target = out_field.dtype.to_arrow()
        return Series.from_arrow(
            pc.if_else(pred.to_arrow(),
                       t.to_arrow().cast(target), f.to_arrow().cast(target)),
            t.name())
    if op == "coalesce":
        cur = b(kids[0]).cast(out_field.dtype)
        for k in kids[1:]:
            ka = b(k).cast(out_field.dtype)
            cur = Series.from_arrow(
                pc.if_else(pc.is_valid(cur.to_arrow()), cur.to_arrow(),
                           ka.to_arrow()), cur.name())
        return cur

    if op in ("ceil", "floor", "sign"):
        fn = {"ceil": pc.ceil, "floor": pc.floor, "sign": pc.sign}[op]
        out = fn(kids[0].to_arrow())
        return Series.from_arrow(out, kids[0].name()).cast(out_field.dtype)
    if op == "round":
        return Series.from_arrow(
            pc.round(kids[0].to_arrow(), ndigits=e.params[0]),
            kids[0].name()).cast(out_field.dtype)
    if op == "clip":
        v = b(kids[0]).to_numpy().astype(np.float64)
        lo = kids[1].to_pylist()[0] if len(kids) > 1 else None
        hi = kids[2].to_pylist()[0] if len(kids) > 2 else None
        out = np.clip(v, -np.inf if lo is None else lo, np.inf if hi is None else hi)
        return Series.from_arrow(pa.array(out, from_pandas=True),
                                 kids[0].name()).cast(out_field.dtype)
    if op in ("sqrt", "cbrt", "exp", "log2", "log10", "ln", "sin", "cos", "tan",
              "arcsin", "arccos", "arctan", "sinh", "cosh", "tanh", "degrees",
              "radians", "log", "arcsinh", "arccosh", "arctanh", "cot", "csc",
              "sec", "expm1", "log1p"):
        v = kids[0].to_numpy().astype(np.float64)
        npfn = {"sqrt": np.sqrt, "cbrt": np.cbrt, "exp": np.exp, "log2": np.log2,
                "log10": np.log10, "ln": np.log, "sin": np.sin, "cos": np.cos,
                "tan": np.tan, "arcsin": np.arcsin, "arccos": np.arccos,
                "arctan": np.arctan, "sinh": np.sinh, "cosh": np.cosh,
                "tanh": np.tanh, "degrees": np.degrees, "radians": np.radians,
                "arcsinh": np.arcsinh, "arccosh": np.arccosh,
                "arctanh": np.arctanh, "expm1": np.expm1, "log1p": np.log1p,
                "cot": lambda x: 1.0 / np.tan(x),
                "csc": lambda x: 1.0 / np.sin(x),
                "sec": lambda x: 1.0 / np.cos(x)}
        with np.errstate(all="ignore"):
            if op == "log":
                out = np.log(v) / math.log(e.params[0])
            else:
                out = npfn[op](v)
        return Series.from_arrow(pa.array(out, from_pandas=True), kids[0].name())
    if op == "arctan2":
        l, r = b(kids[0]), b(kids[1])
        out = np.arctan2(l.to_numpy().astype(np.float64),
                         r.to_numpy().astype(np.float64))
        return Series.from_arrow(pa.array(out), l.name())
    if op in ("shift_left", "shift_right"):
        fn = pc.shift_left if op == "shift_left" else pc.shift_right
        return Series.from_arrow(fn(b(kids[0]).to_arrow(), b(kids[1]).to_arrow()),
                                 kids[0].name())
    if op in ("bitwise_and", "bitwise_or", "bitwise_xor"):
        fn = {"bitwise_and": pc.bit_wise_and, "bitwise_or": pc.bit_wise_or,
              "bitwise_xor": pc.bit_wise_xor}[op]
        return Series.from_arrow(fn(b(kids[0]).to_arrow(), b(kids[1]).to_arrow()),
                                 kids[0].name())
    if op in ("deserialize", "try_deserialize"):
        import json as _json
        fmt, dtype = e.params
        if fmt != "json":
            raise ValueError(f"deserialize format {fmt!r} (only 'json')")
        strict = op == "deserialize"
        out = []
        for v in kids[0].to_pylist():
            if v is None:
                out.append(None)
                continue
            try:
                out.append(_json.loads(v))
            except ValueError:
                if strict:
                    raise
                out.append(None)
        # enforce the DECLARED dtype: parsed-but-mismatched values must not
        # leak through as python objects under a typed schema
        target = dtype.to_arrow()
        try:
            arr = pa.array(out, type=target)
        except (pa.ArrowInvalid, pa.ArrowTypeError, TypeError,
                OverflowError):
            if strict:
                raise
            coerced = []
            for v in out:
                try:
                    pa.array([v], type=target)
                    coerced.append(v)
                except (pa.ArrowInvalid, pa.ArrowTypeError, TypeError,
                        OverflowError):
                    coerced.append(None)
            arr = pa.array(coerced, type=target)
        return Series.from_arrow(arr, kids[0].name()).cast(dtype)
    if op == "hash":
        return kids[0].hash(kids[1] if len(kids) > 1 else None)
    if op == "minhash":
        num_hashes, ngram_size, seed = e.params
        return kids[0].minhash(num_hashes, ngram_size, seed)
    if op == "udf":
        u, arg_spec, kw_spec = e.params
        out = u.run(kids, arg_spec, kw_spec, max_len)
        nm = kids[0].name() if kids else u.name
        return out.rename(nm)
    if op == "py_apply":
        fn, ret = e.params
        vals = kids[0].to_pylist()
        out = [None if v is None else fn(v) for v in vals]
        return Series.from_pylist(out, kids[0].name(), dtype=ret)
    if op == "explode":
        # handled by the explode kernel at the RecordBatch level
        return kids[0]
    if op == "list":
        arrs = [b(k) for k in kids]
        target = out_field.dtype.inner.to_arrow()
        cols_np = [a.cast(out_field.dtype.inner).to_arrow() for a in arrs]
        out = []
        for i in range(max_len):
            out.append([c[i].as_py() for c in cols_np])
        return Series.from_pylist(out, "list", dtype=out_field.dtype)
    if op == "struct_make":
        arrs = [b(k) for k in kids]
        sa = pa.StructArray.from_arrays([a.to_arrow() for a in arrs],
                                        [a.name() for a in arrs])
        return Series.from_arrow(sa, "struct")

    if "." in op:
        from .fn_host import eval_function
        return eval_function(op, e, kids, b, out_field)

    raise NotImplementedError(f"host eval for expression op {op!r}")


def pd_isnull(v: np.ndarray) -> np.ndarray:
    if v.dtype == object:
        return np.array([x is None for x in v])
    if v.dtype.kind == "f":
        return np.isnan(v)
    return np.zeros(len(v), dtype=bool)


def _temporal_arith(op: str, l: Series, r: Series, out_dtype: DataType) -> Series:
    la, ra = l.to_arrow(), r.to_arrow()
    if op == "add":
        return Series.from_arrow(pc.add(la, ra), l.name()).cast(out_dtype)
    if op == "sub":
        out = pc.subtract(la, ra)
        return Series.from_arrow(out, l.name()).cast(out_dtype)
    raise NotImplementedError(f"temporal {op}")
