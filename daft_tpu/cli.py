"""Command-line interface (reference: ``src/daft-cli`` — the ``daft
dashboard`` subcommand, ``python.rs:11-41``; entry ``daft/cli.py``).

Usage: ``python -m daft_tpu.cli dashboard [--port N]``
       ``python -m daft_tpu.cli version``
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="daft-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)
    dash = sub.add_parser("dashboard", help="serve the query dashboard")
    dash.add_argument("--port", type=int, default=None)
    sub.add_parser("version", help="print the version")
    args = parser.parse_args(argv)

    if args.cmd == "version":
        from . import __version__
        print(__version__)
        return 0
    if args.cmd == "dashboard":
        from . import dashboard
        port = dashboard.launch(args.port if args.port is not None
                                else dashboard.DEFAULT_PORT)
        print(f"daft-tpu dashboard on http://127.0.0.1:{port}", flush=True)
        try:
            import time
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            dashboard.shutdown()
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
