"""Native Lance dataset support: versioned columnar datasets with
column-page files, no `lance` SDK.

The reference delegates Lance IO to the lancedb SDK
(``/root/reference/daft/io/_lance.py`` read path,
``/root/reference/src/daft-writers/src/lance.rs`` write path). This module
implements the dataset natively, mirroring Lance's architecture:

- **dataset layout**: ``data/<uuid>.lance`` column-page files and
  ``_versions/<v>.manifest`` version snapshots — append/overwrite create
  a NEW version; old versions stay readable (``read_lance(uri,
  version=N)`` time travel). Version resolution always globs the
  manifest directory (no hint file that could go stale under races).
- **file layout** (v2-style): page data first, then a column-metadata
  table addressing every page's byte range, then a fixed-size footer
  (``meta_off, meta_len, major=2, minor=0, magic b"LANC"``). Reads seek
  the footer, fetch the metadata table, then fetch ONLY the projected
  columns' page ranges — real columnar IO over any object store.
- **page encoding**: each page is a single-column Arrow IPC blob
  (Lance v2 treats page encodings as pluggable; Arrow IPC is this
  implementation's encoding, which keeps every Arrow dtype round-trippable).
- **pushdowns**: column projection (byte-range reads), limit (page-count
  cutoff), filter (fragment pruning via per-column min/max stats, residual
  applied at scan).
- **commits**: create-exclusive version manifests with the same optimistic
  retry as ``iceberg.py`` — concurrent writers serialize, never clobber.

Byte-level interop with the lance SDK is NOT claimed (the manifest/page
protobufs cannot be validated in this environment); the dataset semantics
— versioning, fragments, column pages, projection/limit/filter pushdown —
match, and the format is self-describing.
"""

from __future__ import annotations

import io as _io
import json
import re
import struct
import time
import uuid
from typing import Any, Dict, List, Optional

import pyarrow as pa

from .iceberg import _get, _is_remote, _join, _put, _put_if_absent
from .object_io import IOConfig, get_io_client

_MAGIC = b"LANC"
_PAGE_ROWS = 64 * 1024
_FOOTER = struct.Struct("<QQHH4s")  # meta_off, meta_len, major, minor, magic


# ----------------------------------------------------------------- file

def _ipc_blob(arr: pa.ChunkedArray, name: str) -> bytes:
    t = pa.table({name: arr})
    buf = _io.BytesIO()
    with pa.ipc.new_stream(buf, t.schema) as w:
        w.write_table(t)
    return buf.getvalue()


def _ipc_unblob(data: bytes) -> pa.ChunkedArray:
    with pa.ipc.open_stream(_io.BytesIO(data)) as r:
        return r.read_all().column(0)


def _col_stats(arr: pa.ChunkedArray) -> Dict[str, Any]:
    import pyarrow.compute as pc
    out: Dict[str, Any] = {"null_count": arr.null_count}
    try:
        mn, mx = pc.min(arr).as_py(), pc.max(arr).as_py()
        if isinstance(mn, (int, float, str, bool)) or mn is None:
            out["min"], out["max"] = mn, mx
    except Exception:
        pass
    return out


def write_fragment_file(table: pa.Table, uri: str, io_config) -> dict:
    """One Arrow table → one .lance column-page file; returns the fragment
    manifest entry."""
    body = bytearray()
    columns = []
    for name in table.column_names:
        arr = table.column(name)
        pages = []
        for start in range(0, max(table.num_rows, 1), _PAGE_ROWS):
            page = arr.slice(start, _PAGE_ROWS)
            if len(page) == 0 and table.num_rows > 0:
                break
            blob = _ipc_blob(page, name)
            pages.append({"rows": len(page),
                          "offset": len(body), "length": len(blob)})
            body += blob
            if table.num_rows == 0:
                break
        columns.append({"name": name, "pages": pages,
                        "stats": _col_stats(arr)})
    meta = json.dumps({"columns": columns,
                       "rows": table.num_rows}).encode()
    meta_off = len(body)
    body += meta
    body += _FOOTER.pack(meta_off, len(meta), 2, 0, _MAGIC)
    _put(uri, bytes(body), io_config)
    return {"file": uri.rsplit("/", 1)[-1], "rows": table.num_rows,
            "size": len(body),
            "stats": {c["name"]: c["stats"] for c in columns}}


def _read_footer_meta(uri: str, io_config, file_size: Optional[int] = None
                      ) -> dict:
    client = get_io_client(io_config)
    if _is_remote(uri):
        if file_size is None:
            file_size = client.source_for(uri).get_size(uri)
        tail = client.get(uri, byte_range=(file_size - _FOOTER.size,
                                           file_size))
    else:
        import os
        p = uri[7:] if uri.startswith("file://") else uri
        file_size = os.path.getsize(p)
        with open(p, "rb") as f:
            f.seek(file_size - _FOOTER.size)
            tail = f.read()
    meta_off, meta_len, major, minor, magic = _FOOTER.unpack(tail)
    if magic != _MAGIC:
        raise ValueError(f"not a lance file: {uri!r}")
    return {"meta": json.loads(_read_range(uri, meta_off,
                                           meta_len, io_config)),
            "major": major, "minor": minor}


def _read_range(uri: str, off: int, length: int, io_config) -> bytes:
    if _is_remote(uri):
        return get_io_client(io_config).get(uri, byte_range=(off,
                                                             off + length))
    p = uri[7:] if uri.startswith("file://") else uri
    with open(p, "rb") as f:
        f.seek(off)
        return f.read(length)


def read_fragment_file(uri: str, io_config,
                       columns: Optional[List[str]] = None,
                       limit: Optional[int] = None) -> pa.Table:
    """Projected (and limit-bounded) read of one .lance file: only the
    selected columns' page ranges are fetched."""
    meta = _read_footer_meta(uri, io_config)["meta"]
    by_name = {c["name"]: c for c in meta["columns"]}
    names = columns if columns is not None else [c["name"]
                                                for c in meta["columns"]]
    nrows = meta["rows"] if limit is None else min(meta["rows"], limit)
    out = {}
    for name in names:
        c = by_name.get(name)
        if c is None:
            # fragment predates this column (appended with a wider
            # schema): null-fill; the caller casts to the dataset schema
            out[name] = pa.nulls(nrows)
            continue
        arrs = []
        got = 0
        for pg in c["pages"]:
            if limit is not None and got >= limit:
                break
            blob = _read_range(uri, pg["offset"], pg["length"], io_config)
            arrs.append(_ipc_unblob(blob))
            got += pg["rows"]
        if arrs:
            chunks = [ch for a in arrs for ch in a.chunks]
            merged = pa.chunked_array(chunks, type=arrs[0].type)
        else:
            merged = pa.chunked_array([], type=pa.null())
        if limit is not None and len(merged) > limit:
            merged = merged.slice(0, limit)
        out[name] = merged
    if not out:  # count-style: no columns, rows only
        n = meta["rows"] if limit is None else min(meta["rows"], limit)
        return pa.table({"__dummy__": pa.nulls(n)}).drop(["__dummy__"])
    return pa.table(out)


# -------------------------------------------------------------- dataset

def _manifest_dir(uri: str) -> str:
    return _join(uri, "_versions")


def _resolve_version(uri: str, io_config, version: Optional[int] = None
                     ) -> Optional[dict]:
    pattern = _join(_manifest_dir(uri), "*.manifest")
    if _is_remote(uri):
        hits = get_io_client(io_config).glob(pattern)
    else:
        import glob as _g
        hits = _g.glob(pattern)

    def vnum(p: str) -> int:
        m = re.search(r"(\d+)\.manifest$", p)
        return int(m.group(1)) if m else -1

    if version is not None:
        for p in hits:
            if vnum(p) == version:
                return json.loads(_get(p, io_config))
        raise ValueError(f"lance dataset {uri!r} has no version {version}")
    if not hits:
        return None
    return json.loads(_get(max(hits, key=vnum), io_config))


def write_lance(df, uri: str, mode: str = "create",
                io_config: Optional[IOConfig] = None) -> None:
    """DataFrame → Lance dataset version. ``mode``: ``create`` (error if
    the dataset exists), ``append``, ``overwrite`` (new version listing
    only the new fragments; prior versions stay readable)."""
    if mode not in ("create", "append", "overwrite"):
        raise ValueError(f"write_lance mode {mode!r}")
    # one resolve covers both the create-exclusivity check (BEFORE any
    # bytes land, so no orphan fragments on user error) and the first
    # commit attempt; conflicts re-resolve inside the loop
    cur = _resolve_version(uri, io_config)
    if mode == "create" and cur is not None:
        raise ValueError(f"lance dataset already exists at {uri!r} "
                         "(use mode='append' or 'overwrite')")
    table = df.to_arrow()
    frag = write_fragment_file(
        table, _join(uri, "data", f"{uuid.uuid4().hex}.lance"), io_config)
    buf = _io.BytesIO()
    with pa.ipc.new_stream(buf, table.schema):
        pass  # header-only stream: the exact arrow schema, no batches
    import base64
    for _attempt in range(5):
        if mode == "create" and cur is not None:
            # a concurrent create won the race mid-retry: creating "over"
            # it would silently stack a version
            raise ValueError(f"lance dataset already exists at {uri!r} "
                             "(use mode='append' or 'overwrite')")
        base_version = cur["version"] if cur else 0
        frags = list(cur["fragments"]) if (cur and mode == "append") else []
        frags.append(frag)
        manifest = {
            "version": base_version + 1,
            "timestamp_ms": int(time.time() * 1000),
            "arrow_schema_ipc_b64": base64.b64encode(
                buf.getvalue()).decode(),
            "fragments": frags,
        }
        target = _join(_manifest_dir(uri),
                       f"{base_version + 1}.manifest")
        if _put_if_absent(target, json.dumps(manifest, indent=1).encode(),
                          io_config):
            return
        cur = _resolve_version(uri, io_config)  # lost the race: refresh
    raise RuntimeError(f"write_lance: lost the version commit race at "
                       f"{uri!r} 5 times")


# ----------------------------------------------------------------- scan

_NUM_OPS = {"lt": lambda mn, mx, v: mn < v, "le": lambda mn, mx, v: mn <= v,
            "gt": lambda mn, mx, v: mx > v, "ge": lambda mn, mx, v: mx >= v,
            "eq": lambda mn, mx, v: mn <= v <= mx}


def _fragment_survives(filters, stats: Dict[str, dict]) -> bool:
    """Conservative min/max pruning: False only when a conjunct provably
    excludes every row of the fragment."""
    if filters is None:
        return True
    from ..logical.optimizer import split_conjuncts
    try:
        conjs = split_conjuncts(filters)
    except Exception:
        return True
    for c in conjs:
        u = c._unalias()
        if u.op not in _NUM_OPS or len(u.args) != 2:
            continue
        a, b = u.args
        if a.op == "col" and b.op == "lit":
            name, v = a.params[0], b.params[0]
        elif b.op == "col" and a.op == "lit":
            inv = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                   "eq": "eq"}
            u = type(u)(inv[u.op], u.args, u.params)
            name, v = b.params[0], a.params[0]
        else:
            continue
        st = stats.get(name) or {}
        mn, mx = st.get("min"), st.get("max")
        if mn is None or mx is None or v is None:
            continue
        try:
            if not _NUM_OPS[u.op](mn, mx, v):
                return False
        except TypeError:
            continue
    return True


def read_lance(uri: str, version: Optional[int] = None,
               io_config: Optional[IOConfig] = None):
    """Lance dataset → DataFrame (column-projection, limit and
    filter-pruning pushdowns applied at scan)."""
    from ..dataframe import DataFrame
    from ..logical.builder import LogicalPlanBuilder
    from ..recordbatch import RecordBatch
    from ..schema import Schema
    from .scan import GeneratorScanOperator

    manifest = _resolve_version(uri, io_config, version)
    if manifest is None:
        raise FileNotFoundError(f"no lance dataset at {uri!r}")
    import base64
    arrow_schema = pa.ipc.open_stream(_io.BytesIO(base64.b64decode(
        manifest["arrow_schema_ipc_b64"]))).schema
    schema = Schema.from_arrow(arrow_schema)

    frags = manifest["fragments"]

    def make_loader(fr):
        furi = _join(uri, "data", fr["file"])

        def load(pushdowns):
            cols = list(pushdowns.columns) \
                if pushdowns.columns is not None else None
            t = read_fragment_file(
                furi, io_config, columns=cols,
                limit=pushdowns.limit
                if pushdowns.filters is None else None)
            yield RecordBatch.from_arrow_table(t).cast_to_schema(
                schema.project(cols) if cols is not None else schema)
        return [furi], load

    entries = [make_loader(fr) for fr in frags]
    hints = [{"format": "lance", "rows": fr.get("rows"),
              "size": fr.get("size")} for fr in frags]

    def prune(i, pushdowns):
        return _fragment_survives(pushdowns.filters,
                                  frags[i].get("stats", {}))

    op = GeneratorScanOperator(
        schema, entries,
        f"LanceScanOperator({uri!r}, version={manifest['version']})",
        io_config=io_config, prune_fn=prune, entry_hints=hints)
    return DataFrame(LogicalPlanBuilder.from_scan(op))
