"""WARC (Web ARChive / Common Crawl) streaming reader.

Capability mirror of the reference's ``src/daft-warc`` crate: parses
``.warc`` / ``.warc.gz`` files into the fixed 7-column schema
(``src/daft-warc/src/lib.rs:615-632``) — mandatory metadata columns,
``warc_content`` raw bytes, and the remaining record headers as a JSON
string.
"""

from __future__ import annotations

import datetime
import gzip
import io
import json
from typing import BinaryIO, Iterator, Optional, Tuple

import pyarrow as pa

from ..datatype import DataType, TimeUnit
from ..schema import Field, Schema

# the reference's fixed WARC schema (lib.rs:615)
WARC_SCHEMA = Schema([
    Field("WARC-Record-ID", DataType.string()),
    Field("WARC-Type", DataType.string()),
    Field("WARC-Date", DataType.timestamp(TimeUnit.ns, "Etc/UTC")),
    Field("Content-Length", DataType.int64()),
    Field("WARC-Identified-Payload-Type", DataType.string()),
    Field("warc_content", DataType.binary()),
    Field("warc_headers", DataType.string()),
])

_MANDATORY = ("WARC-Record-ID", "WARC-Type", "WARC-Date", "Content-Length",
              "WARC-Identified-Payload-Type")


def _open(path: str) -> BinaryIO:
    if path.endswith(".gz"):
        # gzip.open(path) owns + closes the underlying fd (a passed fileobj
        # would be left open); handles multi-member (one member per record)
        return gzip.open(path, "rb")
    return open(path, "rb")


def _read_record(f: BinaryIO) -> Optional[Tuple[dict, bytes]]:
    """Parse one WARC record: version line, CRLF headers, blank line,
    Content-Length bytes of block, trailing CRLF CRLF."""
    # skip inter-record blank lines
    line = f.readline()
    while line in (b"\r\n", b"\n"):
        line = f.readline()
    if not line:
        return None
    if not line.startswith(b"WARC/"):
        raise ValueError(f"malformed WARC record header: {line[:40]!r}")
    headers = {}
    last_key = None
    while True:
        line = f.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        text = line.decode("utf-8", errors="replace")
        if text[:1] in (" ", "\t") and last_key is not None:
            # folded (continuation) header line per the WARC/1.1 grammar:
            # append to the previous header's value
            headers[last_key] += " " + text.strip()
            continue
        k, _, v = text.partition(":")
        last_key = k.strip()
        headers[last_key] = v.strip()
    length = int(headers.get("Content-Length", 0))
    content = f.read(length)
    return headers, content


_EPOCH_UTC = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


def _parse_warc_date(v: Optional[str]) -> Optional[int]:
    """→ ns since epoch. Naive dates are taken as UTC (WARC-Date is defined
    as UTC); integer arithmetic keeps ns exact (float timestamp() has ~256ns
    spacing at current epochs)."""
    if not v:
        return None
    try:
        dt = datetime.datetime.fromisoformat(v.replace("Z", "+00:00"))
    except ValueError:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    micros = (dt - _EPOCH_UTC) // datetime.timedelta(microseconds=1)
    return micros * 1000


def iter_records(path: str) -> Iterator[Tuple[dict, bytes]]:
    with _open(path) as f:
        # gzip.open(fileobj) lacks readline buffering guarantees we rely on
        if isinstance(f, gzip.GzipFile):
            f = io.BufferedReader(f)
        while True:
            rec = _read_record(f)
            if rec is None:
                return
            yield rec


def read_warc_file(path: str, limit: Optional[int] = None) -> pa.Table:
    ids, types, dates, lengths, payload_types = [], [], [], [], []
    contents, extra_headers = [], []
    for headers, content in iter_records(path):
        ids.append(headers.get("WARC-Record-ID"))
        types.append(headers.get("WARC-Type"))
        dates.append(_parse_warc_date(headers.get("WARC-Date")))
        cl = headers.get("Content-Length")
        lengths.append(int(cl) if cl is not None else None)
        payload_types.append(headers.get("WARC-Identified-Payload-Type"))
        contents.append(content)
        rest = {k: v for k, v in headers.items() if k not in _MANDATORY}
        extra_headers.append(json.dumps(rest))
        if limit is not None and len(ids) >= limit:
            break
    ts_type = pa.timestamp("ns", tz="Etc/UTC")
    return pa.table({
        "WARC-Record-ID": pa.array(ids, pa.large_string()),
        "WARC-Type": pa.array(types, pa.large_string()),
        "WARC-Date": pa.array(dates, pa.int64()).cast(ts_type),
        "Content-Length": pa.array(lengths, pa.int64()),
        "WARC-Identified-Payload-Type": pa.array(payload_types,
                                                 pa.large_string()),
        "warc_content": pa.array(contents, pa.large_binary()),
        "warc_headers": pa.array(extra_headers, pa.large_string()),
    })
