"""Minimal Avro Object Container File codec (read + write).

Iceberg's manifest lists and manifest files are Avro; the reference reads
them through pyiceberg (``/root/reference/daft/io/_iceberg.py``). This is a
dependency-free, schema-driven implementation of the Avro 1.11 spec subset
those files use: container framing (magic ``Obj\\x01``, metadata map, sync
markers, deflate/null codecs) and the binary encoding of null / boolean /
int / long (zigzag varints) / float / double / bytes / string / fixed /
enum / array / map / union / record. Values decode to plain dicts keyed by
field name, so callers pull what they need without hardcoding Iceberg's
schemas.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

_MAGIC = b"Obj\x01"


# ---------------------------------------------------------------- binary

def _read_varint(buf) -> int:
    shift = 0
    out = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        v = b[0]
        out |= (v & 0x7F) << shift
        if not v & 0x80:
            break
        shift += 7
    return out


def _read_long(buf) -> int:
    n = _read_varint(buf)
    return (n >> 1) ^ -(n & 1)  # zigzag


def _write_varint(out, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _write_long(out, v: int) -> None:
    _write_varint(out, (v << 1) ^ (v >> 63))


class _Decoder:
    def __init__(self, data: bytes):
        self.buf = io.BytesIO(data)

    def decode(self, schema) -> Any:
        if isinstance(schema, list):  # union
            idx = _read_long(self.buf)
            return self.decode(schema[idx])
        if isinstance(schema, dict):
            t = schema["type"]
            if t == "record":
                return {f["name"]: self.decode(f["type"])
                        for f in schema["fields"]}
            if t == "array":
                return self._blocks(lambda: self.decode(schema["items"]))
            if t == "map":
                out = {}
                for k, v in self._blocks(lambda: (
                        self._string(), self.decode(schema["values"]))):
                    out[k] = v
                return out
            if t == "fixed":
                return self.buf.read(schema["size"])
            if t == "enum":
                return schema["symbols"][_read_long(self.buf)]
            return self.decode(t)  # {"type": "string", logicalType...}
        if schema == "null":
            return None
        if schema == "boolean":
            return self.buf.read(1) == b"\x01"
        if schema in ("int", "long"):
            return _read_long(self.buf)
        if schema == "float":
            return struct.unpack("<f", self.buf.read(4))[0]
        if schema == "double":
            return struct.unpack("<d", self.buf.read(8))[0]
        if schema == "bytes":
            return self.buf.read(_read_long(self.buf))
        if schema == "string":
            return self._string()
        raise ValueError(f"unsupported avro type {schema!r}")

    def _string(self) -> str:
        return self.buf.read(_read_long(self.buf)).decode("utf-8")

    def _blocks(self, item) -> List[Any]:
        out = []
        while True:
            n = _read_long(self.buf)
            if n == 0:
                return out
            if n < 0:  # block with byte size prefix
                n = -n
                _read_long(self.buf)
            for _ in range(n):
                out.append(item())


class _Encoder:
    def __init__(self):
        self.out = bytearray()

    def encode(self, schema, value) -> None:
        if isinstance(schema, list):  # union: pick first matching branch
            idx = _union_branch(schema, value)
            _write_long(self.out, idx)
            self.encode(schema[idx], value)
            return
        if isinstance(schema, dict):
            t = schema["type"]
            if t == "record":
                for f in schema["fields"]:
                    self.encode(f["type"], value.get(f["name"]))
                return
            if t == "array":
                if value:
                    _write_long(self.out, len(value))
                    for v in value:
                        self.encode(schema["items"], v)
                _write_long(self.out, 0)
                return
            if t == "map":
                if value:
                    _write_long(self.out, len(value))
                    for k, v in value.items():
                        self._string(k)
                        self.encode(schema["values"], v)
                _write_long(self.out, 0)
                return
            if t == "fixed":
                assert len(value) == schema["size"]
                self.out += value
                return
            if t == "enum":
                _write_long(self.out, schema["symbols"].index(value))
                return
            self.encode(t, value)
            return
        if schema == "null":
            return
        if schema == "boolean":
            self.out.append(1 if value else 0)
        elif schema in ("int", "long"):
            _write_long(self.out, int(value))
        elif schema == "float":
            self.out += struct.pack("<f", value)
        elif schema == "double":
            self.out += struct.pack("<d", value)
        elif schema == "bytes":
            _write_long(self.out, len(value))
            self.out += value
        elif schema == "string":
            self._string(value)
        else:
            raise ValueError(f"unsupported avro type {schema!r}")

    def _string(self, s: str) -> None:
        b = s.encode("utf-8")
        _write_long(self.out, len(b))
        self.out += b


def _union_branch(union: list, value) -> int:
    def matches(s) -> bool:
        name = s if isinstance(s, str) else s.get("type")
        if value is None:
            return name == "null"
        if name == "null":
            return False
        if isinstance(value, bool):
            return name == "boolean"
        if isinstance(value, int):
            return name in ("int", "long")
        if isinstance(value, float):
            return name in ("float", "double")
        if isinstance(value, str):
            return name in ("string", "enum")
        if isinstance(value, bytes):
            return name in ("bytes", "fixed")
        if isinstance(value, dict):
            return name in ("record", "map")
        if isinstance(value, list):
            return name == "array"
        return False

    for i, s in enumerate(union):
        if matches(s):
            return i
    raise ValueError(f"no union branch for {type(value)} in {union}")


# ------------------------------------------------------------- container

def read_avro(data: bytes) -> Tuple[dict, List[dict]]:
    """→ (metadata, records). ``metadata`` holds the decoded file metadata
    (``avro.schema`` parsed to JSON under key ``schema``)."""
    buf = io.BytesIO(data)
    if buf.read(4) != _MAGIC:
        raise ValueError("not an avro object container file")
    dec = _Decoder(b"")
    dec.buf = buf
    meta_raw = {}
    while True:
        n = _read_long(buf)
        if n == 0:
            break
        if n < 0:
            n = -n
            _read_long(buf)
        for _ in range(n):
            k = dec._string()
            v = buf.read(_read_long(buf))
            meta_raw[k] = v
    sync = buf.read(16)
    # metadata keys decode as strings, values stay bytes
    schema = json.loads(meta_raw["avro.schema"])
    codec = meta_raw.get("avro.codec", b"null").decode()
    records: List[dict] = []
    while True:
        head = buf.read(1)
        if not head:
            break
        buf.seek(-1, io.SEEK_CUR)
        count = _read_long(buf)
        nbytes = _read_long(buf)
        block = buf.read(nbytes)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        bd = _Decoder(block)
        for _ in range(count):
            records.append(bd.decode(schema))
        if buf.read(16) != sync:
            raise ValueError("avro sync marker mismatch")
    return {"schema": schema, "codec": codec}, records


def write_avro(schema: dict, records: List[dict],
               metadata: Optional[Dict[str, str]] = None,
               codec: str = "null") -> bytes:
    """Records → one-block Avro object container file."""
    out = bytearray()
    out += _MAGIC
    meta = {"avro.schema": json.dumps(schema), "avro.codec": codec}
    meta.update(metadata or {})
    enc = _Encoder()
    _write_long(enc.out, len(meta))
    for k, v in meta.items():
        enc._string(k)
        vb = v.encode() if isinstance(v, str) else v
        _write_long(enc.out, len(vb))
        enc.out += vb
    _write_long(enc.out, 0)
    out += enc.out
    sync = os.urandom(16)
    out += sync
    body = _Encoder()
    for r in records:
        body.encode(schema, r)
    block = bytes(body.out)
    if codec == "deflate":
        c = zlib.compressobj(wbits=-15)
        block = c.compress(block) + c.flush()
    tail = _Encoder()
    _write_long(tail.out, len(records))
    _write_long(tail.out, len(block))
    out += tail.out
    out += block
    out += sync
    return bytes(out)
