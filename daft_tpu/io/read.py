"""Public read_* entry points (reference: ``daft/io/_parquet.py`` etc.)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from ..schema import Schema
from .scan import GlobScanOperator


def _df_from_scan(op):
    from ..dataframe import DataFrame
    from ..logical.builder import LogicalPlanBuilder
    return DataFrame(LogicalPlanBuilder.from_scan(op))


def read_parquet(path: Union[str, List[str]],
                 schema: Optional[Dict[str, Any]] = None,
                 hive_partitioning: bool = False,
                 io_config: Any = None,
                 **kwargs):
    """Lazily read Parquet file(s) into a DataFrame
    (reference: ``daft/io/_parquet.py:20``)."""
    sch = Schema.from_pydict(schema) if isinstance(schema, dict) else schema
    return _df_from_scan(GlobScanOperator(
        path, "parquet", schema=sch, hive_partitioning=hive_partitioning,
        io_config=io_config))


def read_csv(path: Union[str, List[str]],
             has_headers: bool = True,
             delimiter: Optional[str] = None,
             schema: Optional[Dict[str, Any]] = None,
             quote: Optional[str] = None,
             escape_char: Optional[str] = None,
             allow_variable_columns: bool = False,
             hive_partitioning: bool = False,
             io_config: Any = None,
             **kwargs):
    sch = Schema.from_pydict(schema) if isinstance(schema, dict) else schema
    opts = {"has_headers": has_headers, "delimiter": delimiter,
            "quote": quote, "escape_char": escape_char,
            "allow_variable_columns": allow_variable_columns,
            "schema": sch}
    return _df_from_scan(GlobScanOperator(
        path, "csv", schema=sch, format_options=opts,
        hive_partitioning=hive_partitioning, io_config=io_config))


def read_json(path: Union[str, List[str]],
              schema: Optional[Dict[str, Any]] = None,
              hive_partitioning: bool = False,
              io_config: Any = None,
              **kwargs):
    sch = Schema.from_pydict(schema) if isinstance(schema, dict) else schema
    return _df_from_scan(GlobScanOperator(
        path, "json", schema=sch, hive_partitioning=hive_partitioning,
        io_config=io_config))


def read_warc(path: Union[str, List[str]],
              io_config: Any = None,
              **kwargs):
    """Lazily read WARC / gzipped-WARC file(s) into a DataFrame with the
    fixed 7-column WARC schema (reference: ``daft/io/_warc.py:20``)."""
    import warnings
    if io_config is not None or kwargs:
        # remote WARC paths (e.g. Common Crawl on S3) are not wired yet —
        # don't let an IOConfig silently degrade to local-glob behavior
        warnings.warn(
            "read_warc currently reads local paths only; io_config and "
            f"extra options {sorted(kwargs) or ''} are ignored",
            stacklevel=2)
    from .warc import WARC_SCHEMA
    return _df_from_scan(GlobScanOperator(path, "warc", schema=WARC_SCHEMA))
