"""Public read_* entry points (reference: ``daft/io/_parquet.py`` etc.)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from ..schema import Schema
from .scan import GlobScanOperator


def _df_from_scan(op):
    from ..dataframe import DataFrame
    from ..logical.builder import LogicalPlanBuilder
    return DataFrame(LogicalPlanBuilder.from_scan(op))


def read_parquet(path: Union[str, List[str]],
                 schema: Optional[Dict[str, Any]] = None,
                 hive_partitioning: bool = False,
                 io_config: Any = None,
                 **kwargs):
    """Lazily read Parquet file(s) into a DataFrame
    (reference: ``daft/io/_parquet.py:20``)."""
    sch = Schema.from_pydict(schema) if isinstance(schema, dict) else schema
    return _df_from_scan(GlobScanOperator(
        path, "parquet", schema=sch, hive_partitioning=hive_partitioning,
        io_config=io_config))


def read_csv(path: Union[str, List[str]],
             has_headers: bool = True,
             delimiter: Optional[str] = None,
             schema: Optional[Dict[str, Any]] = None,
             quote: Optional[str] = None,
             escape_char: Optional[str] = None,
             allow_variable_columns: bool = False,
             hive_partitioning: bool = False,
             io_config: Any = None,
             **kwargs):
    sch = Schema.from_pydict(schema) if isinstance(schema, dict) else schema
    opts = {"has_headers": has_headers, "delimiter": delimiter,
            "quote": quote, "escape_char": escape_char,
            "allow_variable_columns": allow_variable_columns,
            "schema": sch}
    return _df_from_scan(GlobScanOperator(
        path, "csv", schema=sch, format_options=opts,
        hive_partitioning=hive_partitioning, io_config=io_config))


def read_json(path: Union[str, List[str]],
              schema: Optional[Dict[str, Any]] = None,
              hive_partitioning: bool = False,
              io_config: Any = None,
              **kwargs):
    sch = Schema.from_pydict(schema) if isinstance(schema, dict) else schema
    return _df_from_scan(GlobScanOperator(
        path, "json", schema=sch, hive_partitioning=hive_partitioning,
        io_config=io_config))


def read_warc(path: Union[str, List[str]],
              io_config: Any = None,
              **kwargs):
    """Lazily read WARC / gzipped-WARC file(s) into a DataFrame with the
    fixed 7-column WARC schema (reference: ``daft/io/_warc.py:20``)."""
    import warnings
    if io_config is not None or kwargs:
        # remote WARC paths (e.g. Common Crawl on S3) are not wired yet —
        # don't let an IOConfig silently degrade to local-glob behavior
        warnings.warn(
            "read_warc currently reads local paths only; io_config and "
            f"extra options {sorted(kwargs) or ''} are ignored",
            stacklevel=2)
    from .warc import WARC_SCHEMA
    return _df_from_scan(GlobScanOperator(path, "warc", schema=WARC_SCHEMA))


def read_deltalake(table_uri, version=None, io_config: Any = None, **kwargs):
    """Native Delta Lake snapshot read (see ``daft_tpu/io/delta.py``)."""
    from .delta import read_deltalake as _impl
    return _impl(table_uri, version, io_config, **kwargs)


def _sdk_gated(name: str, sdk: str):
    def entry(*args, **kwargs):
        raise ImportError(
            f"{name} requires the optional {sdk!r} package, which is not "
            f"available in this environment. The reference engine gates "
            f"this reader on the same SDK.")
    entry.__name__ = name
    return entry


def read_iceberg(table, snapshot_id: Optional[int] = None,
                 io_config: Any = None, **kwargs):
    """Read an Apache Iceberg table (reference: ``daft/io/_iceberg.py``
    over pyiceberg scan tasks). Natively implemented — ``table`` is a
    warehouse path / metadata JSON URI, or a pyiceberg-style object
    exposing ``metadata_location``."""
    from .iceberg import read_iceberg as _impl
    uri = getattr(table, "metadata_location", table)
    if not isinstance(uri, str):
        raise TypeError(f"read_iceberg expects a table path or an object "
                        f"with .metadata_location, got {type(table)!r}")
    return _impl(uri, snapshot_id=snapshot_id, io_config=io_config)


def read_hudi(table_uri: str, io_config: Any = None,
              query_type: str = "snapshot", **kwargs):
    """Read an Apache Hudi table's latest snapshot — CoW, and MoR with
    log-file merging (``query_type='read_optimized'`` for base files
    only). Reference: ``daft/io/_hudi.py`` over pyhudi, which is CoW-only;
    natively implemented — timeline + file-slice resolution + log merge
    in io/hudi.py."""
    if kwargs:
        raise TypeError(f"read_hudi: unsupported options {sorted(kwargs)} "
                        f"(incremental options are not implemented)")
    from .hudi import read_hudi as _impl
    return _impl(table_uri, io_config=io_config, query_type=query_type)


def read_lance(uri: str, version: Optional[int] = None,
               io_config=None):
    """Read a Lance dataset (reference: ``daft/io/_lance.py`` over the
    lance SDK; implemented natively — versioned column-page datasets with
    projection/limit/filter pushdown, ``io/lance.py``)."""
    from .lance import read_lance as _impl
    return _impl(uri, version=version, io_config=io_config)


def read_sql(sql: str, conn, partition_col: Optional[str] = None,
             num_partitions: Optional[int] = None, **kwargs):
    """Read from a SQL database via a user-supplied connection factory
    (reference: ``daft/io/_sql.py`` over connectorx/sqlalchemy). ``conn``
    is a zero-arg callable returning a DB-API connection. With
    ``partition_col`` + ``num_partitions`` the read splits into range
    predicates over the column, fetched lazily per scan task (the
    reference's partitioned-read path)."""
    from ..dataframe import DataFrame
    from ..logical.builder import LogicalPlanBuilder
    from ..recordbatch import RecordBatch
    from .scan import ScanTask, ScanOperator, Pushdowns
    import pyarrow as pa

    if not callable(conn):
        raise TypeError("conn must be a zero-arg callable returning a "
                        "DB-API connection")

    def fetch(query: str) -> "RecordBatch":
        c = conn()
        try:
            cur = c.cursor()
            cur.execute(query)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            c.close()
        data = {nm: [r[i] for r in rows] for i, nm in enumerate(cols)}
        return RecordBatch.from_arrow_table(pa.table(data))

    # schema from a one-row probe (a zero-row probe would type every
    # column null); full results stay lazy in the scan tasks
    try:
        probe = fetch(f"SELECT * FROM ({sql}) LIMIT 1")
    except Exception:
        probe = fetch(sql)
    schema = probe.schema

    def make_task(query: str, pushdowns: Pushdowns) -> ScanTask:
        return ScanTask([], "sql", schema, pushdowns,
                        generator=lambda q=query: iter([fetch(q)]))

    class _SQLScan(ScanOperator):
        def schema(self):
            return schema

        def multiline_display(self):
            return [f"SQLScanOperator({sql[:40]!r})"]

        def to_scan_tasks(self, pushdowns: Pushdowns):
            if partition_col is None or not num_partitions \
                    or num_partitions <= 1:
                return [make_task(sql, pushdowns)]
            bounds = fetch(f"SELECT MIN({partition_col}), "
                           f"MAX({partition_col}) FROM ({sql})")
            row = bounds.to_arrow_table().to_pylist()[0]
            lo, hi = list(row.values())
            if lo is None or hi is None or lo == hi:
                return [make_task(sql, pushdowns)]
            step = (hi - lo) / num_partitions
            tasks = []
            for i in range(num_partitions):
                a = lo + step * i
                b = lo + step * (i + 1)
                last = i == num_partitions - 1
                cond = (f"{partition_col} >= {a!r} AND "
                        + (f"{partition_col} <= {hi!r}" if last
                           else f"{partition_col} < {b!r}"))
                tasks.append(make_task(
                    f"SELECT * FROM ({sql}) WHERE {cond}", pushdowns))
            return tasks

    return DataFrame(LogicalPlanBuilder.from_scan(_SQLScan()))
