"""Native GCS object source over the JSON API.

Capability mirror of the reference's GCS client (``src/daft-io/src/
google_cloud.rs``: authenticated + anonymous modes, ranged reads, list
pagination) built on the public GCS JSON API with stdlib ``http.client`` —
no SDK, same stance as the S3 source (``s3.py``). Auth is a static OAuth2
bearer token (``GCSConfig.access_token`` / ``GCS_ACCESS_TOKEN`` env);
anonymous works for public buckets. ``endpoint_url`` points at emulators in
tests.
"""

from __future__ import annotations

import http.client
import json
import os
import re
import time
import urllib.parse
from typing import Dict, Iterator, List, Optional, Tuple

from .object_io import (RETRYABLE_STATUS as _RETRYABLE_STATUS,
                        GCSConfig, IOStatsContext, ObjectSource,
                        parallel_get_ranges, retry_backoff_s)
from .s3 import _ConnectionPool, _glob_regex


def _parse_gs_url(path: str) -> Tuple[str, str]:
    u = urllib.parse.urlparse(path)
    if u.scheme != "gs":
        raise ValueError(f"not a gs url: {path!r}")
    return u.netloc, u.path.lstrip("/")


class GCSSource(ObjectSource):
    scheme = "gs"

    def __init__(self, config: GCSConfig = GCSConfig()):
        self.config = config
        self._pool = _ConnectionPool(config.max_connections)
        self._token = config.access_token \
            or os.environ.get("GCS_ACCESS_TOKEN")
        endpoint = config.endpoint_url \
            or os.environ.get("GCS_ENDPOINT_URL") \
            or "https://storage.googleapis.com"
        u = urllib.parse.urlparse(endpoint)
        self._tls = u.scheme == "https"
        self._host = u.hostname
        self._port = u.port or (443 if self._tls else 80)

    # ------------------------------------------------------------ transport
    def _request(self, method: str, path: str, headers: Dict[str, str] = None,
                 body: bytes = b"") -> Tuple[int, Dict[str, str], bytes]:
        hdrs = dict(headers or {})
        if self._token and not self.config.anonymous:
            hdrs["Authorization"] = f"Bearer {self._token}"
        last_exc: Optional[Exception] = None
        for attempt in range(max(1, self.config.num_tries)):
            conn = self._pool.acquire(self._host, self._port, self._tls)
            try:
                conn.request(method, path, body=body or None, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                status = resp.status
                rheaders = dict(resp.getheaders())
                self._pool.release(self._host, self._port, self._tls, conn)
            except (OSError, http.client.HTTPException) as exc:
                conn.close()
                last_exc = exc
                time.sleep(retry_backoff_s(path, attempt))
                continue
            if status in _RETRYABLE_STATUS:
                last_exc = RuntimeError(
                    f"gcs {method} {path}: HTTP {status}: {data[:200]!r}")
                time.sleep(retry_backoff_s(path, attempt))
                continue
            return status, rheaders, data
        raise last_exc

    @staticmethod
    def _object_path(bucket: str, key: str, **params) -> str:
        p = f"/storage/v1/b/{bucket}/o/{urllib.parse.quote(key, safe='')}"
        if params:
            p += "?" + urllib.parse.urlencode(params)
        return p

    # ------------------------------------------------------- ObjectSource
    def get(self, path, byte_range=None, stats=None) -> bytes:
        bucket, key = _parse_gs_url(path)
        headers = {}
        if byte_range is not None:
            headers["Range"] = f"bytes={byte_range[0]}-{byte_range[1] - 1}"
        status, _, data = self._request(
            "GET", self._object_path(bucket, key, alt="media"), headers)
        if status not in (200, 206):
            raise FileNotFoundError(f"gcs GET {path}: HTTP {status}")
        if stats:
            stats.record_get(len(data))
        return data

    def get_ranges(self, path, ranges, stats=None, parallelism=None):
        return parallel_get_ranges(
            self, path, ranges, stats,
            min(parallelism or 8, self.config.max_connections))

    def put(self, path, data, stats=None) -> None:
        bucket, key = _parse_gs_url(path)
        p = (f"/upload/storage/v1/b/{bucket}/o?uploadType=media&"
             f"name={urllib.parse.quote(key, safe='')}")
        status, _, body = self._request(
            "POST", p, {"Content-Type": "application/octet-stream"}, data)
        if status not in (200, 201):
            raise IOError(f"gcs PUT {path}: HTTP {status}: {body[:200]!r}")
        if stats:
            stats.record_put(len(data))

    def get_size(self, path) -> int:
        bucket, key = _parse_gs_url(path)
        status, _, data = self._request(
            "GET", self._object_path(bucket, key))
        if status != 200:
            raise FileNotFoundError(f"gcs STAT {path}: HTTP {status}")
        return int(json.loads(data).get("size", 0))

    def _list(self, bucket: str, prefix: str,
              stats: Optional[IOStatsContext] = None
              ) -> Iterator[Tuple[str, int]]:
        token = None
        while True:
            params = {"prefix": prefix}
            if token:
                params["pageToken"] = token
            p = f"/storage/v1/b/{bucket}/o?" + urllib.parse.urlencode(params)
            status, _, data = self._request("GET", p)
            if status != 200:
                raise IOError(f"gcs LIST {bucket}/{prefix}: HTTP {status}")
            if stats:
                stats.record_list()
            payload = json.loads(data)
            for item in payload.get("items", []):
                yield item["name"], int(item.get("size", 0))
            token = payload.get("nextPageToken")
            if not token:
                return

    def glob(self, pattern, stats=None) -> List[str]:
        bucket, keypat = _parse_gs_url(pattern)
        wild = min((keypat.index(ch) for ch in "*?[" if ch in keypat),
                   default=None)
        if wild is None:
            return [pattern]
        prefix = keypat[:wild]
        pat = re.compile(_glob_regex(keypat))
        out = []
        for key, _size in self._list(bucket, prefix, stats=stats):
            if pat.match(key):
                out.append(f"gs://{bucket}/{key}")
        return sorted(out)

    def ls(self, path) -> Iterator[Tuple[str, int]]:
        bucket, prefix = _parse_gs_url(path)
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        for key, size in self._list(bucket, prefix):
            yield f"gs://{bucket}/{key}", size
