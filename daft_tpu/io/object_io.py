"""Multi-source object IO layer.

Capability mirror of the reference's ``src/daft-io`` crate: an
``ObjectSource`` trait (get/put/get_size/glob/ls — ``object_io.rs:177-210``)
with per-scheme implementations, an ``IOClient`` cache keyed by
(scheme, config) and ``IOStatsContext`` byte/request counters
(``src/daft-io/src/stats.rs``). Cloud sources are native no-SDK clients:
S3 (``s3.py``, SigV4), GCS (``gcs.py``, JSON API), Azure Blob
(``azure.py``, SharedKey/SAS).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
import threading
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple


# ---------------------------------------------------------------------------
# configs (reference: src/common/io-config)


@dataclasses.dataclass(frozen=True)
class S3Config:
    region_name: Optional[str] = None
    endpoint_url: Optional[str] = None
    key_id: Optional[str] = None
    access_key: Optional[str] = None
    session_token: Optional[str] = None
    anonymous: bool = False
    max_connections: int = 64
    num_tries: int = 5


@dataclasses.dataclass(frozen=True)
class GCSConfig:
    project_id: Optional[str] = None
    anonymous: bool = False
    # static OAuth2 bearer token (service-account flows need a token broker;
    # the reference reads credentials the same lazily-pluggable way)
    access_token: Optional[str] = None
    endpoint_url: Optional[str] = None  # override for emulators/tests
    max_connections: int = 32
    num_tries: int = 5


@dataclasses.dataclass(frozen=True)
class AzureConfig:
    storage_account: Optional[str] = None
    access_key: Optional[str] = None
    sas_token: Optional[str] = None
    anonymous: bool = False
    endpoint_url: Optional[str] = None  # override for Azurite/tests
    max_connections: int = 32
    num_tries: int = 5


@dataclasses.dataclass(frozen=True)
class HTTPConfig:
    user_agent: str = "daft-tpu/0.1"
    bearer_token: Optional[str] = None
    num_tries: int = 3


@dataclasses.dataclass(frozen=True)
class IOConfig:
    s3: S3Config = dataclasses.field(default_factory=S3Config)
    gcs: GCSConfig = dataclasses.field(default_factory=GCSConfig)
    azure: AzureConfig = dataclasses.field(default_factory=AzureConfig)
    http: HTTPConfig = dataclasses.field(default_factory=HTTPConfig)


# ---------------------------------------------------------------------------
# stats


class IOStatsContext:
    """Request/byte counters (reference: ``IOStatsContext``, stats.rs)."""

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self.num_gets = 0
        self.num_puts = 0
        self.num_lists = 0
        self.bytes_read = 0
        self.bytes_written = 0

    def record_get(self, nbytes: int):
        with self._lock:
            self.num_gets += 1
            self.bytes_read += nbytes

    def record_put(self, nbytes: int):
        with self._lock:
            self.num_puts += 1
            self.bytes_written += nbytes

    def record_list(self):
        with self._lock:
            self.num_lists += 1

    def as_dict(self) -> Dict[str, int]:
        return {"num_gets": self.num_gets, "num_puts": self.num_puts,
                "num_lists": self.num_lists, "bytes_read": self.bytes_read,
                "bytes_written": self.bytes_written}


# ---------------------------------------------------------------------------
# sources


class ObjectSource:
    """Scheme-specific object storage backend (reference trait:
    ``src/daft-io/src/object_io.rs:177-210``)."""

    scheme = ""

    def get(self, path: str, byte_range: Optional[Tuple[int, int]] = None,
            stats: Optional[IOStatsContext] = None) -> bytes:
        raise NotImplementedError

    def put(self, path: str, data: bytes,
            stats: Optional[IOStatsContext] = None) -> None:
        raise NotImplementedError

    def get_size(self, path: str) -> int:
        raise NotImplementedError

    def glob(self, pattern: str,
             stats: Optional[IOStatsContext] = None) -> List[str]:
        raise NotImplementedError

    def ls(self, path: str) -> Iterator[Tuple[str, int]]:
        raise NotImplementedError


class LocalSource(ObjectSource):
    scheme = "file"

    @staticmethod
    def _strip(path: str) -> str:
        if path.startswith("file://"):
            return path[len("file://"):]
        return path

    def get(self, path, byte_range=None, stats=None):
        p = self._strip(path)
        with open(p, "rb") as f:
            if byte_range is not None:
                start, end = byte_range
                f.seek(start)
                data = f.read(end - start)
            else:
                data = f.read()
        if stats:
            stats.record_get(len(data))
        return data

    def put(self, path, data, stats=None):
        p = self._strip(path)
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)
        if stats:
            stats.record_put(len(data))

    def get_size(self, path):
        return os.path.getsize(self._strip(path))

    def glob(self, pattern, stats=None):
        if stats:
            stats.record_list()
        p = self._strip(pattern)
        if os.path.isdir(p):
            p = os.path.join(p, "**")
        hits = sorted(h for h in _glob.glob(p, recursive=True)
                      if os.path.isfile(h))
        return hits

    def ls(self, path):
        p = self._strip(path)
        for entry in sorted(os.listdir(p)):
            full = os.path.join(p, entry)
            yield full, (os.path.getsize(full) if os.path.isfile(full) else 0)


class HTTPSource(ObjectSource):
    scheme = "http"

    def __init__(self, config: HTTPConfig = HTTPConfig()):
        self.config = config

    def _request(self, path: str, byte_range=None):
        headers = {"User-Agent": self.config.user_agent}
        if self.config.bearer_token:
            headers["Authorization"] = f"Bearer {self.config.bearer_token}"
        if byte_range is not None:
            headers["Range"] = f"bytes={byte_range[0]}-{byte_range[1] - 1}"
        return urllib.request.Request(path, headers=headers)

    def get(self, path, byte_range=None, stats=None):
        last_err = None
        for _ in range(max(1, self.config.num_tries)):
            try:
                with urllib.request.urlopen(self._request(path, byte_range)) as r:
                    data = r.read()
                if stats:
                    stats.record_get(len(data))
                return data
            except Exception as exc:  # retry on transient network errors
                last_err = exc
        raise last_err

    def get_size(self, path):
        req = self._request(path)
        req.get_method = lambda: "HEAD"
        with urllib.request.urlopen(req) as r:
            return int(r.headers.get("Content-Length", 0))


# ---------------------------------------------------------------------------
# client


class IOClient:
    """Caches one ``ObjectSource`` per (scheme, config) — reference:
    ``IOClient`` cache in ``src/daft-io/src/lib.rs``."""

    def __init__(self, config: Optional[IOConfig] = None):
        self.config = config or IOConfig()
        self._sources: Dict[str, ObjectSource] = {}
        self._lock = threading.Lock()

    def source_for(self, path: str) -> ObjectSource:
        scheme = urllib.parse.urlparse(path).scheme or "file"
        if scheme in ("http", "https"):
            scheme = "http"
        if scheme == "s3a":
            scheme = "s3"
        with self._lock:
            src = self._sources.get(scheme)
            if src is None:
                src = self._make(scheme)
                self._sources[scheme] = src
            return src

    def _make(self, scheme: str) -> ObjectSource:
        if scheme == "file":
            return LocalSource()
        if scheme == "http":
            return HTTPSource(self.config.http)
        if scheme in ("s3", "s3a"):
            from .s3 import S3Source
            return S3Source(self.config.s3)
        if scheme == "gs":
            from .gcs import GCSSource
            return GCSSource(self.config.gcs)
        if scheme in ("az", "abfs", "abfss"):
            from .azure import AzureBlobSource
            return AzureBlobSource(self.config.azure)
        if scheme == "hf":
            from .hf import HFSource
            return HFSource(self.config.http)
        raise ValueError(f"unsupported URL scheme {scheme!r}")

    # convenience passthroughs
    def get(self, path, byte_range=None, stats=None) -> bytes:
        return self.source_for(path).get(path, byte_range, stats)

    def put(self, path, data, stats=None) -> None:
        return self.source_for(path).put(path, data, stats)

    def glob(self, pattern, stats=None) -> List[str]:
        return self.source_for(pattern).glob(pattern, stats)


_default_client: Optional[IOClient] = None
_default_lock = threading.Lock()


def get_io_client(config: Optional[IOConfig] = None) -> IOClient:
    global _default_client
    if config is not None:
        return IOClient(config)
    with _default_lock:
        if _default_client is None:
            _default_client = IOClient()
        return _default_client
