"""Multi-source object IO layer.

Capability mirror of the reference's ``src/daft-io`` crate: an
``ObjectSource`` trait (get/put/get_size/glob/ls — ``object_io.rs:177-210``)
with per-scheme implementations, an ``IOClient`` cache keyed by
(scheme, config) and ``IOStatsContext`` byte/request counters
(``src/daft-io/src/stats.rs``). Cloud sources are native no-SDK clients:
S3 (``s3.py``, SigV4), GCS (``gcs.py``, JSON API), Azure Blob
(``azure.py``, SharedKey/SAS).
"""

from __future__ import annotations

import concurrent.futures as _cf
import dataclasses
import glob as _glob
import hashlib
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple

#: HTTP statuses worth retrying across every object source (throttle +
#: transient server errors); any other 4xx/3xx is deterministic — retrying
#: a 404 just burns the whole retry budget against a missing key
RETRYABLE_STATUS = frozenset({408, 429, 500, 502, 503, 504})


def retry_backoff_s(key: str, attempt: int, base: float = 0.05,
                    cap: float = 2.0) -> float:
    """Bounded exponential backoff with deterministic jitter for object
    source retry loops (same policy shape as the resilience plane's
    ``RetryPolicy.backoff_s`` / ``FetchRetryState``: the jitter hashes
    from (key, attempt), so chaos replays pace identically)."""
    exp = base * (2 ** max(attempt, 0))
    h = int(hashlib.sha256(f"{key}:{attempt}".encode()).hexdigest()[:8], 16)
    return min(exp * (0.5 + h / 0xFFFFFFFF), cap)


_io_pool_lock = threading.Lock()
_io_pool: Optional[_cf.ThreadPoolExecutor] = None


def io_pool() -> _cf.ThreadPoolExecutor:
    """Shared bounded pool for parallel range fetches (the process-wide
    analogue of the reference's tokio IO runtime)."""
    global _io_pool
    with _io_pool_lock:
        if _io_pool is None:
            _io_pool = _cf.ThreadPoolExecutor(
                max_workers=max(min((os.cpu_count() or 4) * 2, 16), 4),
                thread_name_prefix="daft-tpu-io")
        return _io_pool


def parallel_get_ranges(source: "ObjectSource", path: str,
                        ranges: List[Tuple[int, int]],
                        stats: Optional["IOStatsContext"] = None,
                        parallelism: Optional[int] = None) -> List[bytes]:
    """Fetch ``ranges`` concurrently on the shared IO pool, bounded by
    ``parallelism`` in-flight requests; results come back in input order.
    The per-scheme sources route ``get_ranges`` here (their connection
    pools make the concurrent GETs reuse sockets)."""
    par = max(parallelism or 1, 1)
    if len(ranges) <= 1 or par <= 1:
        return [source.get(path, r, stats) for r in ranges]
    pool = io_pool()
    out: List[Optional[bytes]] = [None] * len(ranges)
    it = iter(enumerate(ranges))
    pending = {}
    err: List[BaseException] = []

    from .. import observability as obs
    attr_ctx = obs.current_attribution()

    def submit():
        try:
            i, r = next(it)
        except StopIteration:
            return
        # IO-pool workers inherit the submitting query's stats
        # attribution so per-query io counters stay scoped
        pending[pool.submit(obs.run_attributed, attr_ctx,
                            source.get, path, r, stats)] = i

    for _ in range(min(par, len(ranges))):
        submit()
    while pending:
        done, _ = _cf.wait(list(pending),
                           return_when=_cf.FIRST_COMPLETED)
        for f in done:
            i = pending.pop(f)
            try:
                out[i] = f.result()
            except BaseException as exc:  # noqa: BLE001
                err.append(exc)
            if not err:
                submit()
    if err:
        raise err[0]
    return out


# ---------------------------------------------------------------------------
# configs (reference: src/common/io-config)


@dataclasses.dataclass(frozen=True)
class S3Config:
    region_name: Optional[str] = None
    endpoint_url: Optional[str] = None
    key_id: Optional[str] = None
    access_key: Optional[str] = None
    session_token: Optional[str] = None
    anonymous: bool = False
    max_connections: int = 64
    num_tries: int = 5


@dataclasses.dataclass(frozen=True)
class GCSConfig:
    project_id: Optional[str] = None
    anonymous: bool = False
    # static OAuth2 bearer token (service-account flows need a token broker;
    # the reference reads credentials the same lazily-pluggable way)
    access_token: Optional[str] = None
    endpoint_url: Optional[str] = None  # override for emulators/tests
    max_connections: int = 32
    num_tries: int = 5


@dataclasses.dataclass(frozen=True)
class AzureConfig:
    storage_account: Optional[str] = None
    access_key: Optional[str] = None
    sas_token: Optional[str] = None
    anonymous: bool = False
    endpoint_url: Optional[str] = None  # override for Azurite/tests
    max_connections: int = 32
    num_tries: int = 5


@dataclasses.dataclass(frozen=True)
class HTTPConfig:
    user_agent: str = "daft-tpu/0.1"
    bearer_token: Optional[str] = None
    num_tries: int = 3


@dataclasses.dataclass(frozen=True)
class IOConfig:
    s3: S3Config = dataclasses.field(default_factory=S3Config)
    gcs: GCSConfig = dataclasses.field(default_factory=GCSConfig)
    azure: AzureConfig = dataclasses.field(default_factory=AzureConfig)
    http: HTTPConfig = dataclasses.field(default_factory=HTTPConfig)


# ---------------------------------------------------------------------------
# stats


class IOStatsContext:
    """Request/byte counters (reference: ``IOStatsContext``, stats.rs)."""

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self.num_gets = 0
        self.num_puts = 0
        self.num_lists = 0
        self.bytes_read = 0
        self.bytes_written = 0

    def record_get(self, nbytes: int):
        with self._lock:
            self.num_gets += 1
            self.bytes_read += nbytes

    def record_put(self, nbytes: int):
        with self._lock:
            self.num_puts += 1
            self.bytes_written += nbytes

    def record_list(self):
        with self._lock:
            self.num_lists += 1

    def as_dict(self) -> Dict[str, int]:
        return {"num_gets": self.num_gets, "num_puts": self.num_puts,
                "num_lists": self.num_lists, "bytes_read": self.bytes_read,
                "bytes_written": self.bytes_written}


# ---------------------------------------------------------------------------
# sources


class ObjectSource:
    """Scheme-specific object storage backend (reference trait:
    ``src/daft-io/src/object_io.rs:177-210``)."""

    scheme = ""

    def get(self, path: str, byte_range: Optional[Tuple[int, int]] = None,
            stats: Optional[IOStatsContext] = None) -> bytes:
        raise NotImplementedError

    def get_ranges(self, path: str, ranges: List[Tuple[int, int]],
                   stats: Optional[IOStatsContext] = None,
                   parallelism: Optional[int] = None) -> List[bytes]:
        """Fetch several byte ranges of one object; results in input
        order. Default loops over :meth:`get`; network sources override
        with pooled concurrent requests."""
        return [self.get(path, r, stats) for r in ranges]

    def put(self, path: str, data: bytes,
            stats: Optional[IOStatsContext] = None) -> None:
        raise NotImplementedError

    def version(self, path: str):
        """Version token for ``path`` — a tuple that changes whenever
        the object's bytes may have changed (size + etag / mtime…), or
        None when this store exposes no version signal. The serving
        plan/result caches key remote sources on this, so a store
        without one keeps remote plans uncacheable (fail-safe)."""
        return None

    def get_size(self, path: str) -> int:
        raise NotImplementedError

    def glob(self, pattern: str,
             stats: Optional[IOStatsContext] = None) -> List[str]:
        raise NotImplementedError

    def ls(self, path: str) -> Iterator[Tuple[str, int]]:
        raise NotImplementedError


class LocalSource(ObjectSource):
    scheme = "file"

    @staticmethod
    def _strip(path: str) -> str:
        if path.startswith("file://"):
            return path[len("file://"):]
        return path

    def get(self, path, byte_range=None, stats=None):
        p = self._strip(path)
        with open(p, "rb") as f:
            if byte_range is not None:
                start, end = byte_range
                f.seek(start)
                data = f.read(end - start)
            else:
                data = f.read()
        if stats:
            stats.record_get(len(data))
        return data

    def get_ranges(self, path, ranges, stats=None, parallelism=None):
        # one open + seeks: local disk gains nothing from pooled threads
        out = []
        with open(self._strip(path), "rb") as f:
            for start, end in ranges:
                f.seek(start)
                out.append(f.read(end - start))
        if stats:
            for b in out:
                stats.record_get(len(b))
        return out

    def put(self, path, data, stats=None):
        p = self._strip(path)
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)
        if stats:
            stats.record_put(len(data))

    def get_size(self, path):
        return os.path.getsize(self._strip(path))

    def version(self, path):
        try:
            st = os.stat(self._strip(path))
            return ("stat", int(st.st_size), int(st.st_mtime_ns))
        except OSError:
            return None

    def glob(self, pattern, stats=None):
        if stats:
            stats.record_list()
        p = self._strip(pattern)
        if os.path.isdir(p):
            p = os.path.join(p, "**")
        hits = sorted(h for h in _glob.glob(p, recursive=True)
                      if os.path.isfile(h))
        return hits

    def ls(self, path):
        p = self._strip(path)
        for entry in sorted(os.listdir(p)):
            full = os.path.join(p, entry)
            yield full, (os.path.getsize(full) if os.path.isfile(full) else 0)


class HTTPSource(ObjectSource):
    scheme = "http"

    def __init__(self, config: HTTPConfig = HTTPConfig()):
        self.config = config

    def _request(self, path: str, byte_range=None):
        headers = {"User-Agent": self.config.user_agent}
        if self.config.bearer_token:
            headers["Authorization"] = f"Bearer {self.config.bearer_token}"
        if byte_range is not None:
            headers["Range"] = f"bytes={byte_range[0]}-{byte_range[1] - 1}"
        return urllib.request.Request(path, headers=headers)

    def get(self, path, byte_range=None, stats=None):
        last_err = None
        tries = max(1, self.config.num_tries)
        for attempt in range(tries):
            try:
                with urllib.request.urlopen(self._request(path, byte_range)) as r:
                    data = r.read()
                if stats:
                    stats.record_get(len(data))
                return data
            except urllib.error.HTTPError as exc:
                # non-transient statuses (404, 403, 400 …) are
                # deterministic: retrying just burns the budget
                if exc.code not in RETRYABLE_STATUS:
                    raise
                last_err = exc
            except Exception as exc:  # transient network errors
                last_err = exc
            if attempt + 1 < tries:
                time.sleep(retry_backoff_s(path, attempt))
        raise last_err

    def get_ranges(self, path, ranges, stats=None, parallelism=None):
        return parallel_get_ranges(self, path, ranges, stats,
                                   parallelism or 8)

    def get_size(self, path):
        req = self._request(path)
        req.get_method = lambda: "HEAD"
        with urllib.request.urlopen(req) as r:
            return int(r.headers.get("Content-Length", 0))

    def version(self, path):
        # etag (or last-modified) + size from one HEAD; servers sending
        # neither give no change signal, so the source stays uncacheable
        req = self._request(path)
        req.get_method = lambda: "HEAD"
        try:
            with urllib.request.urlopen(req) as r:
                tag = r.headers.get("ETag") \
                    or r.headers.get("Last-Modified")
                size = int(r.headers.get("Content-Length", 0) or 0)
        except Exception:
            return None
        if not tag:
            return None
        return ("http", size, tag)


# ---------------------------------------------------------------------------
# client


class IOClient:
    """Caches one ``ObjectSource`` per (scheme, config) — reference:
    ``IOClient`` cache in ``src/daft-io/src/lib.rs``."""

    def __init__(self, config: Optional[IOConfig] = None):
        self.config = config or IOConfig()
        self._sources: Dict[str, ObjectSource] = {}
        self._lock = threading.Lock()

    def source_for(self, path: str) -> ObjectSource:
        scheme = urllib.parse.urlparse(path).scheme or "file"
        if scheme in ("http", "https"):
            scheme = "http"
        if scheme == "s3a":
            scheme = "s3"
        with self._lock:
            src = self._sources.get(scheme)
            if src is None:
                src = self._make(scheme)
                self._sources[scheme] = src
            return src

    def _make(self, scheme: str) -> ObjectSource:
        if scheme == "file":
            return LocalSource()
        if scheme == "http":
            return HTTPSource(self.config.http)
        if scheme in ("s3", "s3a"):
            from .s3 import S3Source
            return S3Source(self.config.s3)
        if scheme == "gs":
            from .gcs import GCSSource
            return GCSSource(self.config.gcs)
        if scheme in ("az", "abfs", "abfss"):
            from .azure import AzureBlobSource
            return AzureBlobSource(self.config.azure)
        if scheme == "hf":
            from .hf import HFSource
            return HFSource(self.config.http)
        raise ValueError(f"unsupported URL scheme {scheme!r}")

    # convenience passthroughs
    def get(self, path, byte_range=None, stats=None) -> bytes:
        return self.source_for(path).get(path, byte_range, stats)

    def get_ranges(self, path, ranges, stats=None,
                   parallelism=None) -> List[bytes]:
        return self.source_for(path).get_ranges(path, ranges, stats,
                                                parallelism)

    def put(self, path, data, stats=None) -> None:
        return self.source_for(path).put(path, data, stats)

    def glob(self, pattern, stats=None) -> List[str]:
        return self.source_for(pattern).glob(pattern, stats)

    def version(self, path):
        return self.source_for(path).version(path)


_default_client: Optional[IOClient] = None
_default_lock = threading.Lock()


def get_io_client(config: Optional[IOConfig] = None) -> IOClient:
    global _default_client
    if config is not None:
        return IOClient(config)
    with _default_lock:
        if _default_client is None:
            _default_client = IOClient()
        return _default_client
