from .read import read_parquet, read_csv, read_json, read_warc
from .scan import Pushdowns, ScanOperator, ScanTask
from .sink import DataSink, WriteResult

__all__ = ["read_parquet", "read_csv", "read_json", "read_warc", "Pushdowns",
           "ScanOperator", "ScanTask", "DataSink", "WriteResult"]
