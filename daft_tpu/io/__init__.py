from .read import (read_parquet, read_csv, read_json, read_warc,
                   read_deltalake, read_iceberg, read_hudi, read_lance,
                   read_sql)
from .scan import Pushdowns, ScanOperator, ScanTask
from .sink import DataSink, WriteResult

__all__ = ["read_parquet", "read_csv", "read_json", "read_warc",
           "read_deltalake", "read_iceberg", "read_hudi", "read_lance",
           "read_sql", "Pushdowns",
           "ScanOperator", "ScanTask", "DataSink", "WriteResult"]
