from .read import read_parquet, read_csv, read_json
from .scan import Pushdowns, ScanOperator, ScanTask
from .sink import DataSink, WriteResult

__all__ = ["read_parquet", "read_csv", "read_json", "Pushdowns",
           "ScanOperator", "ScanTask", "DataSink", "WriteResult"]
