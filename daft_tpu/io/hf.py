"""HuggingFace Hub object source: ``hf://[datasets/]org/repo[@rev]/path``.

Capability mirror of the reference's HF client (``src/daft-io/src/
huggingface.rs``): resolve-URL downloads, tree-API listing/glob, optional
bearer token, anonymous for public repos. Rides the HTTP source's
request/retry machinery; ``HF_ENDPOINT`` points at a mirror or a mock
server in tests.
"""

from __future__ import annotations

import json
import os
import re
import urllib.parse
import urllib.request
from typing import Iterator, List, Optional, Tuple

from .object_io import HTTPConfig, HTTPSource, IOStatsContext, ObjectSource


def _parse_hf_url(path: str) -> Tuple[str, str, str, str]:
    """→ (repo_type, repo_id, revision, path_in_repo)."""
    u = urllib.parse.urlparse(path)
    if u.scheme != "hf":
        raise ValueError(f"not an hf url: {path!r}")
    full = (u.netloc + u.path).strip("/")
    parts = full.split("/")
    if parts and parts[0] in ("datasets", "spaces", "models"):
        repo_type = parts[0]
        parts = parts[1:]
    else:
        repo_type = "datasets"
    if len(parts) < 2:
        raise ValueError(f"hf url needs org/repo: {path!r}")
    org, repo = parts[0], parts[1]
    revision = "main"
    if "@" in repo:
        repo, revision = repo.split("@", 1)
    return repo_type, f"{org}/{repo}", revision, "/".join(parts[2:])


class HFSource(ObjectSource):
    scheme = "hf"

    def __init__(self, config: HTTPConfig = HTTPConfig()):
        token = config.bearer_token or os.environ.get("HF_TOKEN")
        self._http = HTTPSource(HTTPConfig(
            user_agent=config.user_agent, bearer_token=token,
            num_tries=config.num_tries))
        self._endpoint = os.environ.get("HF_ENDPOINT",
                                        "https://huggingface.co")

    def _resolve_url(self, path: str) -> str:
        repo_type, repo_id, rev, inner = _parse_hf_url(path)
        prefix = "" if repo_type == "models" else f"{repo_type}/"
        return (f"{self._endpoint}/{prefix}{repo_id}/resolve/"
                f"{urllib.parse.quote(rev, safe='')}/"
                f"{urllib.parse.quote(inner, safe='/')}")

    # ------------------------------------------------------- ObjectSource
    def get(self, path, byte_range=None, stats=None) -> bytes:
        return self._http.get(self._resolve_url(path), byte_range, stats)

    def get_size(self, path) -> int:
        return self._http.get_size(self._resolve_url(path))

    def _tree(self, repo_type: str, repo_id: str, rev: str,
              subpath: str) -> List[dict]:
        url = (f"{self._endpoint}/api/{repo_type}/{repo_id}/tree/"
               f"{urllib.parse.quote(rev, safe='')}")
        if subpath:
            url += f"/{subpath}"
        url += "?recursive=true"
        body = self._http.get(url)
        return json.loads(body)

    def glob(self, pattern, stats=None) -> List[str]:
        from .s3 import _glob_regex
        repo_type, repo_id, rev, inner = _parse_hf_url(pattern)
        wild = min((inner.index(ch) for ch in "*?[" if ch in inner),
                   default=None)
        if wild is None:
            return [pattern]
        prefix = inner[:wild].rsplit("/", 1)[0] if "/" in inner[:wild] else ""
        if stats:
            stats.record_list()
        entries = self._tree(repo_type, repo_id, rev, prefix)
        rx = re.compile(_glob_regex(inner))
        at = "" if rev == "main" else f"@{rev}"
        base = f"hf://{repo_type}/{repo_id}{at}"
        return sorted(f"{base}/{e['path']}" for e in entries
                      if e.get("type") == "file" and rx.match(e["path"]))

    def ls(self, path) -> Iterator[Tuple[str, int]]:
        repo_type, repo_id, rev, inner = _parse_hf_url(path)
        at = "" if rev == "main" else f"@{rev}"
        base = f"hf://{repo_type}/{repo_id}{at}"
        for e in self._tree(repo_type, repo_id, rev, inner):
            if e.get("type") == "file":
                yield f"{base}/{e['path']}", int(e.get("size", 0))
