"""Scan-side byte-range read planner (reference: ``daft-parquet/read_planner``).

The scan fast path's planning layer: given a parquet footer plus the
projected columns and pruned row groups, emit the EXACT byte ranges the
decode will touch, coalesce them (hole tolerance + request floor) into few
large GETs, fetch them concurrently over the source's connection pool
(``ObjectSource.get_ranges``), and hand pyarrow an in-memory
:class:`RangeCache` file shim so it never issues its own small GETs.

Also owns the process-wide scan-plane counters (mirroring the shuffle
counters in ``distributed/shuffle_service.py``): ``RuntimeStatsContext``
snapshots at query start and diffs at ``finish()`` into the per-query
``io`` block — requests issued vs ranges planned (coalescing evidence),
bytes fetched vs bytes used (over-fetch), and prefetch overlap wall vs
serial-equivalent.
"""

from __future__ import annotations

import io
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from .object_io import IOStatsContext


# ------------------------------------------------------- scan-plane counters

_counters_lock = threading.Lock()
_counters: Dict[str, float] = {}


def scan_count(name: str, n: float = 1) -> None:
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + n
    # also credit the thread's attributed query context (serving plane:
    # two overlapping queries must not read each other's io counters)
    from .. import observability as obs
    obs.bump_plane("io", name, n)


def scan_counters_snapshot() -> Dict[str, float]:
    with _counters_lock:
        return dict(_counters)


def scan_counters_delta(before: Dict[str, float],
                        after: Optional[Dict[str, float]] = None
                        ) -> Dict[str, float]:
    if after is None:
        after = scan_counters_snapshot()
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


def scan_counters_reset() -> None:
    with _counters_lock:
        _counters.clear()


class _ScanIOStats(IOStatsContext):
    """The previously-dangling ``IOStatsContext``, wired for real: every
    scan-path object GET/PUT records here AND mirrors into the process-wide
    scan counters so the per-query ``io`` stats block sees it."""

    def record_get(self, nbytes: int):
        super().record_get(nbytes)
        scan_count("gets")
        scan_count("bytes_fetched", nbytes)

    def record_list(self):
        super().record_list()
        scan_count("lists")


#: process-wide stats context threaded through planner / fetch / scan reads
SCAN_STATS = _ScanIOStats("scan")


# ----------------------------------------------------------------- knobs

def _env_bytes(name: str) -> Optional[int]:
    from ..analysis import knobs
    return knobs.env_bytes(name, default=None)


def _cfg(attr: str, default):
    try:
        from ..context import get_context
        return getattr(get_context().execution_config, attr)
    except Exception:
        return default


def coalesce_gap_bytes() -> int:
    """Hole tolerance for range coalescing (``DAFT_TPU_IO_COALESCE_GAP``,
    default 1MiB): two needed ranges separated by at most this many waste
    bytes merge into one request."""
    v = _env_bytes("DAFT_TPU_IO_COALESCE_GAP")
    return v if v is not None else _cfg("tpu_io_coalesce_gap", 1 << 20)


def min_request_bytes() -> int:
    """Request floor (``DAFT_TPU_IO_MIN_REQUEST``, default 8MiB): after
    gap-coalescing, a sub-floor request absorbs its neighbor when the hole
    between them is itself smaller than the floor — request count drops
    toward per-RTT-amortizing sizes with bounded waste."""
    v = _env_bytes("DAFT_TPU_IO_MIN_REQUEST")
    return v if v is not None else _cfg("tpu_io_min_request", 8 << 20)


def range_parallelism() -> int:
    """Concurrent range GETs per source (``DAFT_TPU_IO_RANGE_PARALLELISM``,
    default 8; each source additionally caps at its configured
    ``max_connections``)."""
    from ..analysis import knobs
    v = knobs.env_int("DAFT_TPU_IO_RANGE_PARALLELISM", default=None)
    if v is not None:
        return max(v, 1)
    return max(int(_cfg("tpu_io_range_parallelism", 8)), 1)


def planned_reads_enabled() -> bool:
    """``DAFT_TPU_IO_PLANNED_READS=0`` restores the naive per-column-chunk
    ranged-read path (the pre-fast-path behavior; also the bench baseline)."""
    from ..analysis import knobs
    v = knobs.env_bool("DAFT_TPU_IO_PLANNED_READS", default=None)
    if v is not None:
        return v
    return bool(_cfg("tpu_io_planned_reads", True))


def scan_prefetch_tasks() -> int:
    """How many upcoming ScanTasks the scan source resolves ahead of the
    consumer (``DAFT_TPU_SCAN_PREFETCH``, default 2; 0 disables)."""
    from ..analysis import knobs
    v = knobs.env_int("DAFT_TPU_SCAN_PREFETCH", default=None)
    if v is not None:
        return max(v, 0)
    return max(int(_cfg("tpu_scan_prefetch", 2)), 0)


def stream_chunk_bytes() -> int:
    """Chunk size for streaming whole-object reads (CSV/JSON),
    ``DAFT_TPU_IO_STREAM_CHUNK`` default 8MiB."""
    v = _env_bytes("DAFT_TPU_IO_STREAM_CHUNK")
    return v if v is not None else 8 << 20


def infer_head_bytes() -> int:
    """Byte budget for head-range schema inference on remote CSV/JSON
    (``DAFT_TPU_IO_INFER_BYTES``, default 1MiB; 0 → whole object)."""
    v = _env_bytes("DAFT_TPU_IO_INFER_BYTES")
    return v if v is not None else 1 << 20


def scan_sequential_fallback() -> bool:
    """True when the scan fast path must degrade to the sequential path:
    ``DAFT_TPU_CHAOS_SERIALIZE=1`` or an active fault plan — PR 2's chaos
    replay contract requires the injected-fault exposure (and event order)
    of the pre-fast-path scan loop."""
    from ..analysis import knobs
    if knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE"):
        return True
    try:
        from ..distributed.resilience import active_fault_plan
        return active_fault_plan() is not None
    except Exception:
        return False


# -------------------------------------------------------------- planning

def plan_parquet_ranges(md, row_groups: Optional[Sequence[int]] = None,
                        columns: Optional[Sequence[str]] = None
                        ) -> List[Tuple[int, int]]:
    """Exact [start, end) byte ranges of the column chunks a read of
    ``row_groups`` × ``columns`` will touch (dictionary page through last
    data page — parquet stores them contiguously per chunk). ``None``
    means all groups / all columns. Nested columns match on their root
    name. Sorted and overlap-merged."""
    groups = range(md.num_row_groups) if row_groups is None else row_groups
    roots = None if columns is None else {c for c in columns}
    out: List[Tuple[int, int]] = []
    for g in groups:
        rg = md.row_group(g)
        for ci in range(rg.num_columns):
            cc = rg.column(ci)
            if roots is not None \
                    and cc.path_in_schema.split(".")[0] not in roots:
                continue
            start = cc.data_page_offset
            if cc.dictionary_page_offset is not None:
                start = min(start, cc.dictionary_page_offset)
            out.append((start, start + cc.total_compressed_size))
    return _normalize(out)


def _normalize(ranges: Sequence[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Sort and merge overlapping/adjacent ranges."""
    out: List[Tuple[int, int]] = []
    for s, e in sorted(r for r in ranges if r[1] > r[0]):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def coalesce_ranges(ranges: Sequence[Tuple[int, int]],
                    gap: Optional[int] = None,
                    floor: Optional[int] = None) -> List[Tuple[int, int]]:
    """Needed ranges → request ranges. Two passes:

    1. **hole tolerance**: merge neighbors separated by at most ``gap``
       waste bytes (column chunks of adjacent projected columns are
       usually separated only by the chunks of pruned columns' headers
       or nothing at all);
    2. **request floor**: a request smaller than ``floor`` absorbs its
       neighbor when the hole between them is itself under ``floor`` —
       tiny scattered chunks (many row groups × narrow projection) batch
       into RTT-amortizing GETs with bounded waste (every absorbed hole
       < floor).
    """
    gap = coalesce_gap_bytes() if gap is None else gap
    floor = min_request_bytes() if floor is None else floor
    merged = _normalize(ranges)
    if not merged:
        return []

    def merge_pass(rs: List[Tuple[int, int]], want) -> List[Tuple[int, int]]:
        out = [rs[0]]
        for s, e in rs[1:]:
            ps, pe = out[-1]
            if want(ps, pe, s, e):
                out[-1] = (ps, max(pe, e))
            else:
                out.append((s, e))
        return out

    merged = merge_pass(merged, lambda ps, pe, s, e: s - pe <= gap)
    merged = merge_pass(
        merged, lambda ps, pe, s, e: s - pe < floor
        and (pe - ps < floor or e - s < floor))
    return merged


# ------------------------------------------------------------ range cache

class RangeCache:
    """Fetched [start, end) → bytes segments; serves sub-range reads by
    slicing across segments (requests may each cover several needed
    ranges). ``read`` raises ``KeyError`` on any uncovered byte so the
    caller can fall back to a direct GET."""

    def __init__(self, segments: Sequence[Tuple[Tuple[int, int], bytes]]):
        self._segs = sorted(((s, s + len(data), data)
                             for (s, _e), data in segments),
                            key=lambda x: x[0])

    def covers(self, start: int, end: int) -> bool:
        try:
            self.read(start, end)
            return True
        except KeyError:
            return False

    def read(self, start: int, end: int) -> bytes:
        if end <= start:
            return b""
        parts = []
        pos = start
        for s, e, data in self._segs:
            if e <= pos:
                continue
            if s > pos:
                break
            take_end = min(end, e)
            parts.append(data[pos - s:take_end - s])
            pos = take_end
            if pos >= end:
                return b"".join(parts)
        raise KeyError(f"range [{start}, {end}) not covered")


class RangeCacheFile(io.RawIOBase):
    """Seekable file shim over a :class:`RangeCache`, with per-read
    fallback to direct ranged GETs for bytes the planner did not fetch
    (pyarrow header probes, planner misses). Feeds ``pa.PythonFile``."""

    def __init__(self, cache: RangeCache, source, path: str,
                 size: Optional[int] = None,
                 stats: Optional[IOStatsContext] = None):
        self._cache = cache
        self._src = source
        self._path = path
        self._lazy_size = size
        self._stats = stats
        self._pos = 0

    @property
    def _size(self) -> int:
        if self._lazy_size is None:
            self._lazy_size = self._src.get_size(self._path)
        return self._lazy_size

    def readable(self):
        return True

    def seekable(self):
        return True

    def seek(self, offset, whence=io.SEEK_SET):
        if whence == io.SEEK_SET:
            self._pos = offset
        elif whence == io.SEEK_CUR:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def tell(self):
        return self._pos

    def read(self, n=-1):
        if n is None or n < 0:
            n = self._size - self._pos
        if n <= 0:
            return b""
        start, end = self._pos, self._pos + n
        try:
            data = self._cache.read(start, end)
        except KeyError:
            # planner miss — bounded by the file size, counted so the
            # stats expose any systematic planning hole
            end = min(end, self._size)
            if end <= start:
                return b""
            scan_count("planner_miss_gets")
            data = self._src.get(self._path, (start, end), self._stats)
        self._pos += len(data)
        return data

    def size(self):
        return self._size


class ChunkedObjectReader(io.RawIOBase):
    """Sequential streaming reader over chunked ranged GETs — the
    single-pass formats' (CSV/JSON) replacement for buffering the whole
    object: resident memory is chunk-sized, and the parser starts before
    the tail arrives."""

    def __init__(self, source, path: str, chunk: Optional[int] = None,
                 stats: Optional[IOStatsContext] = None):
        self._src = source
        self._path = path
        self._chunk = chunk or stream_chunk_bytes()
        self._stats = stats
        self._size = source.get_size(path)
        self._pos = 0  # next byte to hand out
        self._buf = b""
        self._buf_at = 0  # file offset of _buf[0]

    def readable(self):
        return True

    def read(self, n=-1):
        if n is None or n < 0:
            n = self._size - self._pos
        out = []
        need = n
        while need > 0 and self._pos < self._size:
            off = self._pos - self._buf_at
            avail = len(self._buf) - off
            if avail <= 0:
                fetch_end = min(self._pos + max(self._chunk, need),
                                self._size)
                self._buf = self._src.get(self._path,
                                          (self._pos, fetch_end),
                                          self._stats)
                self._buf_at = self._pos
                off, avail = 0, len(self._buf)
                if avail == 0:
                    break
            take = min(avail, need)
            out.append(self._buf[off:off + take])
            self._pos += take
            need -= take
        return b"".join(out)
