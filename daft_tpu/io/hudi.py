"""Native Apache Hudi Copy-on-Write snapshot reader.

The reference reads Hudi through its Python SDK
(``/root/reference/daft/io/_hudi.py`` + ``daft/hudi``). This is SDK-free:
the ``.hoodie`` timeline (completed ``*.commit`` / ``*.replacecommit``
instants, JSON) and ``hoodie.properties`` are parsed directly, base files
are grouped into file slices by ``{fileId}_{writeToken}_{instantTime}``
naming, and the snapshot is the newest committed base file per live file
group — honoring replacecommits that retire file groups (clustering).

Unsupported (raises): Merge-on-Read tables (log files need the Hudi
merger), incremental queries.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from .iceberg import _get, _is_remote  # shared URI helpers
from .object_io import IOConfig, get_io_client

_BASE_FILE_RE = re.compile(
    r"^(?P<file_id>.+?)_(?P<token>[0-9\-]+)_(?P<instant>\d+)\.parquet$")


def _strip(uri: str) -> str:
    return uri[7:] if uri.startswith("file://") else uri


def _list_files(table_uri: str, io_config) -> List[str]:
    if _is_remote(table_uri):
        return get_io_client(io_config).glob(table_uri.rstrip("/") + "/**")
    root = _strip(table_uri)
    out = []
    for dirpath, _, files in os.walk(root):
        for f in files:
            out.append(os.path.join(dirpath, f))
    return sorted(out)


def _load_properties(table_uri: str, io_config) -> Dict[str, str]:
    raw = _get(f"{table_uri.rstrip('/')}/.hoodie/hoodie.properties",
               io_config).decode()
    props = {}
    for line in raw.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        k, _, v = line.partition("=")
        props[k.strip()] = v.strip()
    return props


def _timeline(files: List[str]) -> Tuple[Dict[str, str], List[str]]:
    """→ ({instant: action} for completed instants, replacecommit uris)."""
    completed: Dict[str, str] = {}
    replaces: List[str] = []
    for f in files:
        name = f.replace("\\", "/").rsplit("/", 1)[-1]
        parent = f.replace("\\", "/").rsplit("/", 2)[-2]
        if parent != ".hoodie":
            continue
        m = re.match(r"^(\d+)\.(commit|replacecommit)$", name)
        if m:
            completed[m.group(1)] = m.group(2)
            if m.group(2) == "replacecommit":
                replaces.append(f)
    return completed, replaces


def snapshot_files(table_uri: str,
                   io_config: Optional[IOConfig] = None
                   ) -> List[Dict[str, Any]]:
    """Live base files of the latest snapshot:
    [{path, partition, file_id, instant}]."""
    props = _load_properties(table_uri, io_config)
    ttype = props.get("hoodie.table.type", "COPY_ON_WRITE").upper()
    if ttype != "COPY_ON_WRITE":
        raise NotImplementedError(
            f"hudi table type {ttype}: only Copy-on-Write snapshots are "
            f"supported (Merge-on-Read needs log-file merging)")
    all_files = _list_files(table_uri, io_config)
    completed, replace_uris = _timeline(all_files)
    replaced: set = set()
    for uri in replace_uris:
        try:
            doc = json.loads(_get(uri, io_config))
        except ValueError:
            continue
        for part, ids in (doc.get("partitionToReplaceFileIds") or {}).items():
            for fid in ids:
                replaced.add((part, fid))
    root = table_uri.rstrip("/")
    groups: Dict[Tuple[str, str], Dict[str, Any]] = {}
    root_local = _strip(root).replace("\\", "/")
    for f in all_files:
        norm = f.replace("\\", "/")
        rel = norm[len(root_local):].lstrip("/") if not _is_remote(root) \
            else norm.split(root.split("://", 1)[1], 1)[-1].lstrip("/")
        if rel.startswith(".hoodie"):
            continue
        parts = rel.rsplit("/", 1)
        partition = parts[0] if len(parts) == 2 else ""
        m = _BASE_FILE_RE.match(parts[-1])
        if not m or m.group("instant") not in completed:
            continue
        if (partition, m.group("file_id")) in replaced:
            continue
        key = (partition, m.group("file_id"))
        cur = groups.get(key)
        if cur is None or m.group("instant") > cur["instant"]:
            groups[key] = {"path": f, "partition": partition,
                           "file_id": m.group("file_id"),
                           "instant": m.group("instant")}
    return sorted(groups.values(), key=lambda g: g["path"])


def read_hudi(table_uri: str, io_config: Optional[IOConfig] = None):
    """Hudi CoW table → DataFrame of its latest snapshot."""
    import daft_tpu as dt
    files = snapshot_files(table_uri, io_config)
    if not files:
        raise ValueError(f"hudi table {table_uri!r} has no committed "
                         f"base files")
    return dt.read_parquet([f["path"] for f in files], io_config=io_config)
