"""Native Apache Hudi snapshot reader: Copy-on-Write and Merge-on-Read.

The reference reads Hudi through its vendored pyhudi
(``/root/reference/daft/hudi/pyhudi/table.py``) — which REJECTS anything
but Copy-on-Write (``table.py:134``). This module is SDK-free and goes
further: the ``.hoodie`` timeline (completed ``*.commit`` /
``*.deltacommit`` / ``*.replacecommit`` instants, JSON) and
``hoodie.properties`` are parsed directly; base files group into file
slices by ``{fileId}_{writeToken}_{instantTime}`` naming, honoring
replacecommits that retire file groups (clustering).

Merge-on-Read: each file slice's log files
(``.{fileId}_{baseInstant}.log.{version}[_{token}]``) merge over the base
file by record key (``hoodie.table.recordkey.fields``, falling back to
the ``_hoodie_record_key`` meta column): later records upsert earlier
ones, records flagged ``_hoodie_is_deleted`` drop the key. Log blocks are
decoded as Avro object-container or parquet payloads (detected by magic);
the binary HoodieLogFormat framing is not parsed — a documented subset
chosen because nothing in this environment can produce or validate it
(the reference rejects MoR tables entirely). ``query_type=
"read_optimized"`` serves base files only, the standard MoR RO view.

Unsupported (raises): incremental queries.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from .iceberg import _get, _is_remote  # shared URI helpers
from .object_io import IOConfig, get_io_client

_BASE_FILE_RE = re.compile(
    r"^(?P<file_id>.+?)_(?P<token>[0-9\-]+)_(?P<instant>\d+)\.parquet$")
_LOG_FILE_RE = re.compile(
    r"^\.(?P<file_id>.+?)_(?P<base_instant>\d+)\.log\.(?P<version>\d+)"
    r"(?:_(?P<token>[\w\-]+))?$")


def _strip(uri: str) -> str:
    return uri[7:] if uri.startswith("file://") else uri


def _list_files(table_uri: str, io_config) -> List[str]:
    if _is_remote(table_uri):
        return get_io_client(io_config).glob(table_uri.rstrip("/") + "/**")
    root = _strip(table_uri)
    out = []
    for dirpath, _, files in os.walk(root):
        for f in files:
            out.append(os.path.join(dirpath, f))
    return sorted(out)


def _load_properties(table_uri: str, io_config) -> Dict[str, str]:
    raw = _get(f"{table_uri.rstrip('/')}/.hoodie/hoodie.properties",
               io_config).decode()
    props = {}
    for line in raw.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        k, _, v = line.partition("=")
        props[k.strip()] = v.strip()
    return props


def _timeline(files: List[str]) -> Tuple[Dict[str, str], List[str],
                                         List[str]]:
    """→ ({instant: action} for completed instants, replacecommit uris,
    all completed instant uris)."""
    completed: Dict[str, str] = {}
    replaces: List[str] = []
    instant_uris: List[str] = []
    for f in files:
        name = f.replace("\\", "/").rsplit("/", 1)[-1]
        parent = f.replace("\\", "/").rsplit("/", 2)[-2]
        if parent != ".hoodie":
            continue
        m = re.match(r"^(\d+)\.(commit|deltacommit|replacecommit)$", name)
        if m:
            completed[m.group(1)] = m.group(2)
            instant_uris.append(f)
            if m.group(2) == "replacecommit":
                replaces.append(f)
    return completed, replaces, instant_uris


def _committed_log_names(instant_uris: List[str], io_config) -> Optional[set]:
    """Log-file basenames referenced by completed commits'
    ``partitionToWriteStats`` — a log file not listed there belongs to an
    in-flight or crashed writer and must stay invisible (base files get
    the same treatment via their instant suffix). Returns None when no
    commit carries write stats (legacy metadata): caller accepts logs
    whose base instant is committed."""
    names: set = set()
    any_stats = False
    for uri in instant_uris:
        try:
            doc = json.loads(_get(uri, io_config))
        except ValueError:
            continue
        stats = doc.get("partitionToWriteStats")
        if not isinstance(stats, dict):
            continue
        for entries in stats.values():
            for e in entries or []:
                p = (e or {}).get("path")
                if p:
                    any_stats = True
                    names.add(str(p).replace("\\", "/").rsplit("/", 1)[-1])
    return names if any_stats else None


def snapshot_slices(table_uri: str,
                    io_config: Optional[IOConfig] = None
                    ) -> List[Dict[str, Any]]:
    """Latest file slice per live file group:
    [{base, logs, partition, file_id, instant}] — ``base`` may be None
    (log-only group on a MoR table), ``logs`` ordered by version."""
    all_files = _list_files(table_uri, io_config)
    completed, replace_uris, instant_uris = _timeline(all_files)
    committed_logs = _committed_log_names(instant_uris, io_config)
    replaced: set = set()
    for uri in replace_uris:
        try:
            doc = json.loads(_get(uri, io_config))
        except ValueError:
            continue
        for part, ids in (doc.get("partitionToReplaceFileIds") or {}).items():
            for fid in ids:
                replaced.add((part, fid))
    root = table_uri.rstrip("/")
    groups: Dict[Tuple[str, str], Dict[str, Any]] = {}
    logs: Dict[Tuple[str, str, str], List[Tuple[int, str]]] = {}
    root_local = _strip(root).replace("\\", "/")
    for f in all_files:
        norm = f.replace("\\", "/")
        rel = norm[len(root_local):].lstrip("/") if not _is_remote(root) \
            else norm.split(root.split("://", 1)[1], 1)[-1].lstrip("/")
        if rel.startswith(".hoodie"):
            continue
        parts = rel.rsplit("/", 1)
        partition = parts[0] if len(parts) == 2 else ""
        m = _BASE_FILE_RE.match(parts[-1])
        if m:
            if m.group("instant") not in completed \
                    or (partition, m.group("file_id")) in replaced:
                continue
            key = (partition, m.group("file_id"))
            cur = groups.get(key)
            if cur is None or m.group("instant") > cur["instant"]:
                groups[key] = {"base": f, "partition": partition,
                               "file_id": m.group("file_id"),
                               "instant": m.group("instant"), "logs": []}
            continue
        lm = _LOG_FILE_RE.match(parts[-1])
        if lm and (partition, lm.group("file_id")) not in replaced:
            if committed_logs is not None:
                if parts[-1] not in committed_logs:
                    continue  # in-flight / crashed writer: not committed
            else:
                # legacy metadata without write stats: a log can only be
                # live if its base instant is committed AND some later
                # deltacommit completed (coarser than per-file stats —
                # a writer crashing after an unrelated deltacommit is
                # indistinguishable here)
                base_i = lm.group("base_instant")
                if base_i not in completed or not any(
                        act == "deltacommit" and inst > base_i
                        for inst, act in completed.items()):
                    continue
            logs.setdefault(
                (partition, lm.group("file_id"), lm.group("base_instant")),
                []).append((int(lm.group("version")), f))
    # attach logs to their slice (same base instant); log-only groups
    # become base-less slices
    for (partition, fid, base_instant), entries in logs.items():
        key = (partition, fid)
        cur = groups.get(key)
        if cur is not None and cur["instant"] == base_instant:
            cur["logs"] = [p for _, p in sorted(entries)]
        elif cur is None:
            groups[key] = {"base": None, "partition": partition,
                           "file_id": fid, "instant": base_instant,
                           "logs": [p for _, p in sorted(entries)]}
    return sorted(groups.values(), key=lambda g: (g["partition"],
                                                  g["file_id"]))


def snapshot_files(table_uri: str,
                   io_config: Optional[IOConfig] = None
                   ) -> List[Dict[str, Any]]:
    """Live base files of the latest snapshot:
    [{path, partition, file_id, instant}] (read-optimized view)."""
    out = []
    for s in snapshot_slices(table_uri, io_config):
        if s["base"] is not None:
            out.append({"path": s["base"], "partition": s["partition"],
                        "file_id": s["file_id"], "instant": s["instant"]})
    return out


# ----------------------------------------------------------------- merge

_AVRO_MAGIC = b"Obj\x01"
_PARQUET_MAGIC = b"PAR1"
_DELETED_COL = "_hoodie_is_deleted"


def _load_log_table(uri: str, io_config):
    """One log file → arrow table of its records (Avro object-container or
    parquet payload, detected by magic)."""
    import io as io_

    import pyarrow as pa
    import pyarrow.parquet as pq
    raw = _get(uri, io_config)
    if raw[:4] == _PARQUET_MAGIC:
        return pq.read_table(io_.BytesIO(raw))
    if raw[:4] == _AVRO_MAGIC:
        from .avro import read_avro
        hdr, records = read_avro(raw)
        fields = hdr["schema"]["fields"]
        cols = {f["name"]: [r.get(f["name"]) for r in records]
                for f in fields}
        return pa.table(cols)
    raise NotImplementedError(
        f"hudi log file {uri!r}: binary HoodieLogFormat framing is not "
        "supported (payload must be an Avro object-container or parquet "
        "file)")


def _record_key_cols(props: Dict[str, str], schema_names) -> List[str]:
    keys = props.get("hoodie.table.recordkey.fields")
    if keys:
        return [k.strip() for k in keys.split(",") if k.strip()]
    if "_hoodie_record_key" in schema_names:
        return ["_hoodie_record_key"]
    raise ValueError(
        "hudi merge needs a record key: set "
        "hoodie.table.recordkey.fields or include _hoodie_record_key")


def _align_tables(tables, out_schema):
    """(aligned tables over out_schema with omitted columns null-filled,
    per-row tombstone bool array)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc
    aligned, dead = [], []
    for t in tables:
        cols = []
        for f in out_schema:
            if f.name in t.column_names:
                cols.append(t.column(f.name).cast(f.type))
            else:
                # partial-update log payloads may omit columns: null-fill
                cols.append(pa.chunked_array([pa.nulls(t.num_rows, f.type)]))
        aligned.append(pa.table(dict(zip(out_schema.names, cols)),
                                schema=out_schema))
        if _DELETED_COL in t.column_names:
            d = pc.fill_null(t.column(_DELETED_COL).cast(pa.bool_()), False)
            dead.append(d.to_numpy(zero_copy_only=False).astype(bool))
        else:
            dead.append(np.zeros(t.num_rows, dtype=bool))
    return aligned, np.concatenate(dead) if dead else np.zeros(0, bool)


def _merge_slice(base_t, log_tables, key_cols: List[str]):
    """Upsert log records over the base by key, honoring
    ``_hoodie_is_deleted`` tombstones; later tables win.

    Vectorized: dictionary-encode each key column to integer codes, group
    rows with one ``np.unique(axis=0)``, pick each group's LAST row
    (np.maximum.at) and emit winners in first-appearance order — one
    ``take`` instead of per-row Python dict churn. Key types that refuse
    dictionary encoding fall back to the interpreted merge."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc
    tables = ([base_t] if base_t is not None else []) + log_tables
    out_schema = pa.schema(
        [f for f in tables[0].schema if f.name != _DELETED_COL])
    aligned, dead = _align_tables(tables, out_schema)
    big = pa.concat_tables(aligned)
    n = big.num_rows
    if n == 0:
        return out_schema.empty_table()
    try:
        planes = []
        for k in key_cols:
            enc = pc.dictionary_encode(big.column(k).combine_chunks())
            codes = pc.fill_null(enc.indices.cast(pa.int64()), -1)
            planes.append(codes.to_numpy(zero_copy_only=False))
    except (pa.ArrowException, TypeError):
        return _merge_slice_rows(tables, out_schema, key_cols)
    _, inv = np.unique(np.stack(planes, axis=1), axis=0,
                       return_inverse=True)
    inv = inv.reshape(-1)
    ng = int(inv.max()) + 1
    rowidx = np.arange(n, dtype=np.int64)
    # rowidx is ascending, so plain fancy assignment computes per-group
    # max (last write wins) and, reversed, per-group min — no ufunc.at
    last = np.full(ng, -1, dtype=np.int64)
    last[inv] = rowidx
    first = np.full(ng, n, dtype=np.int64)
    first[inv[::-1]] = rowidx[::-1]
    winners = last[np.argsort(first, kind="stable")]
    winners = winners[~dead[winners]]
    return big.take(pa.array(winners))


def _merge_slice_rows(tables, out_schema, key_cols: List[str]):
    """Interpreted fallback for key types pyarrow can't dictionary-encode."""
    import pyarrow as pa
    rows: Dict[tuple, Optional[dict]] = {}
    order: List[tuple] = []
    for t in tables:
        d = t.to_pydict()
        n = t.num_rows
        deleted = d.get(_DELETED_COL, [False] * n)
        for i in range(n):
            key = tuple(tuple(v) if isinstance(v, list) else v
                        for v in (d[k][i] for k in key_cols))
            if key not in rows:
                order.append(key)
            rows[key] = None if deleted[i] else \
                {f.name: d[f.name][i] if f.name in d else None
                 for f in out_schema}
    live = [rows[k] for k in order if rows[k] is not None]
    if not live:
        return out_schema.empty_table()
    return pa.table({f.name: [r[f.name] for r in live]
                     for f in out_schema}, schema=out_schema)


def read_hudi(table_uri: str, io_config: Optional[IOConfig] = None,
              query_type: str = "snapshot"):
    """Hudi table → DataFrame of its latest snapshot.

    CoW: newest base file per file group. MoR ``snapshot``: log files
    merged over each base file by record key; ``read_optimized``: base
    files only."""
    import daft_tpu as dt
    if query_type not in ("snapshot", "read_optimized"):
        raise ValueError(f"read_hudi query_type {query_type!r}")
    props = _load_properties(table_uri, io_config)
    ttype = props.get("hoodie.table.type", "COPY_ON_WRITE").upper()
    slices = snapshot_slices(table_uri, io_config)
    if not slices:
        raise ValueError(f"hudi table {table_uri!r} has no committed "
                         f"base files")
    has_logs = any(s["logs"] for s in slices)
    if ttype == "COPY_ON_WRITE" or query_type == "read_optimized" \
            or not has_logs:
        paths = [s["base"] for s in slices if s["base"] is not None]
        if not paths:
            raise ValueError(f"hudi table {table_uri!r} has no base files "
                             "for the read-optimized view")
        return dt.read_parquet(paths, io_config=io_config)
    return _read_mor_snapshot(slices, props, io_config)


def _parquet_schema(uri: str, io_config):
    """Arrow schema from a parquet FOOTER only — the readers module's
    ranged-open reads just the tail over any object store."""
    import pyarrow.parquet as pq

    from .readers import _open_ranged
    if not _is_remote(uri):
        return pq.read_schema(_strip(uri))
    return pq.read_schema(_open_ranged(uri, io_config))


def _read_mor_snapshot(slices, props, io_config):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ..dataframe import DataFrame
    from ..logical.builder import LogicalPlanBuilder
    from ..recordbatch import RecordBatch
    from ..schema import Schema
    from .readers import _open_ranged
    from .scan import GeneratorScanOperator

    # schema from footers/headers only — no slice materializes at plan time
    s0 = slices[0]
    if s0["base"] is not None:
        arrow_schema = _parquet_schema(s0["base"], io_config)
    else:
        arrow_schema = _load_log_table(s0["logs"][0], io_config).schema
    key_cols = _record_key_cols(props, arrow_schema.names)
    arrow_schema = pa.schema(
        [f for f in arrow_schema if f.name != _DELETED_COL])
    schema = Schema.from_arrow(arrow_schema)

    def load_slice(s, columns):
        """Column pushdown: the base parquet reads only the requested
        columns + record keys + tombstone flag (ranged reads on remote
        stores); the merge runs over that slim set; the final select trims
        the merge-only helpers back out."""
        merge_cols = None if columns is None else list(
            dict.fromkeys(list(columns) + key_cols))
        base_t = None
        if s["base"] is not None:
            src = _strip(s["base"]) if not _is_remote(s["base"]) else \
                _open_ranged(s["base"], io_config)
            pf = pq.ParquetFile(src)
            rc = None if merge_cols is None else \
                [c for c in merge_cols + [_DELETED_COL]
                 if c in pf.schema_arrow.names]
            base_t = pf.read(columns=rc)
        log_ts = [_load_log_table(p, io_config) for p in s["logs"]]
        if merge_cols is not None:
            log_ts = [t.select([c for c in merge_cols + [_DELETED_COL]
                                if c in t.column_names]) for t in log_ts]
        if not log_ts:
            t = base_t
        else:
            t = _merge_slice(base_t, log_ts, key_cols)
        if columns is not None:
            t = t.select([c for c in columns if c in t.column_names])
        return t

    def make_loader(s):
        def load(pushdowns):
            cols = list(pushdowns.columns) \
                if pushdowns.columns is not None else None
            out_schema = schema.project(
                [c for c in cols if c in schema]) if cols is not None \
                else schema
            yield RecordBatch.from_arrow_table(
                load_slice(s, cols)).cast_to_schema(out_schema)
        paths = ([s["base"]] if s["base"] else []) + s["logs"]
        return paths, load

    entries = [make_loader(s) for s in slices]
    op = GeneratorScanOperator(
        schema, entries,
        f"HudiScanOperator(MoR snapshot, {len(slices)} slices)",
        io_config=io_config)
    return DataFrame(LogicalPlanBuilder.from_scan(op))
