"""Native Apache Iceberg table support: metadata/manifest reader + a
v1-format snapshot writer.

The reference reads Iceberg through pyiceberg scan tasks
(``/root/reference/daft/io/_iceberg.py``) and commits through pyiceberg
transactions (``daft/dataframe/dataframe.py`` write_iceberg). This module is
SDK-free: table metadata JSON, Avro manifest lists and manifests are parsed
directly (``avro.py``), and appends write spec-compliant v1 metadata —
so ``read_iceberg``/``write_iceberg`` work against a plain warehouse path
on any supported object store (local/S3/GCS/Azure).

Writes carry Iceberg spec field-ids in the Avro manifest schemas and commit
optimistically: the new ``v(N+1).metadata.json`` is create-exclusive (truly
atomic on local paths; check-then-put on object stores) and the commit is
retried against the refreshed table state on conflict, so concurrent
writers serialize instead of clobbering. Prior snapshots are retained in
the metadata snapshot log on overwrite (time travel). External-engine
interop (pyiceberg/Spark/Trino) is untested in this environment.

Unsupported (raises): v2 position/equality delete files, schema evolution
by field-id remapping, partitioned writes.
"""

from __future__ import annotations

import json
import os
import re
import time
import urllib.parse
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .avro import read_avro, write_avro
from .object_io import IOConfig, get_io_client


# ----------------------------------------------------------------- utils

def _is_remote(uri: str) -> bool:
    return "://" in uri and not uri.startswith("file://")


def _join(base: str, *parts: str) -> str:
    if _is_remote(base):
        return "/".join([base.rstrip("/")] + [p.strip("/") for p in parts])
    return os.path.join(base, *parts)


def _get(uri: str, io_config) -> bytes:
    if _is_remote(uri):
        return get_io_client(io_config).get(uri)
    with open(uri[7:] if uri.startswith("file://") else uri, "rb") as f:
        return f.read()


def _put(uri: str, data: bytes, io_config) -> None:
    if _is_remote(uri):
        get_io_client(io_config).put(uri, data)
        return
    p = uri[7:] if uri.startswith("file://") else uri
    os.makedirs(os.path.dirname(p), exist_ok=True)
    with open(p, "wb") as f:
        f.write(data)


def _exists(uri: str, io_config) -> bool:
    try:
        _get(uri, io_config)
        return True
    except Exception:
        return False


def _put_if_absent(uri: str, data: bytes, io_config) -> bool:
    """Create-exclusive write for the metadata-commit race. Local paths are
    truly atomic (O_CREAT|O_EXCL); object stores get check-then-put, which
    narrows but cannot eliminate the window without store preconditions."""
    if _is_remote(uri):
        client = get_io_client(io_config)
        try:
            client.source_for(uri).get_size(uri)
            return False  # object already exists (HEAD, not a full GET)
        except FileNotFoundError:
            pass  # transport errors propagate: clobbering a committed
            # metadata file is worse than failing the commit attempt
        client.put(uri, data)
        return True
    p = uri[7:] if uri.startswith("file://") else uri
    os.makedirs(os.path.dirname(p), exist_ok=True)
    try:
        fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    return True


# ------------------------------------------------------------- metadata

def _resolve_metadata_path(table_uri: str, io_config) -> str:
    """Table root → current metadata JSON (version-hint, else highest
    vN.metadata.json via glob)."""
    if table_uri.endswith(".metadata.json"):
        return table_uri
    pattern = _join(table_uri, "metadata", "*.metadata.json")
    if _is_remote(table_uri):
        hits = get_io_client(io_config).glob(pattern)
    else:
        import glob as _g
        hits = sorted(_g.glob(pattern))

    def version(p: str) -> Tuple[int, str]:
        m = re.search(r"v?(\d+)[^/]*\.metadata\.json$", p)
        return (int(m.group(1)) if m else -1, p)

    # the hint is a last-writer-wins pointer that a racing committer may
    # not have updated yet — take the max of hint and glob, never trust
    # the hint alone (a stale hint would wedge every later commit)
    best = max(hits, key=version) if hits else None
    hint = _join(table_uri, "metadata", "version-hint.text")
    try:
        v = _get(hint, io_config).decode().strip()
        cand = _join(table_uri, "metadata", f"v{v}.metadata.json")
        if (best is None or version(cand) > version(best)) \
                and _exists(cand, io_config):
            best = cand
    except Exception:
        pass
    if best is None:
        raise FileNotFoundError(
            f"no Iceberg metadata under {table_uri!r}")
    return best


def load_table_metadata(table_uri: str,
                        io_config: Optional[IOConfig] = None) -> dict:
    path = _resolve_metadata_path(table_uri, io_config)
    meta = json.loads(_get(path, io_config))
    meta["_metadata_path"] = path
    return meta


def _current_snapshot(meta: dict, snapshot_id: Optional[int]) -> Optional[dict]:
    snaps = meta.get("snapshots", [])
    if snapshot_id is not None:
        for s in snaps:
            if s["snapshot-id"] == snapshot_id:
                return s
        raise ValueError(f"snapshot {snapshot_id} not found")
    cur = meta.get("current-snapshot-id")
    if cur in (None, -1):
        return None
    for s in snaps:
        if s["snapshot-id"] == cur:
            return s
    return None


def _rewrite_location(uri: str, meta: dict, table_uri: str) -> str:
    """Manifest/data paths are absolute at write time; when a table moved
    (e.g. generated elsewhere, downloaded locally) remap through the
    metadata ``location``."""
    loc = meta.get("location", "")
    if loc and uri.startswith(loc):
        return _join(table_uri, uri[len(loc):].lstrip("/"))
    return uri


def scan_entries(table_uri: str, snapshot_id: Optional[int] = None,
                 io_config: Optional[IOConfig] = None
                 ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]],
                            List[Dict[str, Any]]]:
    """Snapshot scan plan: (data_files, positional_deletes,
    equality_deletes), each entry carrying its v2 sequence number (0 for
    v1) so delete applicability follows the spec (positional deletes apply
    to data sequence ≤ theirs; equality deletes to data sequence strictly
    less)."""
    meta = load_table_metadata(table_uri, io_config)
    snap = _current_snapshot(meta, snapshot_id)
    if snap is None:
        return [], [], []
    data: List[Dict[str, Any]] = []
    pos_dels: List[Dict[str, Any]] = []
    eq_dels: List[Dict[str, Any]] = []
    mlist_uri = _rewrite_location(snap["manifest-list"], meta, table_uri)
    _, manifests = read_avro(_get(mlist_uri, io_config))
    for m in manifests:
        m_uri = _rewrite_location(m["manifest_path"], meta, table_uri)
        m_seq = m.get("sequence_number") or 0
        _, entries = read_avro(_get(m_uri, io_config))
        for e in entries:
            if e.get("status") == 2:  # DELETED
                continue
            df = e["data_file"]
            seq = e.get("sequence_number")
            if seq is None:
                seq = m_seq  # v2 inheritance: null → manifest's sequence
            entry = {
                "path": _rewrite_location(df["file_path"], meta, table_uri),
                "raw_path": df["file_path"],  # delete files reference this
                "format": str(df.get("file_format", "PARQUET")).lower(),
                "records": df.get("record_count", 0),
                "sequence": seq,
            }
            content = df.get("content", 0)
            if content == 0:
                data.append(entry)
            elif content == 1:
                pos_dels.append(entry)
            elif content == 2:
                entry["equality_ids"] = list(df.get("equality_ids") or [])
                eq_dels.append(entry)
            else:
                raise NotImplementedError(
                    f"iceberg data_file content {content}")
    return data, pos_dels, eq_dels


def data_files(table_uri: str, snapshot_id: Optional[int] = None,
               io_config: Optional[IOConfig] = None) -> List[Dict[str, Any]]:
    """Live data-file entries for a snapshot: [{path, format, records}]."""
    data, pos_dels, eq_dels = scan_entries(table_uri, snapshot_id, io_config)
    if pos_dels or eq_dels:
        raise NotImplementedError(
            "snapshot has v2 delete files; use read_iceberg (it applies "
            "them at scan)")
    return data


def read_iceberg(table_uri: str, snapshot_id: Optional[int] = None,
                 io_config: Optional[IOConfig] = None):
    """Iceberg table (warehouse path or metadata JSON path) → DataFrame.

    v2 tables: positional and equality delete files are applied per data
    file at scan (the reference's delete-map,
    ``src/daft-local-execution/src/sources/scan_task.rs:95-147``), and
    columns resolve by FIELD ID against the current schema (renames and
    added columns from schema evolution read correctly; dropped columns
    disappear)."""
    import daft_tpu as dt
    meta = load_table_metadata(table_uri, io_config)
    data, pos_dels, eq_dels = scan_entries(table_uri, snapshot_id, io_config)
    if not data:
        schema = _schema_from_iceberg(meta)
        if schema is None:
            raise ValueError(f"iceberg table {table_uri!r} has no snapshot "
                             "and no schema")
        return _empty_df(schema)
    fmts = {f["format"] for f in data}
    if fmts - {"parquet"}:
        raise NotImplementedError(f"iceberg data file formats {fmts}")
    if not pos_dels and not eq_dels:
        return dt.read_parquet([f["path"] for f in data],
                               io_config=io_config)
    return _read_with_deletes(meta, data, pos_dels, eq_dels, io_config)


def _load_parquet_table(uri: str, io_config):
    import pyarrow.parquet as pq
    if _is_remote(uri):
        import io as io_
        return pq.read_table(io_.BytesIO(_get(uri, io_config)))
    return pq.read_table(uri[7:] if uri.startswith("file://") else uri)


def _field_id_map(meta: dict) -> Dict[int, str]:
    """current schema: field id → current column name."""
    schemas = meta.get("schemas") or ([meta["schema"]] if "schema" in meta
                                      else [])
    sid = meta.get("current-schema-id", 0)
    schema = next((s for s in schemas if s.get("schema-id", 0) == sid),
                  schemas[-1] if schemas else {"fields": []})
    return {f["id"]: f["name"] for f in schema.get("fields", [])}


def _remap_by_field_id(t, id_to_name: Dict[int, str]):
    """Rename a file's columns to the CURRENT schema via the
    ``PARQUET:field_id`` metadata parquet writers attach; files without
    ids keep name-based resolution (our own v1 writer's files)."""
    import pyarrow as pa
    names = []
    changed = False
    for f in t.schema:
        fid = None
        if f.metadata and b"PARQUET:field_id" in f.metadata:
            try:
                fid = int(f.metadata[b"PARQUET:field_id"])
            except ValueError:
                fid = None
        if fid is not None and fid in id_to_name \
                and id_to_name[fid] != f.name:
            names.append(id_to_name[fid])
            changed = True
        else:
            names.append(f.name)
    return t.rename_columns(names) if changed else t


def _read_with_deletes(meta, data, pos_dels, eq_dels, io_config):
    """Generator scan: per data file, drop positionally-deleted rows and
    anti-join equality deletes (sequence-number-aware)."""
    import numpy as np
    import pyarrow as pa

    from ..dataframe import DataFrame
    from ..logical.builder import LogicalPlanBuilder
    from ..recordbatch import RecordBatch
    from .scan import GeneratorScanOperator

    schema = _schema_from_iceberg(meta)
    id_to_name = _field_id_map(meta)

    # positional deletes: data-file path (as WRITTEN, pre-rewrite) → rows
    pos_map: Dict[str, list] = {}
    for d in pos_dels:
        t = _load_parquet_table(d["path"], io_config)
        for fp, pos in zip(t.column("file_path").to_pylist(),
                           t.column("pos").to_pylist()):
            pos_map.setdefault(fp, []).append((d["sequence"], pos))
    eq_tables = []
    for d in eq_dels:
        # delete files may predate schema renames: remap by field id like
        # data files, then resolve equality_ids against the CURRENT names
        t = _remap_by_field_id(_load_parquet_table(d["path"], io_config),
                               id_to_name)
        cols = [id_to_name[i] for i in d["equality_ids"]
                if i in id_to_name and id_to_name[i] in t.column_names]
        if not cols:
            raise NotImplementedError(
                f"iceberg equality delete {d['path']!r}: equality_ids "
                f"{d['equality_ids']} resolve to no current column — "
                "refusing to guess (a wrong guess would delete rows)")
        eq_tables.append((d["sequence"], cols, t.select(cols)))

    def load_entry(entry):
        t = _remap_by_field_id(
            _load_parquet_table(entry["path"], io_config), id_to_name)
        # current-schema projection: dropped columns vanish, added → null
        out_cols = {}
        for f in schema:
            if f.name in t.column_names:
                out_cols[f.name] = t.column(f.name)
            else:
                out_cols[f.name] = pa.nulls(t.num_rows,
                                            type=f.dtype.to_arrow())
        t = pa.table(out_cols)
        keep = np.ones(t.num_rows, dtype=bool)
        for raw_path in (entry.get("raw_path"), entry["path"]):
            for seq, pos in pos_map.get(raw_path, ()):
                if seq >= entry["sequence"] and 0 <= pos < len(keep):
                    keep[pos] = False
        for seq, cols, dt_ in eq_tables:
            if seq <= entry["sequence"] or not cols or not dt_.num_rows:
                continue
            import pyarrow.compute as pc
            keys_have_null = any(dt_.column(c).null_count > 0 for c in cols)
            if len(cols) == 1:
                hit = pc.is_in(t.column(cols[0]),
                               value_set=dt_.column(cols[0])
                               .combine_chunks())
                hit = np.asarray(hit.fill_null(False).combine_chunks())
                if keys_have_null:
                    # iceberg eq-deletes treat null as equal to null
                    hit |= np.asarray(
                        pc.is_null(t.column(cols[0])).combine_chunks())
                keep &= ~hit
            elif not keys_have_null:
                # multi-key: arrow semi join against the (deduped) delete
                # keys instead of a per-row Python probe
                probe = t.select(cols).append_column(
                    "__idx__", pa.array(np.arange(t.num_rows)))
                dedup = dt_.group_by(cols).aggregate([])
                hit = probe.join(dedup, keys=cols, join_type="left semi")
                keep[hit.column("__idx__").to_numpy()] = False
            else:
                # multi-key with NULLs: arrow joins never match nulls, but
                # the spec's null-equals-null semantics must — fall back
                # to the exact set probe for this (rare) delete file
                dead = set(zip(*[dt_.column(c).to_pylist() for c in cols]))
                vals = [t.column(c).to_pylist() for c in cols]
                for i in range(t.num_rows):
                    if tuple(v[i] for v in vals) in dead:
                        keep[i] = False
        if not keep.all():
            t = t.filter(pa.array(keep))
        return RecordBatch.from_arrow_table(t).cast_to_schema(schema)

    def make_loader(entry):
        def load(pushdowns):
            yield load_entry(entry)
        return [entry["path"]], load

    entries = [make_loader(e) for e in data]
    op = GeneratorScanOperator(
        schema, entries,
        f"IcebergScanOperator({len(data)} data files, "
        f"{len(pos_dels)}+{len(eq_dels)} delete files)",
        io_config=io_config)
    return DataFrame(LogicalPlanBuilder.from_scan(op))


def _empty_df(schema):
    import pyarrow as pa

    import daft_tpu as dt
    empty = pa.table({f.name: pa.array([], type=f.dtype.to_arrow())
                      for f in schema})
    return dt.from_arrow(empty)


# --------------------------------------------------------- schema bridge

_ICEBERG_PRIMITIVES = {
    "boolean": "bool", "int": "int32", "long": "int64", "float": "float32",
    "double": "float64", "date": "date", "string": "string",
    "binary": "binary", "timestamp": "timestamp", "timestamptz": "timestamp",
}


def _schema_from_iceberg(meta: dict):
    from ..datatype import DataType
    from ..schema import Field, Schema
    schemas = meta.get("schemas") or ([meta["schema"]] if "schema" in meta
                                      else [])
    if not schemas:
        return None
    sid = meta.get("current-schema-id", 0)
    schema = next((s for s in schemas if s.get("schema-id", 0) == sid),
                  schemas[-1])
    fields = []
    for f in schema.get("fields", []):
        t = f["type"]
        if isinstance(t, str):
            if t.startswith("decimal"):
                m = re.match(r"decimal\((\d+),\s*(\d+)\)", t)
                dt_ = DataType.decimal128(int(m.group(1)), int(m.group(2)))
            else:
                name = _ICEBERG_PRIMITIVES.get(t)
                if name is None:
                    raise NotImplementedError(f"iceberg type {t!r}")
                dt_ = getattr(DataType, name)()
        else:
            raise NotImplementedError(f"nested iceberg type {t!r}")
        fields.append(Field(f["name"], dt_))
    return Schema(fields)


def _iceberg_type(dtype) -> str:
    inv = {"bool": "boolean", "int8": "int", "int16": "int", "int32": "int",
           "int64": "long", "uint8": "int", "uint16": "int", "uint32": "long",
           "uint64": "long", "float32": "float", "float64": "double",
           "date": "date", "string": "string", "binary": "binary",
           "timestamp": "timestamp"}
    k = dtype.kind
    if k == "decimal128":
        return f"decimal({dtype.precision}, {dtype.scale})"
    if k not in inv:
        raise NotImplementedError(f"write_iceberg: dtype {dtype!r}")
    return inv[k]


# ----------------------------------------------------------------- write

# Field-ids per the Iceberg v1 spec's manifest / manifest-list tables
# (spec "Manifests" and "Manifest Lists" sections; the reference relies on
# pyiceberg carrying the same ids).
_MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int", "field-id": 0},
        {"name": "snapshot_id", "type": ["null", "long"], "field-id": 1},
        {"name": "data_file", "field-id": 2, "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "file_path", "type": "string", "field-id": 100},
                {"name": "file_format", "type": "string", "field-id": 101},
                {"name": "partition", "field-id": 102, "type": {
                    "type": "record", "name": "r102", "fields": []}},
                {"name": "record_count", "type": "long", "field-id": 103},
                {"name": "file_size_in_bytes", "type": "long",
                 "field-id": 104},
                {"name": "block_size_in_bytes", "type": "long",
                 "field-id": 105},
            ]}},
    ]}

_FIELD_SUMMARY_SCHEMA = {
    "type": "record", "name": "field_summary", "fields": [
        {"name": "contains_null", "type": "boolean", "field-id": 509},
        {"name": "lower_bound", "type": ["null", "bytes"], "field-id": 510},
        {"name": "upper_bound", "type": ["null", "bytes"], "field-id": 511},
    ]}

_MANIFEST_FILE_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "added_snapshot_id", "type": ["null", "long"],
         "field-id": 503},
        {"name": "added_data_files_count", "type": ["null", "int"],
         "field-id": 504},
        {"name": "existing_data_files_count", "type": ["null", "int"],
         "field-id": 505},
        {"name": "deleted_data_files_count", "type": ["null", "int"],
         "field-id": 506},
        {"name": "partitions", "field-id": 507, "type": [
            "null", {"type": "array", "items": _FIELD_SUMMARY_SCHEMA,
                     "element-id": 508}]},
    ]}


def write_iceberg(df, table_uri: str, mode: str = "append",
                  io_config: Optional[IOConfig] = None) -> None:
    """Append the DataFrame as a new Iceberg v1 snapshot (creating the
    table on first write). ``mode="overwrite"`` starts a snapshot whose
    manifest list drops all previous manifests."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    if mode not in ("append", "overwrite"):
        raise ValueError(f"write_iceberg mode {mode!r}")
    table = df.to_arrow()
    snapshot_id = int(uuid.uuid4().int % (1 << 62))

    # 1. data file + its manifest: immutable, content-addressed by uuid —
    # written once, reused across metadata-commit retries.
    import io as _io
    buf = _io.BytesIO()
    pq.write_table(table, buf)
    data_uri = _join(table_uri, f"data/{uuid.uuid4().hex}.parquet")
    _put(data_uri, buf.getvalue(), io_config)
    entry = {"status": 1, "snapshot_id": snapshot_id, "data_file": {
        "file_path": data_uri, "file_format": "PARQUET", "partition": {},
        "record_count": table.num_rows,
        "file_size_in_bytes": buf.getbuffer().nbytes,
        "block_size_in_bytes": 64 * 1024 * 1024}}
    manifest_blob = write_avro(
        _MANIFEST_ENTRY_SCHEMA, [entry],
        metadata={"format-version": "1", "content": "data",
                  "partition-spec-id": "0"})
    manifest_uri = _join(table_uri, f"metadata/{uuid.uuid4().hex}-m0.avro")
    _put(manifest_uri, manifest_blob, io_config)

    schema = df.schema()
    ice_schema = {"type": "struct", "schema-id": 0, "fields": [
        {"id": i + 1, "name": f.name, "required": False,
         "type": _iceberg_type(f.dtype)}
        for i, f in enumerate(schema)]}

    _MLIST_KEYS = ("manifest_path", "manifest_length", "partition_spec_id",
                   "added_snapshot_id", "added_data_files_count",
                   "existing_data_files_count", "deleted_data_files_count",
                   "partitions")

    # 2. optimistic metadata commit: v(N+1) is create-exclusive; on losing
    # the race, re-read the table state and rebuild the manifest list
    # against the new current snapshot.
    for _attempt in range(5):
        try:
            meta = load_table_metadata(table_uri, io_config)
            version = int(re.search(r"v?(\d+)[^/]*\.metadata\.json$",
                                    meta["_metadata_path"]).group(1))
        except FileNotFoundError:
            meta = None
            version = 0
        now_ms = int(time.time() * 1000)

        manifests = [{"manifest_path": manifest_uri,
                      "manifest_length": len(manifest_blob),
                      "partition_spec_id": 0,
                      "added_snapshot_id": snapshot_id,
                      "added_data_files_count": 1,
                      "existing_data_files_count": 0,
                      "deleted_data_files_count": 0,
                      "partitions": None}]
        if meta is not None and mode == "append":
            snap = _current_snapshot(meta, None)
            if snap is not None:
                mlist_uri0 = _rewrite_location(snap["manifest-list"], meta,
                                               table_uri)
                _, prior = read_avro(_get(mlist_uri0, io_config))
                for m in prior:
                    if m.get("content", 0) != 0:
                        raise NotImplementedError(
                            "append to a table with v2 delete manifests "
                            "would silently rewrite them as data manifests")
                carried = [{k: m.get(k) for k in _MLIST_KEYS}
                           for m in prior]
                manifests = carried + manifests
        mlist_blob = write_avro(
            _MANIFEST_FILE_SCHEMA, manifests,
            metadata={"format-version": "1"})
        mlist_uri = _join(
            table_uri,
            f"metadata/snap-{snapshot_id}-1-{uuid.uuid4().hex}.avro")
        _put(mlist_uri, mlist_blob, io_config)

        snapshot = {"snapshot-id": snapshot_id, "timestamp-ms": now_ms,
                    "manifest-list": mlist_uri,
                    "summary": {"operation": "append" if mode == "append"
                                else "overwrite"},
                    "schema-id": 0}
        if meta is None:
            new_meta = {
                "format-version": 1,
                "table-uuid": str(uuid.uuid4()),
                "location": table_uri,
                "last-updated-ms": now_ms,
                "last-column-id": len(schema.fields),
                "schema": ice_schema, "schemas": [ice_schema],
                "current-schema-id": 0,
                "partition-spec": [],
                "partition-specs": [{"spec-id": 0, "fields": []}],
                "default-spec-id": 0,
                "properties": {},
                "current-snapshot-id": snapshot_id,
                "snapshots": [snapshot],
            }
        else:
            new_meta = {k: v for k, v in meta.items()
                        if k != "_metadata_path"}
            # prior snapshots stay in the log either way (time travel);
            # overwrite only changes which manifests the NEW snapshot lists
            new_meta["snapshots"] = (new_meta.get("snapshots", [])
                                     + [snapshot])
            new_meta["current-snapshot-id"] = snapshot_id
            new_meta["last-updated-ms"] = now_ms
        new_version = version + 1
        meta_uri = _join(table_uri, "metadata",
                         f"v{new_version}.metadata.json")
        if _put_if_absent(meta_uri, json.dumps(new_meta, indent=2).encode(),
                          io_config):
            _put(_join(table_uri, "metadata", "version-hint.text"),
                 str(new_version).encode(), io_config)
            return
    raise RuntimeError(
        f"write_iceberg: lost the metadata commit race at {table_uri!r} "
        "5 times (concurrent writers)")
