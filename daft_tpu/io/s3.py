"""Native S3 object source: SigV4 signing, connection pooling, retries.

Capability mirror of the reference's native S3 client
(``src/daft-io/src/s3_like.rs``: connection pooling, credential handling,
retry policy ``src/daft-io/src/retry.rs``) built directly on the S3 REST
API with stdlib ``http.client``/``hmac`` — no SDK dependency, matching the
reference's no-SDK stance. Supports path-style addressing against custom
endpoints (MinIO / mock servers in tests) and virtual-hosted style against
AWS, ranged GETs, HEAD, PUT, and paginated ListObjectsV2 for glob/ls.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import io
import os
import re
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, Iterator, List, Optional, Tuple

from .object_io import (RETRYABLE_STATUS as _RETRYABLE_STATUS,
                        IOStatsContext, ObjectSource, S3Config,
                        parallel_get_ranges, retry_backoff_s)

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _parse_s3_url(path: str) -> Tuple[str, str]:
    u = urllib.parse.urlparse(path)
    if u.scheme not in ("s3", "s3a"):
        raise ValueError(f"not an s3 url: {path!r}")
    return u.netloc, u.path.lstrip("/")


class _ConnectionPool:
    """Reusable HTTP(S) connections per host (the reference pools via its
    hyper client; ``max_connections`` mirrors S3Config)."""

    def __init__(self, max_connections: int):
        self._lock = threading.Lock()
        self._idle: Dict[Tuple[str, int, bool], List[http.client.HTTPConnection]] = {}
        self.max_connections = max_connections

    def acquire(self, host: str, port: int, tls: bool):
        with self._lock:
            conns = self._idle.get((host, port, tls))
            if conns:
                return conns.pop()
        cls = http.client.HTTPSConnection if tls else http.client.HTTPConnection
        return cls(host, port, timeout=60)

    def release(self, host: str, port: int, tls: bool, conn) -> None:
        with self._lock:
            conns = self._idle.setdefault((host, port, tls), [])
            if len(conns) < self.max_connections:
                conns.append(conn)
                return
        conn.close()


class S3Source(ObjectSource):
    scheme = "s3"

    def __init__(self, config: S3Config = S3Config()):
        self.config = config
        self._pool = _ConnectionPool(config.max_connections)
        self._region = config.region_name \
            or os.environ.get("AWS_REGION") \
            or os.environ.get("AWS_DEFAULT_REGION") or "us-east-1"
        self._key_id = config.key_id or os.environ.get("AWS_ACCESS_KEY_ID")
        self._secret = config.access_key \
            or os.environ.get("AWS_SECRET_ACCESS_KEY")
        self._token = config.session_token \
            or os.environ.get("AWS_SESSION_TOKEN")
        self._endpoint = config.endpoint_url \
            or os.environ.get("AWS_ENDPOINT_URL")

    # ------------------------------------------------------------- signing
    def _sign(self, method: str, host: str, canonical_uri: str,
              query: str, headers: Dict[str, str], payload_hash: str) -> None:
        """AWS Signature Version 4 (header-based)."""
        if self.config.anonymous or not (self._key_id and self._secret):
            return
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash
        if self._token:
            headers["x-amz-security-token"] = self._token
        signed = sorted(k.lower() for k in headers if k.lower() == "host"
                        or k.lower().startswith("x-amz-")
                        or k.lower() == "range")
        canonical_headers = "".join(
            f"{k}:{_header_val(headers, k)}\n" for k in signed)
        signed_headers = ";".join(signed)
        canonical_request = "\n".join([
            method, canonical_uri, query, canonical_headers, signed_headers,
            payload_hash])
        scope = f"{datestamp}/{self._region}/s3/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical_request.encode()).hexdigest()])

        def _hmac(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = _hmac(("AWS4" + self._secret).encode(), datestamp)
        k = _hmac(k, self._region)
        k = _hmac(k, "s3")
        k = _hmac(k, "aws4_request")
        sig = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self._key_id}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={sig}")

    # ------------------------------------------------------------ transport
    def _locate(self, bucket: str) -> Tuple[str, int, bool, str]:
        """(host, port, tls, uri_prefix) — path-style for custom endpoints,
        virtual-hosted for AWS."""
        if self._endpoint:
            u = urllib.parse.urlparse(self._endpoint)
            tls = u.scheme == "https"
            return (u.hostname, u.port or (443 if tls else 80), tls,
                    f"/{bucket}")
        return (f"{bucket}.s3.{self._region}.amazonaws.com", 443, True, "")

    def _request(self, method: str, bucket: str, key: str,
                 query: Dict[str, str] = None, headers: Dict[str, str] = None,
                 body: bytes = b"") -> Tuple[int, Dict[str, str], bytes]:
        host, port, tls, prefix = self._locate(bucket)
        canonical_uri = prefix + "/" + urllib.parse.quote(key, safe="/~._-")
        qitems = sorted((query or {}).items())
        qs = "&".join(f"{urllib.parse.quote(k, safe='~._-')}="
                      f"{urllib.parse.quote(str(v), safe='~._-')}"
                      for k, v in qitems)
        hdrs = dict(headers or {})
        hdrs["host"] = host if port in (80, 443) else f"{host}:{port}"
        payload_hash = hashlib.sha256(body).hexdigest() if body \
            else _EMPTY_SHA256
        self._sign(method, host, canonical_uri, qs, hdrs, payload_hash)
        path = canonical_uri + (f"?{qs}" if qs else "")

        last_exc: Optional[Exception] = None
        for attempt in range(max(1, self.config.num_tries)):
            conn = self._pool.acquire(host, port, tls)
            try:
                conn.request(method, path, body=body or None, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                status = resp.status
                rheaders = dict(resp.getheaders())
                self._pool.release(host, port, tls, conn)
            except (OSError, http.client.HTTPException) as exc:
                conn.close()
                last_exc = exc
                time.sleep(retry_backoff_s(path, attempt))
                continue
            if status in _RETRYABLE_STATUS:
                last_exc = RuntimeError(
                    f"s3 {method} {path}: HTTP {status}: {data[:200]!r}")
                time.sleep(retry_backoff_s(path, attempt))
                continue
            return status, rheaders, data
        raise last_exc

    # ------------------------------------------------------------- ObjectSource
    def get(self, path, byte_range=None, stats=None) -> bytes:
        bucket, key = _parse_s3_url(path)
        headers = {}
        if byte_range is not None:
            headers["range"] = f"bytes={byte_range[0]}-{byte_range[1] - 1}"
        status, _, data = self._request("GET", bucket, key, headers=headers)
        if status not in (200, 206):
            raise FileNotFoundError(f"s3 GET {path}: HTTP {status}")
        if stats:
            stats.record_get(len(data))
        return data

    def get_ranges(self, path, ranges, stats=None, parallelism=None):
        return parallel_get_ranges(
            self, path, ranges, stats,
            min(parallelism or 8, self.config.max_connections))

    def put(self, path, data, stats=None) -> None:
        bucket, key = _parse_s3_url(path)
        status, _, body = self._request("PUT", bucket, key, body=data)
        if status not in (200, 201):
            raise IOError(f"s3 PUT {path}: HTTP {status}: {body[:200]!r}")
        if stats:
            stats.record_put(len(data))

    def get_size(self, path) -> int:
        bucket, key = _parse_s3_url(path)
        status, headers, _ = self._request("HEAD", bucket, key)
        if status != 200:
            raise FileNotFoundError(f"s3 HEAD {path}: HTTP {status}")
        lower = {k.lower(): v for k, v in headers.items()}
        return int(lower.get("content-length", 0))

    def version(self, path):
        # S3 always returns an ETag on HEAD; it is the object's change
        # signal (content hash for simple puts, opaque for multipart)
        try:
            bucket, key = _parse_s3_url(path)
            status, headers, _ = self._request("HEAD", bucket, key)
            if status != 200:
                return None
            lower = {k.lower(): v for k, v in headers.items()}
            tag = lower.get("etag") or lower.get("last-modified")
            if not tag:
                return None
            return ("s3", int(lower.get("content-length", 0) or 0), tag)
        except Exception:
            return None

    def _list(self, bucket: str, prefix: str,
              delimiter: Optional[str] = None,
              stats: Optional[IOStatsContext] = None
              ) -> Iterator[Tuple[str, int]]:
        token = None
        while True:
            q = {"list-type": "2", "prefix": prefix}
            if delimiter:
                q["delimiter"] = delimiter
            if token:
                q["continuation-token"] = token
            status, _, data = self._request("GET", bucket, "", query=q)
            if status != 200:
                raise IOError(f"s3 LIST {bucket}/{prefix}: HTTP {status}")
            if stats:
                stats.record_list()
            root = ET.fromstring(data)
            ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") \
                else ""
            for c in root.findall(f"{ns}Contents"):
                key = c.find(f"{ns}Key").text
                size = int(c.find(f"{ns}Size").text or 0)
                yield key, size
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is None or trunc.text != "true":
                return
            nxt = root.find(f"{ns}NextContinuationToken")
            token = nxt.text if nxt is not None else None
            if not token:
                return

    def glob(self, pattern, stats=None) -> List[str]:
        bucket, keypat = _parse_s3_url(pattern)
        wild = min((keypat.index(ch) for ch in "*?[" if ch in keypat),
                   default=None)
        if wild is None:
            return [pattern]
        prefix = keypat[:wild]
        pat = re.compile(_glob_regex(keypat))
        out = []
        for key, _size in self._list(bucket, prefix, stats=stats):
            if pat.match(key):
                out.append(f"s3://{bucket}/{key}")
        return sorted(out)

    def ls(self, path) -> Iterator[Tuple[str, int]]:
        bucket, prefix = _parse_s3_url(path)
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        for key, size in self._list(bucket, prefix, delimiter="/"):
            yield f"s3://{bucket}/{key}", size


def _glob_regex(pat: str) -> str:
    """Glob → regex where ``**`` crosses '/' and ``*``/``?`` do not."""
    out = []
    i = 0
    while i < len(pat):
        ch = pat[i]
        if ch == "*":
            if pat[i:i + 2] == "**":
                out.append(".*")
                i += 2
                if i < len(pat) and pat[i] == "/":
                    i += 1
                continue
            out.append("[^/]*")
        elif ch == "?":
            out.append("[^/]")
        elif ch == "[":
            j = pat.find("]", i)
            if j == -1:
                out.append("\\[")
            else:
                out.append(pat[i:j + 1])
                i = j
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out) + "$"


def _header_val(headers: Dict[str, str], lower_key: str) -> str:
    for k, v in headers.items():
        if k.lower() == lower_key:
            return str(v).strip()
    return ""


class S3ReadableFile(io.RawIOBase):
    """Seekable file-like over ranged S3 GETs — feeds pyarrow readers so
    parquet footer/row-group reads become true range requests (the
    reference's read_planner byte-range model, ``daft-parquet/read_planner``)."""

    def __init__(self, source: S3Source, path: str,
                 stats: Optional[IOStatsContext] = None,
                 size: Optional[int] = None):
        self._src = source
        self._path = path
        self._stats = stats
        self._lazy_size = size  # HEAD deferred until a read/seek needs it
        self._pos = 0

    @property
    def _size(self) -> int:
        if self._lazy_size is None:
            self._lazy_size = self._src.get_size(self._path)
        return self._lazy_size

    def readable(self):
        return True

    def seekable(self):
        return True

    def seek(self, offset, whence=io.SEEK_SET):
        if whence == io.SEEK_SET:
            self._pos = offset
        elif whence == io.SEEK_CUR:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def tell(self):
        return self._pos

    def read(self, n=-1):
        if n is None or n < 0:
            n = self._size - self._pos
        if n <= 0 or self._pos >= self._size:
            return b""
        end = min(self._pos + n, self._size)
        data = self._src.get(self._path, (self._pos, end), self._stats)
        self._pos += len(data)
        return data

    def size(self):
        return self._size
