"""Scan planning: Pushdowns, ScanTask, ScanOperator, glob scans.

Reference: ``src/common/scan-info/src/scan_operator.rs:12-37`` (ScanOperator
trait + Pushdowns), ``src/daft-scan/src/lib.rs:417-436`` (ScanTask fields),
``src/daft-scan/src/glob.rs:28`` (GlobScanOperator with schema inference from
the first file), ``src/daft-scan/src/scan_task_iters/`` (merge-by-size 96–384MB
and split-by-rowgroup).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from ..datatype import DataType
from ..expressions import Expression, col
from ..recordbatch import RecordBatch
from ..schema import Field, Schema


@dataclasses.dataclass(frozen=True)
class Pushdowns:
    """Pushed-down scan constraints (reference: ``pushdowns.rs``)."""

    filters: Optional[Expression] = None
    partition_filters: Optional[Expression] = None
    columns: Optional[Tuple[str, ...]] = None
    limit: Optional[int] = None

    def with_columns(self, columns: Optional[Sequence[str]]) -> "Pushdowns":
        return dataclasses.replace(
            self, columns=tuple(columns) if columns is not None else None)

    def with_limit(self, limit: Optional[int]) -> "Pushdowns":
        return dataclasses.replace(self, limit=limit)

    def with_filters(self, filters: Optional[Expression]) -> "Pushdowns":
        return dataclasses.replace(self, filters=filters)


class ScanTask:
    """One unit of scan work: file(s) + format + pushdowns.

    ``execute()`` → list[RecordBatch]; runs on the executor's IO pool.
    """

    def __init__(self, paths: List[str], file_format: str, schema: Schema,
                 pushdowns: Pushdowns = Pushdowns(),
                 num_rows_hint: Optional[int] = None,
                 size_bytes_hint: Optional[int] = None,
                 row_groups: Optional[List[Optional[List[int]]]] = None,
                 format_options: Optional[Dict[str, Any]] = None,
                 partition_values: Optional[Dict[str, Any]] = None,
                 generator: Optional[Callable[[], Iterator[RecordBatch]]] = None,
                 io_config: Any = None):
        self.paths = paths
        self.io_config = io_config
        self.file_format = file_format
        self.schema = schema
        self.pushdowns = pushdowns
        self._num_rows = num_rows_hint
        self._size_bytes = size_bytes_hint
        self.row_groups = row_groups
        self.format_options = format_options or {}
        self.partition_values = partition_values or {}
        self.generator = generator

    def materialized_schema(self) -> Schema:
        if self.pushdowns.columns is not None:
            keep = [n for n in self.pushdowns.columns if n in self.schema]
            return self.schema.project(keep)
        return self.schema

    def num_rows(self) -> Optional[int]:
        if self.pushdowns.filters is not None:
            return None
        if self._num_rows is not None and self.pushdowns.limit is not None:
            return min(self._num_rows, self.pushdowns.limit)
        return self._num_rows

    def size_bytes(self) -> Optional[int]:
        return self._size_bytes

    def stream_batches(self) -> Iterator[RecordBatch]:
        """Stream result batches (one per source file) with residual
        pushdowns applied incrementally — the prefetch-pipelined scan
        yields morsels off this as each file decodes, and a satisfied
        limit stops reading the remaining files. May yield nothing for an
        all-filtered task (``execute`` adds the empty-batch fallback)."""
        from . import readers
        src = self.generator() if self.generator is not None \
            else readers.iter_scan_task_batches(self)
        remaining = self.pushdowns.limit
        for b in src:
            if self.pushdowns.filters is not None:
                b = b.filter(self.pushdowns.filters)
            if remaining is not None:
                if remaining <= 0:
                    return
                b = b.head(remaining)
                remaining -= len(b)
            if len(b):
                yield b

    def execute(self) -> List[RecordBatch]:
        out = list(self.stream_batches())
        if not out:
            return [RecordBatch.empty(self.materialized_schema())]
        return out

    def __repr__(self):
        return (f"ScanTask({self.file_format}, {len(self.paths)} files, "
                f"rows={self._num_rows}, pushdowns={self.pushdowns})")


class ScanOperator:
    """Produces ScanTasks for a source (reference trait: scan_operator.rs:12-37)."""

    def schema(self) -> Schema:
        raise NotImplementedError

    def partitioning_keys(self) -> List[str]:
        return []

    def can_absorb_filter(self) -> bool:
        return False

    def can_absorb_limit(self) -> bool:
        return True

    def can_absorb_select(self) -> bool:
        return True

    def multiline_display(self) -> List[str]:
        return [type(self).__name__]

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        raise NotImplementedError


class GeneratorScanOperator(ScanOperator):
    """Scan over pre-resolved entries, each loaded by a callback — the
    shared shape of the lake-format readers (Iceberg-with-deletes, Hudi
    MoR slices, Lance fragments), which resolve their file lists at plan
    time and materialize per entry at execution.

    ``entries``: list of (paths, load_fn) where ``load_fn(pushdowns)``
    yields RecordBatches. ``prune_fn(entry_index, pushdowns)`` → False
    drops an entry at planning (stats pruning)."""

    def __init__(self, schema: Schema, entries, label: str,
                 io_config=None, prune_fn=None,
                 entry_hints=None):
        self._schema = schema
        self._entries = entries
        self._label = label
        self._io_config = io_config
        self._prune_fn = prune_fn
        self._hints = entry_hints or [{} for _ in entries]

    def display(self) -> List[str]:
        return [self._label]

    def multiline_display(self) -> List[str]:
        return [self._label]

    def schema(self) -> Schema:
        return self._schema

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        tasks = []
        for i, (paths, load_fn) in enumerate(self._entries):
            if self._prune_fn is not None \
                    and not self._prune_fn(i, pushdowns):
                continue
            def gen(load_fn=load_fn):
                yield from load_fn(pushdowns)
            hint = self._hints[i]
            tasks.append(ScanTask(
                list(paths), hint.get("format", "parquet"), self._schema,
                pushdowns, num_rows_hint=hint.get("rows"),
                size_bytes_hint=hint.get("size"), generator=gen,
                io_config=self._io_config))
        if not tasks:
            schema = self._schema
            tasks.append(ScanTask(
                [], "parquet", schema, pushdowns, num_rows_hint=0,
                generator=lambda: iter([_empty_batch(schema, pushdowns)])))
        return tasks


def _empty_batch(schema: Schema, pushdowns: Pushdowns):
    from ..recordbatch import RecordBatch
    if pushdowns.columns is not None:
        keep = [n for n in pushdowns.columns if n in schema]
        return RecordBatch.empty(schema.project(keep))
    return RecordBatch.empty(schema)


def glob_paths(path_or_paths, io_config=None) -> List[str]:
    """Local / file:// / remote (s3://) glob expansion (fanout-style,
    reference ``object_store_glob.rs``). Directories expand to their
    files."""
    paths = [path_or_paths] if isinstance(path_or_paths, str) else list(path_or_paths)
    out: List[str] = []
    for p in paths:
        if p.startswith("file://"):
            p = p[7:]
        if "://" in p and not p.startswith("file://"):
            from .object_io import get_io_client
            client = get_io_client(io_config)
            if any(ch in p for ch in "*?[]"):
                out.extend(client.glob(p))
            else:
                out.append(p)
            continue
        if any(ch in p for ch in "*?[]"):
            matches = sorted(_glob.glob(p, recursive=True))
            out.extend(m for m in matches if os.path.isfile(m))
        elif os.path.isdir(p):
            for root, _, files in sorted(os.walk(p)):
                for f in sorted(files):
                    if not f.startswith((".", "_")):
                        out.append(os.path.join(root, f))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files found for {path_or_paths!r}")
    return out


class GlobScanOperator(ScanOperator):
    """Scan over globbed files with schema inference from the first file
    (reference: ``glob.rs:28``) plus hive partition-value inference
    (``hive.rs``)."""

    def __init__(self, paths, file_format: str,
                 schema: Optional[Schema] = None,
                 format_options: Optional[Dict[str, Any]] = None,
                 hive_partitioning: bool = False,
                 io_config: Any = None):
        from . import readers
        self._io_config = io_config
        self._paths = glob_paths(paths, io_config)
        self._format = file_format
        self._options = format_options or {}
        self._hive = hive_partitioning
        self._hive_fields: Dict[str, DataType] = {}
        if schema is None:
            schema = readers.infer_schema(self._paths[0], file_format,
                                          self._options, io_config)
        if hive_partitioning:
            # union keys/types across ALL globbed paths — inferring from
            # the first path alone silently drops the partition columns of
            # mixed-key layouts (and types from a single value misjudge
            # e.g. a first partition that happens to look numeric)
            values: Dict[str, List[Any]] = {}
            for p in self._paths:
                for k, v in _hive_values(p).items():
                    values.setdefault(k, []).append(v)
            for k, vs in values.items():
                self._hive_fields[k] = DataType.infer_from_pylist(vs)
            schema = schema.non_distinct_union(
                Schema([Field(k, t) for k, t in self._hive_fields.items()]))
        self._schema = schema

    def schema(self) -> Schema:
        return self._schema

    def partitioning_keys(self) -> List[str]:
        return list(self._hive_fields)

    def multiline_display(self) -> List[str]:
        return [f"GlobScanOperator({self._format})",
                f"paths = {self._paths[:3]}{'…' if len(self._paths) > 3 else ''}"]

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        from . import read_planner as rp, readers
        from ..context import get_context
        cfg = get_context().execution_config

        def plan_one(p: str) -> List[ScanTask]:
            pv = {}
            if self._hive:
                # missing-key → null fill: every task carries the UNION's
                # keys so a path lacking one still materializes the column
                vals = _hive_values(p)
                pv = {k: vals.get(k) for k in self._hive_fields}
            return readers.make_scan_tasks(
                p, self._format, self._schema, pushdowns, self._options, pv,
                self._io_config)

        remote = [p for p in self._paths if "://" in p
                  and not p.startswith("file://")]
        if len(remote) > 1 and not rp.scan_sequential_fallback():
            # footer fetches dominate multi-file remote planning (one RTT
            # chain per file) — fan them over the IO pool, order preserved
            from .object_io import io_pool
            futs = [io_pool().submit(plan_one, p) for p in self._paths]
            groups = [f.result() for f in futs]
        else:
            groups = [plan_one(p) for p in self._paths]
        tasks: List[ScanTask] = [t for g in groups for t in g]
        tasks = split_scan_tasks(tasks, cfg.scan_tasks_max_size_bytes,
                                 cfg.parquet_split_row_groups_max_files)
        return merge_scan_tasks(tasks, cfg.scan_tasks_min_size_bytes,
                                cfg.scan_tasks_max_size_bytes,
                                cfg.max_sources_per_scan_task)


def _hive_values(path: str) -> Dict[str, Any]:
    out = {}
    for part in path.split(os.sep):
        if "=" in part and not part.startswith("."):
            k, _, v = part.partition("=")
            if k and v and "." not in v:
                out[k] = v
    return out


def split_scan_tasks(tasks: List[ScanTask], max_size: int,
                     max_files: int) -> List[ScanTask]:
    """Split oversized single-file parquet tasks into per-row-group-range
    tasks (reference: ``scan_task_iters/split_parquet``). Only the first
    ``max_files`` oversized files pay the metadata fetch; a limit pushdown
    disables splitting (the limit is served from the file head)."""
    out: List[ScanTask] = []
    split_budget = max_files
    for t in tasks:
        sz = t.size_bytes()
        if (t.file_format != "parquet" or len(t.paths) != 1
                or t.pushdowns.limit is not None or t.row_groups is not None
                or sz is None or sz <= max_size or split_budget <= 0):
            out.append(t)
            continue
        split_budget -= 1
        md = getattr(t, "pq_metadata", None)
        if md is None:
            try:
                md = pq.ParquetFile(t.paths[0]).metadata
            except Exception:
                out.append(t)
                continue
        if md.num_row_groups <= 1:
            out.append(t)
            continue
        group: List[int] = []
        gsize = grows = 0
        for g in range(md.num_row_groups):
            rg = md.row_group(g)
            if group and gsize + rg.total_byte_size > max_size:
                out.append(ScanTask(t.paths, "parquet", t.schema, t.pushdowns,
                                    grows, gsize, [group], t.format_options,
                                    t.partition_values))
                group, gsize, grows = [], 0, 0
            group.append(g)
            gsize += rg.total_byte_size
            grows += rg.num_rows
        if group:
            out.append(ScanTask(t.paths, "parquet", t.schema, t.pushdowns,
                                grows, gsize, [group], t.format_options,
                                t.partition_values))
    return out


def merge_scan_tasks(tasks: List[ScanTask], min_size: int, max_size: int,
                     max_sources: int) -> List[ScanTask]:
    """Merge small adjacent tasks into 96–384MB targets
    (reference: ``scan_task_iters``' merge-by-size)."""
    out: List[ScanTask] = []
    acc: Optional[ScanTask] = None
    acc_size = 0
    for t in tasks:
        sz = t.size_bytes() or max_size  # unknown size → don't merge
        limited = t.pushdowns.limit is not None
        if (acc is not None and not limited
                and acc_size + sz <= max_size
                and len(acc.paths) + len(t.paths) <= max_sources
                and acc.file_format == t.file_format
                and acc.row_groups is None and t.row_groups is None
                and acc.partition_values == t.partition_values):
            acc = ScanTask(acc.paths + t.paths, acc.file_format, acc.schema,
                           acc.pushdowns,
                           None if (acc._num_rows is None or t._num_rows is None)
                           else acc._num_rows + t._num_rows,
                           acc_size + sz, None, acc.format_options,
                           acc.partition_values)
            acc_size += sz
            if acc_size >= min_size:
                out.append(acc)
                acc, acc_size = None, 0
            continue
        if acc is not None:
            out.append(acc)
            acc, acc_size = None, 0
        if sz >= min_size or limited:
            out.append(t)
        else:
            acc, acc_size = t, sz
    if acc is not None:
        out.append(acc)
    return out


class InMemoryScanOperator(ScanOperator):
    """Scan over already-materialized partitions (cache entries)."""

    def __init__(self, schema: Schema, partitions):
        self._schema = schema
        self._parts = partitions

    def schema(self) -> Schema:
        return self._schema

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        tasks = []
        for p in self._parts:
            def gen(p=p):
                return iter(p.batches())
            tasks.append(ScanTask([], "memory", self._schema, pushdowns,
                                  p.metadata_num_rows(), None, generator=gen))
        return tasks
