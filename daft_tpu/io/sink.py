"""User-defined data sinks (reference: ``daft/io/sink.py:31`` DataSink ABC)."""

from __future__ import annotations

import dataclasses
from typing import Any, Generic, Iterator, List, TypeVar

from ..micropartition import MicroPartition
from ..schema import Schema

T = TypeVar("T")


@dataclasses.dataclass
class WriteResult(Generic[T]):
    result: T
    bytes_written: int = 0
    rows_written: int = 0


class DataSink(Generic[T]):
    """Custom write destination; drive with ``DataFrame.write_sink``."""

    def name(self) -> str:
        return type(self).__name__

    def schema(self) -> Schema:
        from ..datatype import DataType
        from ..schema import Field
        return Schema([Field("write_results", DataType.python())])

    def start(self) -> None:
        pass

    def write(self, micropartitions: Iterator[MicroPartition]) -> Iterator[WriteResult[T]]:
        raise NotImplementedError

    def finalize(self, write_results: List[WriteResult[T]]) -> MicroPartition:
        from ..series import Series
        from ..recordbatch import RecordBatch
        s = Series.from_pyobjects([w.result for w in write_results],
                                  "write_results")
        return MicroPartition.from_recordbatch(RecordBatch.from_series([s]))
