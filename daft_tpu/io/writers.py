"""File writers: parquet / csv / json, plain + hive-partitioned.

Reference: ``src/daft-writers`` (AsyncFileWriter trait ``lib.rs:57-72``,
target-size batching ``batch.rs``, partitioned writes ``partition.rs``).
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq

from ..expressions import Expression
from ..micropartition import MicroPartition
from ..recordbatch import RecordBatch
from ..schema import Schema
from ..series import Series


def _new_filename(fmt: str, idx: int = 0) -> str:
    ext = {"parquet": "parquet", "csv": "csv", "json": "json"}[fmt]
    return f"{uuid.uuid4().hex}-{idx}.{ext}"


def _target_row_chunks(rb: RecordBatch, fmt: str) -> List[RecordBatch]:
    """Split a batch so each output file lands near the configured target
    size (reference: ``src/daft-writers/src/batch.rs`` TargetBatchWriter —
    estimated via in-memory bytes over the format's inflation factor)."""
    from ..context import get_context
    cfg = get_context().execution_config
    # inflation factor = in-memory bytes / on-disk bytes for the format, so
    # the in-memory chunk that lands near the file target is target × factor
    if fmt == "parquet":
        target = cfg.parquet_target_filesize * cfg.parquet_inflation_factor
    elif fmt == "csv":
        target = cfg.csv_target_filesize * cfg.csv_inflation_factor
    else:
        target = cfg.parquet_target_filesize
    nbytes = rb.size_bytes() or 0
    n = len(rb)
    if n == 0 or nbytes <= target:
        return [rb]
    rows_per_file = max(int(n * target / nbytes), 1)
    return [rb.slice(i, min(i + rows_per_file, n))
            for i in range(0, n, rows_per_file)]


def _write_table(t: pa.Table, fmt: str, path: str,
                 options: Optional[Dict[str, Any]] = None) -> int:
    if fmt == "parquet":
        pq.write_table(t, path, compression=(options or {}).get(
            "compression", "snappy"))
    elif fmt == "csv":
        pacsv.write_csv(t, path)
    elif fmt == "json":
        import json
        rows = t.to_pylist()
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r, default=str) + "\n")
    return os.path.getsize(path)


def write_micropartition(mp: MicroPartition, fmt: str, root_dir: str,
                         partition_cols: Optional[List[Expression]] = None,
                         options: Optional[Dict[str, Any]] = None
                         ) -> RecordBatch:
    """Write one partition; returns a RecordBatch of written file paths
    (the reference's write ops also stream back path manifests)."""
    os.makedirs(root_dir, exist_ok=True)
    rb = mp.combined()
    paths: List[str] = []
    part_values_rows: List[Dict[str, Any]] = []
    if partition_cols:
        parts, pvalues = rb.partition_by_value(partition_cols)
        names = pvalues.column_names()
        for i, part in enumerate(parts):
            if len(part) == 0:
                continue
            vals = {n: pvalues.get_column(n).to_pylist()[i] for n in names}
            subdir = os.path.join(
                root_dir, *[f"{k}={_hive_str(v)}" for k, v in vals.items()])
            os.makedirs(subdir, exist_ok=True)
            drop = [c for c in part.column_names() if c in vals]
            for j, chunk in enumerate(_target_row_chunks(part, fmt)):
                p = os.path.join(subdir, _new_filename(fmt, j))
                _write_table(chunk.to_arrow_table().drop_columns(drop),
                             fmt, p, options)
                paths.append(p)
                part_values_rows.append(vals)
    else:
        if len(rb):
            for j, chunk in enumerate(_target_row_chunks(rb, fmt)):
                p = os.path.join(root_dir, _new_filename(fmt, j))
                _write_table(chunk.to_arrow_table(), fmt, p, options)
                paths.append(p)
    cols = [Series.from_pylist(paths, "path")]
    if partition_cols and part_values_rows:
        for n in part_values_rows[0]:
            cols.append(Series.from_pylist(
                [r[n] for r in part_values_rows], n))
    return RecordBatch.from_series(cols)


def _hive_str(v) -> str:
    return "__HIVE_DEFAULT_PARTITION__" if v is None else str(v)


def overwrite_dir(root_dir: str):
    """WriteMode=overwrite: clear prior files (reference: write modes,
    ``tests/io/test_write_modes.py`` behavior)."""
    if os.path.isdir(root_dir):
        for root, dirs, files in os.walk(root_dir):
            for f in files:
                os.unlink(os.path.join(root, f))
