"""Native Azure Blob object source over the Blob REST API.

Capability mirror of the reference's Azure client (``src/daft-io/src/
azure_blob.rs``: SharedKey / SAS / anonymous auth, ranged reads, paged
listing) built on the Blob service REST API with stdlib ``http.client`` +
``hmac`` — no SDK, same stance as the S3 source. URL forms supported:
``az://container/key`` (account from config/env) and
``abfss://container@account.dfs.core.windows.net/key``.
``endpoint_url`` points at Azurite/mock servers in tests.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import http.client
import os
import re
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, Iterator, List, Optional, Tuple

from .object_io import (RETRYABLE_STATUS as _RETRYABLE_STATUS,
                        AzureConfig, IOStatsContext, ObjectSource,
                        parallel_get_ranges, retry_backoff_s)
from .s3 import _ConnectionPool, _glob_regex, _header_val
_API_VERSION = "2021-08-06"


def _parse_az_url(path: str) -> Tuple[Optional[str], str, str]:
    """→ (account_or_None, container, key)."""
    u = urllib.parse.urlparse(path)
    if u.scheme in ("az", "abfs", "abfss"):
        if "@" in u.netloc:  # abfss://container@account.dfs.core.windows.net
            container, host = u.netloc.split("@", 1)
            account = host.split(".", 1)[0]
            return account, container, u.path.lstrip("/")
        return None, u.netloc, u.path.lstrip("/")
    raise ValueError(f"not an azure url: {path!r}")


class AzureBlobSource(ObjectSource):
    scheme = "az"

    def __init__(self, config: AzureConfig = AzureConfig()):
        self.config = config
        self._pool = _ConnectionPool(config.max_connections)
        self._account = config.storage_account \
            or os.environ.get("AZURE_STORAGE_ACCOUNT")
        self._key = config.access_key \
            or os.environ.get("AZURE_STORAGE_KEY")
        self._sas = config.sas_token \
            or os.environ.get("AZURE_STORAGE_SAS_TOKEN")
        self._endpoint = config.endpoint_url \
            or os.environ.get("AZURE_ENDPOINT_URL")

    # ------------------------------------------------------------ transport
    def _locate(self, account: str) -> Tuple[str, int, bool, str]:
        """(host, port, tls, uri_prefix). Emulator endpoints use
        path-style /{account}/..."""
        if self._endpoint:
            u = urllib.parse.urlparse(self._endpoint)
            tls = u.scheme == "https"
            return (u.hostname, u.port or (443 if tls else 80), tls,
                    f"/{account}")
        return f"{account}.blob.core.windows.net", 443, True, ""

    def _sign(self, method: str, account: str, resource: str,
              query: Dict[str, str], headers: Dict[str, str],
              content_length: int) -> None:
        """SharedKey authorization (Blob service)."""
        if self.config.anonymous or not self._key:
            return
        headers["x-ms-date"] = datetime.datetime.now(
            datetime.timezone.utc).strftime("%a, %d %b %Y %H:%M:%S GMT")
        headers["x-ms-version"] = _API_VERSION
        ms_headers = sorted((k.lower(), str(v).strip())
                            for k, v in headers.items()
                            if k.lower().startswith("x-ms-"))
        canonical_headers = "".join(f"{k}:{v}\n" for k, v in ms_headers)
        canonical_resource = f"/{account}{resource}"
        for k in sorted(query):
            canonical_resource += f"\n{k.lower()}:{query[k]}"
        string_to_sign = "\n".join([
            method,
            "",  # Content-Encoding
            "",  # Content-Language
            str(content_length) if content_length else "",
            "",  # Content-MD5
            _header_val(headers, "content-type"),
            "",  # Date (x-ms-date used instead)
            "",  # If-Modified-Since
            "",  # If-Match
            "",  # If-None-Match
            "",  # If-Unmodified-Since
            _header_val(headers, "range"),
        ]) + "\n" + canonical_headers + canonical_resource
        sig = base64.b64encode(hmac.new(
            base64.b64decode(self._key), string_to_sign.encode("utf-8"),
            hashlib.sha256).digest()).decode()
        headers["Authorization"] = f"SharedKey {account}:{sig}"

    def _request(self, method: str, account: str, resource: str,
                 query: Dict[str, str] = None,
                 headers: Dict[str, str] = None, body: bytes = b""
                 ) -> Tuple[int, Dict[str, str], bytes]:
        if not account:
            raise ValueError(
                "azure url without account: set AzureConfig.storage_account "
                "or use abfss://container@account... form")
        host, port, tls, prefix = self._locate(account)
        q = dict(query or {})
        hdrs = dict(headers or {})
        hdrs.setdefault("x-ms-version", _API_VERSION)
        if body:
            hdrs["Content-Length"] = str(len(body))
        self._sign(method, account, resource, q, hdrs, len(body))
        qs = urllib.parse.urlencode(sorted(q.items()))
        if self._sas and not self._key:
            qs = (qs + "&" if qs else "") + self._sas.lstrip("?")
        quoted = urllib.parse.quote(resource, safe="/~._-")
        path = prefix + quoted + (f"?{qs}" if qs else "")

        last_exc: Optional[Exception] = None
        for attempt in range(max(1, self.config.num_tries)):
            conn = self._pool.acquire(host, port, tls)
            try:
                conn.request(method, path, body=body or None, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                status = resp.status
                rheaders = dict(resp.getheaders())
                self._pool.release(host, port, tls, conn)
            except (OSError, http.client.HTTPException) as exc:
                conn.close()
                last_exc = exc
                time.sleep(retry_backoff_s(path, attempt))
                continue
            if status in _RETRYABLE_STATUS:
                last_exc = RuntimeError(
                    f"azure {method} {path}: HTTP {status}: {data[:200]!r}")
                time.sleep(retry_backoff_s(path, attempt))
                continue
            return status, rheaders, data
        raise last_exc

    def _resolve(self, path: str) -> Tuple[str, str, str]:
        account, container, key = _parse_az_url(path)
        return account or self._account, container, key

    # ------------------------------------------------------- ObjectSource
    def get(self, path, byte_range=None, stats=None) -> bytes:
        account, container, key = self._resolve(path)
        headers = {}
        if byte_range is not None:
            headers["range"] = f"bytes={byte_range[0]}-{byte_range[1] - 1}"
        status, _, data = self._request(
            "GET", account, f"/{container}/{key}", headers=headers)
        if status not in (200, 206):
            raise FileNotFoundError(f"azure GET {path}: HTTP {status}")
        if stats:
            stats.record_get(len(data))
        return data

    def get_ranges(self, path, ranges, stats=None, parallelism=None):
        return parallel_get_ranges(
            self, path, ranges, stats,
            min(parallelism or 8, self.config.max_connections))

    def put(self, path, data, stats=None) -> None:
        account, container, key = self._resolve(path)
        status, _, body = self._request(
            "PUT", account, f"/{container}/{key}",
            headers={"x-ms-blob-type": "BlockBlob",
                     "Content-Type": "application/octet-stream"}, body=data)
        if status not in (200, 201):
            raise IOError(f"azure PUT {path}: HTTP {status}: {body[:200]!r}")
        if stats:
            stats.record_put(len(data))

    def get_size(self, path) -> int:
        account, container, key = self._resolve(path)
        status, headers, _ = self._request("HEAD", account,
                                           f"/{container}/{key}")
        if status != 200:
            raise FileNotFoundError(f"azure HEAD {path}: HTTP {status}")
        lower = {k.lower(): v for k, v in headers.items()}
        return int(lower.get("content-length", 0))

    def _list(self, account: str, container: str, prefix: str,
              stats: Optional[IOStatsContext] = None
              ) -> Iterator[Tuple[str, int]]:
        marker = None
        while True:
            q = {"restype": "container", "comp": "list", "prefix": prefix}
            if marker:
                q["marker"] = marker
            status, _, data = self._request("GET", account, f"/{container}",
                                            query=q)
            if status != 200:
                raise IOError(
                    f"azure LIST {container}/{prefix}: HTTP {status}")
            if stats:
                stats.record_list()
            root = ET.fromstring(data)
            for blob in root.iter("Blob"):
                name = blob.findtext("Name")
                size = int(blob.findtext("Properties/Content-Length") or 0)
                yield name, size
            marker = root.findtext("NextMarker")
            if not marker:
                return

    def glob(self, pattern, stats=None) -> List[str]:
        account, container, keypat = self._resolve(pattern)
        wild = min((keypat.index(ch) for ch in "*?[" if ch in keypat),
                   default=None)
        if wild is None:
            return [pattern]
        prefix = keypat[:wild]
        pat = re.compile(_glob_regex(keypat))
        out = []
        for key, _size in self._list(account, container, prefix,
                                     stats=stats):
            if pat.match(key):
                out.append(f"az://{container}/{key}")
        return sorted(out)

    def ls(self, path) -> Iterator[Tuple[str, int]]:
        account, container, prefix = self._resolve(path)
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        for key, size in self._list(account, container, prefix):
            yield f"az://{container}/{key}", size
