"""Native Delta Lake table reader — no SDK required.

The reference reads Delta through the ``deltalake`` Python package
(``daft/io/_deltalake.py``, ``daft/delta_lake/``); this environment has no
SDK, so the transaction log is replayed directly (the Delta protocol's
reader path is simple): list ``_delta_log/``, start from the latest
``*.checkpoint.parquet`` (if any), apply newer ``NNNNNNNNNN.json`` commits
in order, accumulate ``add`` actions minus ``remove`` actions, take the
schema from the latest ``metaData`` action, and scan the surviving parquet
files with their partition values (partition columns are not stored in the
data files).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import pyarrow.parquet as pq

from ..datatype import DataType
from ..schema import Field, Schema
from .scan import Pushdowns, ScanOperator, ScanTask

_COMMIT_RE = re.compile(r"^(\d{20})\.json$")
_CHECKPOINT_RE = re.compile(r"^(\d{20})\.checkpoint(\.\d+\.\d+)?\.parquet$")

_DELTA_PRIMITIVES = {
    "string": DataType.string, "long": DataType.int64,
    "integer": DataType.int32, "short": DataType.int16,
    "byte": DataType.int8, "float": DataType.float32,
    "double": DataType.float64, "boolean": DataType.bool,
    "binary": DataType.binary, "date": DataType.date,
    "timestamp": lambda: DataType.timestamp("us", "UTC"),
    "timestamp_ntz": lambda: DataType.timestamp("us"),
}


def _delta_type(t) -> DataType:
    if isinstance(t, str):
        if t in _DELTA_PRIMITIVES:
            return _DELTA_PRIMITIVES[t]()
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", t)
        if m:
            return DataType.decimal128(int(m.group(1)), int(m.group(2)))
        raise ValueError(f"unsupported delta type {t!r}")
    kind = t.get("type")
    if kind == "struct":
        return DataType.struct(
            {f["name"]: _delta_type(f["type"]) for f in t["fields"]})
    if kind == "array":
        return DataType.list(_delta_type(t["elementType"]))
    if kind == "map":
        return DataType.map(_delta_type(t["keyType"]),
                            _delta_type(t["valueType"]))
    raise ValueError(f"unsupported delta type {t!r}")


def _schema_from_metadata(meta: Dict[str, Any]) -> Tuple[Schema, List[str]]:
    struct = json.loads(meta["schemaString"])
    fields = [Field(f["name"], _delta_type(f["type"]))
              for f in struct["fields"]]
    return Schema(fields), list(meta.get("partitionColumns") or [])


def _coerce_partition_value(v: Optional[str], dtype: DataType):
    if v is None:
        return None
    if dtype.is_string():
        return v  # "" is a legitimate string partition value, not null
    if v == "":
        return None
    if dtype.is_integer():
        return int(v)
    if dtype.kind in ("float32", "float64"):
        return float(v)
    if dtype.is_boolean():
        return v.lower() == "true"
    return v


class DeltaScanOperator(ScanOperator):
    """Scan over the live ``add`` files of a Delta table snapshot."""

    def __init__(self, table_uri: str, version: Optional[int] = None):
        self._uri = table_uri.rstrip("/")
        log_dir = os.path.join(self._uri, "_delta_log")
        if not os.path.isdir(log_dir):
            raise FileNotFoundError(
                f"not a Delta table (no _delta_log): {table_uri!r}")
        self._version, adds, meta = self._replay(log_dir, version)
        if meta is None:
            raise ValueError(f"Delta log has no metaData action: {log_dir}")
        self._schema, self._partition_cols = _schema_from_metadata(meta)
        self._adds = adds  # path -> partitionValues

    # ------------------------------------------------------------------
    def _replay(self, log_dir: str, want_version: Optional[int]):
        entries = os.listdir(log_dir)
        commits = sorted((int(m.group(1)), f) for f in entries
                         if (m := _COMMIT_RE.match(f)))
        checkpoints = sorted((int(m.group(1)), f) for f in entries
                             if (m := _CHECKPOINT_RE.match(f)))
        if want_version is not None:
            commits = [(v, f) for v, f in commits if v <= want_version]
            checkpoints = [(v, f) for v, f in checkpoints
                           if v <= want_version]
        adds: Dict[str, Dict[str, Any]] = {}
        meta = None
        start = 0
        if checkpoints:
            cv = checkpoints[-1][0]
            # a checkpoint may be multi-part: replay EVERY part at that
            # version (add actions are spread across the parts)
            parts = [f for v, f in checkpoints if v == cv]
            for cf in parts:
                t = pq.read_table(os.path.join(log_dir, cf))
                for row in t.to_pylist():
                    if row.get("metaData") \
                            and row["metaData"].get("schemaString"):
                        meta = row["metaData"]
                    add = row.get("add")
                    if add and add.get("path"):
                        adds[add["path"]] = add.get("partitionValues") or {}
                    rem = row.get("remove")
                    if rem and rem.get("path"):
                        adds.pop(rem["path"], None)
            start = cv + 1
        version = checkpoints[-1][0] if checkpoints else -1
        for v, f in commits:
            if v < start:
                continue
            version = v
            with open(os.path.join(log_dir, f)) as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    action = json.loads(line)
                    if "metaData" in action:
                        meta = action["metaData"]
                    elif "add" in action:
                        adds[action["add"]["path"]] = \
                            action["add"].get("partitionValues") or {}
                    elif "remove" in action:
                        adds.pop(action["remove"]["path"], None)
        return version, adds, meta

    # ------------------------------------------------------------------
    @property
    def version(self) -> int:
        return self._version

    def schema(self) -> Schema:
        return self._schema

    def partitioning_keys(self) -> List[str]:
        return list(self._partition_cols)

    def multiline_display(self) -> List[str]:
        return [f"DeltaScanOperator(v{self._version})",
                f"uri = {self._uri}"]

    def to_scan_tasks(self, pushdowns: Pushdowns) -> List[ScanTask]:
        from . import readers
        tasks: List[ScanTask] = []
        for rel_path, pvals in sorted(self._adds.items()):
            path = os.path.join(self._uri, rel_path)
            coerced = {}
            for c in self._partition_cols:
                dt = self._schema[c].dtype
                coerced[c] = _coerce_partition_value(pvals.get(c), dt)
            tasks.extend(readers.make_scan_tasks(
                path, "parquet", self._schema, pushdowns, {}, coerced))
        if not tasks:
            tasks = [ScanTask([], "parquet", self._schema, pushdowns, 0, 0,
                              generator=lambda: iter(()))]
        return tasks


def read_deltalake(table_uri: str, version: Optional[int] = None,
                   io_config: Any = None, **kwargs):
    """Read a Delta Lake table snapshot into a DataFrame (reference API:
    ``daft/io/_deltalake.py``; implementation is the native log replay
    above — local paths only until remote listing is wired)."""
    from ..dataframe import DataFrame
    from ..logical.builder import LogicalPlanBuilder
    return DataFrame(LogicalPlanBuilder.from_scan(
        DeltaScanOperator(table_uri, version)))


# ---------------------------------------------------------------------------
# writer


def _dtype_to_delta(dt: DataType):
    inverse = {"string": "string", "int64": "long", "int32": "integer",
               "int16": "short", "int8": "byte", "float32": "float",
               "float64": "double", "bool": "boolean", "binary": "binary",
               "date": "date"}
    if dt.kind in inverse:
        return inverse[dt.kind]
    if dt.kind == "timestamp":
        return "timestamp"
    if dt.is_decimal():
        p, s = dt._params[0], dt._params[1]
        return f"decimal({p},{s})"
    raise ValueError(f"cannot map {dt!r} to a Delta type")


def write_deltalake(df, table_uri: str, mode: str = "append",
                    io_config: Any = None, **kwargs):
    """Commit a DataFrame to a Delta table (reference API:
    ``DataFrame.write_deltalake``). Creates the table (protocol v1 +
    metaData) on first write; ``overwrite`` removes the previous snapshot's
    files in the same commit. Unpartitioned writes only."""
    import time
    import uuid as _uuid

    from ..recordbatch import RecordBatch

    uri = table_uri.rstrip("/")
    log_dir = os.path.join(uri, "_delta_log")
    os.makedirs(log_dir, exist_ok=True)
    entries = os.listdir(log_dir)
    existing = sorted(
        {int(m.group(1)) for f in entries if (m := _COMMIT_RE.match(f))}
        | {int(m.group(1)) for f in entries
           if (m := _CHECKPOINT_RE.match(f))})
    version = (existing[-1] + 1) if existing else 0
    now_ms = int(time.time() * 1000)

    actions: List[str] = []
    if version > 0 and mode == "error":
        raise FileExistsError(f"Delta table already exists: {uri}")
    if version == 0:
        schema = df.schema()
        schema_string = json.dumps({
            "type": "struct",
            "fields": [{"name": f.name, "type": _dtype_to_delta(f.dtype),
                        "nullable": True, "metadata": {}} for f in schema]})
        actions.append(json.dumps({"protocol": {
            "minReaderVersion": 1, "minWriterVersion": 2}}))
        actions.append(json.dumps({"metaData": {
            "id": _uuid.uuid4().hex, "format": {"provider": "parquet",
                                                "options": {}},
            "schemaString": schema_string, "partitionColumns": [],
            "configuration": {}, "createdTime": now_ms}}))
    elif mode == "overwrite":
        op = DeltaScanOperator(uri)
        for rel in sorted(op._adds):
            actions.append(json.dumps({"remove": {
                "path": rel, "deletionTimestamp": now_ms,
                "dataChange": True}}))

    from ..context import get_context
    parts = get_context().get_or_create_runner().run(df._builder).partitions
    written = 0
    for i, p in enumerate(parts):
        rb = p.combined() if not isinstance(p, RecordBatch) else p
        if len(rb) == 0:
            continue
        rel = f"part-{version:05d}-{i:05d}-{_uuid.uuid4().hex[:8]}.parquet"
        full = os.path.join(uri, rel)
        pq.write_table(rb.to_arrow_table(), full)
        actions.append(json.dumps({"add": {
            "path": rel, "partitionValues": {},
            "size": os.path.getsize(full), "modificationTime": now_ms,
            "dataChange": True}}))
        written += len(rb)
    actions.append(json.dumps({"commitInfo": {
        "timestamp": now_ms, "operation": "WRITE",
        "operationParameters": {"mode": mode}, "engineInfo": "daft-tpu"}}))
    with open(os.path.join(log_dir, f"{version:020d}.json"), "w") as fh:
        fh.write("\n".join(actions) + "\n")
    return {"version": version, "rows_written": written}
