"""Format readers over Arrow C++ (pyarrow): parquet / csv / json.

Reference capabilities: ``src/daft-parquet`` (bulk reads, row-group pruning
via statistics ``statistics/``, byte-range coalescing), ``src/daft-csv`` /
``src/daft-json`` (schema inference, projection/limit pushdown). The pruning
and projection logic lives here; decode is Arrow C++.
"""

from __future__ import annotations

import json as _json
import os
from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from ..datatype import DataType
from ..expressions import Expression
from ..recordbatch import RecordBatch
from ..schema import Field, Schema
from ..series import Series
from .scan import Pushdowns, ScanTask


def _is_remote(path: str) -> bool:
    return "://" in path and not path.startswith("file://")


def _open_ranged(path: str, io_config=None):
    """Path (local) or a seekable ranged reader (remote) — parquet footer /
    row-group reads become range requests over the object store."""
    if not _is_remote(path):
        return path
    from . import read_planner as rp
    from .object_io import get_io_client
    from .s3 import S3ReadableFile
    client = get_io_client(io_config)
    return pa.PythonFile(S3ReadableFile(client.source_for(path), path,
                                        stats=rp.SCAN_STATS),
                         mode="r")


def _open_full(path: str, io_config=None):
    """Path (local) or an in-memory buffer of the whole object (remote) —
    the whole-object fallback for single-pass formats (csv/json)."""
    if not _is_remote(path):
        return path
    from . import read_planner as rp
    from .object_io import get_io_client
    client = get_io_client(io_config)
    return pa.BufferReader(client.get(path, None, rp.SCAN_STATS))


def _open_stream(path: str, io_config=None):
    """Path (local) or a chunked streaming reader (remote) — single-pass
    formats (csv/json) parse as chunks arrive instead of buffering the
    whole object: resident memory is chunk-sized and the parser overlaps
    the remaining fetches."""
    if not _is_remote(path):
        return path
    from . import read_planner as rp
    from .object_io import get_io_client
    client = get_io_client(io_config)
    src = client.source_for(path)
    try:
        reader = rp.ChunkedObjectReader(src, path, stats=rp.SCAN_STATS)
    except Exception:  # no size probe on this source → buffer whole
        return _open_full(path, io_config)
    return pa.PythonFile(reader, mode="r")


def _head_range_schema(path: str, file_format: str,
                       options: Dict[str, Any], io_config) -> Optional[Schema]:
    """Schema from a bounded head-range read of a remote CSV/JSON object
    (truncated at the last complete line); None → caller falls back to the
    whole object (tiny budget, no newline in the head, or parse failure —
    e.g. one record larger than the head budget).

    CSV inference was first-block-bounded before this path too
    (``pacsv.open_csv`` infers from its first ~1MB block), so only JSON
    trades tail visibility for the bounded read: a column whose type only
    widens past the head (int head, string tail) now surfaces at read
    time instead of inference time. ``DAFT_TPU_IO_INFER_BYTES=0``
    restores whole-object inference."""
    from . import read_planner as rp
    from .object_io import get_io_client
    budget = rp.infer_head_bytes()
    if budget <= 0:
        return None
    src = get_io_client(io_config).source_for(path)
    try:
        size = src.get_size(path)
    except Exception:
        return None
    if size <= 0:
        return None
    if size <= budget:
        data = src.get(path, None, rp.SCAN_STATS)
    else:
        data = src.get(path, (0, budget), rp.SCAN_STATS)
        nl = data.rfind(b"\n")
        if nl <= 0:
            return None
        data = data[:nl + 1]
    try:
        if file_format == "csv":
            ropts, popts, copts = _csv_options(options)
            with pacsv.open_csv(pa.BufferReader(data), read_options=ropts,
                                parse_options=popts,
                                convert_options=copts) as rdr:
                return Schema.from_arrow(rdr.schema)
        t = pajson.read_json(pa.BufferReader(data))
        return Schema.from_arrow(t.schema)
    except Exception:
        rp.scan_count("infer_head_fallbacks")
        return None


def infer_schema(path: str, file_format: str,
                 options: Dict[str, Any], io_config=None) -> Schema:
    if file_format == "parquet":
        return Schema.from_arrow(pq.read_schema(_open_ranged(path, io_config)))
    if file_format == "csv":
        if _is_remote(path):
            s = _head_range_schema(path, "csv", options, io_config)
            if s is not None:
                return s
        ropts, popts, copts = _csv_options(options)
        with pacsv.open_csv(_open_full(path, io_config), read_options=ropts,
                            parse_options=popts,
                            convert_options=copts) as rdr:
            return Schema.from_arrow(rdr.schema)
    if file_format == "json":
        if _is_remote(path):
            s = _head_range_schema(path, "json", options, io_config)
            if s is not None:
                return s
        t = pajson.read_json(_open_full(path, io_config))
        return Schema.from_arrow(t.schema)
    if file_format == "warc":
        from .warc import WARC_SCHEMA
        return WARC_SCHEMA
    raise ValueError(f"unknown format {file_format}")


def _csv_options(options: Dict[str, Any]):
    ropts = pacsv.ReadOptions(
        column_names=options.get("column_names"),
        autogenerate_column_names=not options.get("has_headers", True)
        and options.get("column_names") is None)
    popts = pacsv.ParseOptions(
        delimiter=options.get("delimiter") or ",",
        quote_char=options.get("quote") or '"',
        escape_char=options.get("escape_char") or False,
        newlines_in_values=options.get("allow_variable_columns", False))
    copts = pacsv.ConvertOptions()
    if options.get("schema") is not None:
        sch: Schema = options["schema"]
        copts.column_types = {f.name: f.dtype.to_arrow() for f in sch}
    return ropts, popts, copts


def make_scan_tasks(path: str, file_format: str, schema: Schema,
                    pushdowns: Pushdowns, options: Dict[str, Any],
                    partition_values: Dict[str, Any],
                    io_config=None) -> List[ScanTask]:
    """Per-file scan tasks, with parquet row-group pruning + split."""
    if file_format == "parquet":
        try:
            md = pq.ParquetFile(_open_ranged(path, io_config)).metadata
        except Exception:
            md = None
        if md is not None:
            groups = _prune_row_groups(md, pushdowns.filters, schema)
            nrows = sum(md.row_group(g).num_rows for g in groups) \
                if groups is not None else md.num_rows
            size = sum(md.row_group(g).total_byte_size for g in groups) \
                if groups is not None else \
                sum(md.row_group(i).total_byte_size for i in range(md.num_row_groups))
            task = ScanTask([path], "parquet", schema, pushdowns, nrows, size,
                            [groups] if groups is not None else None,
                            options, partition_values, io_config=io_config)
            task.pq_metadata = md  # reused by split_scan_tasks: one footer read
            return [task]
    if _is_remote(path):
        try:
            from .object_io import get_io_client
            size = get_io_client(io_config).source_for(path).get_size(path)
        except Exception:
            size = None
    else:
        size = os.path.getsize(path) if os.path.exists(path) else None
    return [ScanTask([path], file_format, schema, pushdowns, None, size, None,
                     options, partition_values, io_config=io_config)]


def _prune_row_groups(md, filters: Optional[Expression],
                      schema: Schema) -> Optional[List[int]]:
    """Zone-map pruning: drop row groups whose min/max can't satisfy the
    filter (reference: ``daft-parquet/src/statistics``). Conservative — only
    simple ``col <op> literal`` conjuncts are used."""
    if filters is None:
        return None
    bounds = _extract_bounds(filters)
    if not bounds:
        return None
    keep = []
    name_to_idx = None
    for g in range(md.num_row_groups):
        rg = md.row_group(g)
        if name_to_idx is None:
            name_to_idx = {rg.column(i).path_in_schema: i
                           for i in range(rg.num_columns)}
        ok = True
        for (cname, op, lit) in bounds:
            ci = name_to_idx.get(cname)
            if ci is None:
                continue
            stats = rg.column(ci).statistics
            if stats is None:
                continue
            if op in ("is_null", "not_null"):
                # null_count statistics: a group with zero nulls can't
                # satisfy is_null; an all-null group can't satisfy not_null
                if not getattr(stats, "has_null_count", False):
                    continue
                if op == "is_null" and stats.null_count == 0:
                    ok = False
                elif op == "not_null" and stats.null_count >= rg.num_rows:
                    ok = False
                if not ok:
                    break
                continue
            if not stats.has_min_max:
                continue
            mn, mx = stats.min, stats.max
            try:
                if op == "lt" and not (mn < lit):
                    ok = False
                elif op == "le" and not (mn <= lit):
                    ok = False
                elif op == "gt" and not (mx > lit):
                    ok = False
                elif op == "ge" and not (mx >= lit):
                    ok = False
                elif op == "eq" and not (mn <= lit <= mx):
                    ok = False
                elif op == "is_in" and not any(mn <= v <= mx for v in lit):
                    ok = False
            except TypeError:
                continue
            if not ok:
                break
        if ok:
            keep.append(g)
    return keep


_LIT_TYPES = (int, float, str, bytes)


def _extract_bounds(e: Expression):
    """Top-level AND conjuncts of form col <cmp> lit, plus
    col.is_null()/not_null() (null_count pruning) and
    col.is_in([literals]) (min/max containment pruning)."""
    import datetime
    out = []

    def walk(x: Expression):
        if x.op == "and":
            walk(x.args[0])
            walk(x.args[1])
            return
        if x.op in ("is_null", "not_null"):
            c = x.args[0]._unalias()
            if c.op == "col":
                out.append((c.params[0], x.op, None))
            return
        if x.op == "is_in":
            c = x.args[0]._unalias()
            if c.op != "col":
                return
            vals = []
            for a in x.args[1:]:
                if a.op == "lit" and isinstance(
                        a.params[0],
                        _LIT_TYPES + (datetime.date, datetime.datetime)) \
                        and not isinstance(a.params[0], bool):
                    vals.append(a.params[0])
                else:
                    return  # non-literal member → no static bound
            if vals:
                out.append((c.params[0], "is_in", tuple(vals)))
            return
        if x.op in ("lt", "le", "gt", "ge", "eq"):
            l, r = x.args
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
            if l.op == "lit" and r._unalias().op == "col":
                l, r = r, l
                op = flip[x.op]
            else:
                op = x.op
            li = l._unalias()
            if li.op == "col" and r.op == "lit":
                v = r.params[0]
                if isinstance(v, (datetime.date, datetime.datetime)):
                    # parquet stats for date32 come back as datetime.date
                    out.append((li.params[0], op, v))
                elif isinstance(v, (int, float, str, bytes)):
                    out.append((li.params[0], op, v))
    walk(e)
    return out


def read_scan_task(task: ScanTask) -> List[RecordBatch]:
    return list(iter_scan_task_batches(task))


def _planned_parquet_read(path: str, md, rg: Optional[List[int]],
                          phys_cols: Optional[List[str]], io_config):
    """The scan fast path's parquet read: plan the exact byte ranges for
    (pruned row groups × projected columns) off the footer, coalesce them
    into few large requests, fetch concurrently over the source's pool,
    and decode from the in-memory RangeCache — pyarrow issues zero GETs
    of its own (planner misses fall back per-read and are counted)."""
    from . import read_planner as rp
    from .object_io import get_io_client
    src = get_io_client(io_config).source_for(path)
    if md is None:
        # footer via the ranged reader: tail + footer range requests only
        md = pq.read_metadata(_open_ranged(path, io_config))
    arrow_schema = md.schema.to_arrow_schema()
    file_cols = None
    if phys_cols is not None:
        names = set(arrow_schema.names)
        file_cols = [c for c in phys_cols if c in names]
    if rg is not None and not rg:
        return arrow_schema.empty_table()
    needed = rp.plan_parquet_ranges(md, rg, file_cols)
    # needed may be empty (0-column projection: pyarrow synthesizes row
    # counts from metadata alone) — the empty cache still serves that,
    # with any surprise read falling back to a counted direct GET
    requests = rp.coalesce_ranges(needed)
    rp.scan_count("ranges_planned", len(needed))
    rp.scan_count("range_requests", len(requests))
    rp.scan_count("bytes_used", sum(e - s for s, e in needed))
    bufs = src.get_ranges(path, requests, rp.SCAN_STATS,
                          rp.range_parallelism())
    for (s, e), b in zip(requests, bufs):
        if len(b) != e - s:
            # a server ignoring Range (200 + whole body) would silently
            # corrupt the cache's offsets — refuse and fall back
            raise ValueError(
                f"range GET [{s}, {e}) returned {len(b)} bytes")
    cache = rp.RangeCache(list(zip(requests, bufs)))
    shim = pa.PythonFile(
        rp.RangeCacheFile(cache, src, path, stats=rp.SCAN_STATS), mode="r")
    f = pq.ParquetFile(shim, metadata=md)
    if rg is None:
        return f.read(columns=file_cols)
    return f.read_row_groups(rg, columns=file_cols)


def _read_parquet_path(task: ScanTask, path: str, i: int,
                       phys_cols: Optional[List[str]], cached_md, io_config):
    # reuse the footer metadata fetched at scan-planning time — a
    # remote file then needs only its row-group range requests
    md = cached_md if (cached_md is not None and i == 0
                       and len(task.paths) == 1) else None
    rg = task.row_groups[i] if task.row_groups else None
    if _is_remote(path):
        from . import read_planner as rp
        if rp.planned_reads_enabled():
            try:
                return _planned_parquet_read(path, md, rg, phys_cols,
                                             io_config)
            except Exception:
                rp.scan_count("planned_read_fallbacks")
    f = pq.ParquetFile(_open_ranged(path, io_config), metadata=md)
    file_cols = None
    if phys_cols is not None:
        names = set(f.schema_arrow.names)
        file_cols = [c for c in phys_cols if c in names]
    if rg is None:
        return f.read(columns=file_cols)
    return f.read_row_groups(rg, columns=file_cols) if rg else \
        f.schema_arrow.empty_table()


def iter_scan_task_batches(task: ScanTask) -> Iterator[RecordBatch]:
    """One RecordBatch per source file, yielded as each file decodes —
    the prefetch-pipelined scan consumes morsels off this stream instead
    of waiting for whole-task completion."""
    cols = list(task.pushdowns.columns) if task.pushdowns.columns is not None \
        else None
    phys_cols = None
    if cols is not None:
        phys_cols = [c for c in cols if c not in task.partition_values]
    io_config = getattr(task, "io_config", None)
    cached_md = getattr(task, "pq_metadata", None)
    for i, path in enumerate(task.paths):
        if task.file_format == "parquet":
            t = _read_parquet_path(task, path, i, phys_cols, cached_md,
                                   io_config)
        elif task.file_format == "csv":
            ropts, popts, copts = _csv_options(task.format_options)
            if phys_cols is not None:
                copts.include_columns = phys_cols
                copts.include_missing_columns = True
            t = pacsv.read_csv(_open_stream(path, io_config),
                               read_options=ropts,
                               parse_options=popts, convert_options=copts)
        elif task.file_format == "json":
            t = pajson.read_json(_open_stream(path, io_config))
            if phys_cols is not None:
                keep = [c for c in phys_cols if c in t.column_names]
                t = t.select(keep)
        elif task.file_format == "warc":
            from .warc import read_warc_file
            # limit can only pre-apply when no residual filter runs after
            limit = task.pushdowns.limit if task.pushdowns.filters is None \
                else None
            t = read_warc_file(path, limit=limit)
            if phys_cols is not None:
                keep = [c for c in phys_cols if c in t.column_names]
                t = t.select(keep)
        else:
            raise ValueError(f"unknown format {task.file_format}")
        rb = RecordBatch.from_arrow_table(t)
        if task.partition_values:
            n = len(rb)
            extra = []
            for k, v in task.partition_values.items():
                if cols is not None and k not in cols:
                    continue
                if k in rb.schema:
                    continue
                dt = task.schema[k].dtype if k in task.schema else None
                s = Series.from_pylist([v] * n, k)
                if dt is not None:
                    s = s.cast(dt)
                extra.append(s)
            if extra:
                rb = RecordBatch.from_series(rb.columns() + extra)
        yield rb
