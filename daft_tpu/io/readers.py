"""Format readers over Arrow C++ (pyarrow): parquet / csv / json.

Reference capabilities: ``src/daft-parquet`` (bulk reads, row-group pruning
via statistics ``statistics/``, byte-range coalescing), ``src/daft-csv`` /
``src/daft-json`` (schema inference, projection/limit pushdown). The pruning
and projection logic lives here; decode is Arrow C++.
"""

from __future__ import annotations

import json as _json
import os
from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from ..datatype import DataType
from ..expressions import Expression
from ..recordbatch import RecordBatch
from ..schema import Field, Schema
from ..series import Series
from .scan import Pushdowns, ScanTask


def _is_remote(path: str) -> bool:
    return "://" in path and not path.startswith("file://")


def _open_ranged(path: str, io_config=None):
    """Path (local) or a seekable ranged reader (remote) — parquet footer /
    row-group reads become range requests over the object store."""
    if not _is_remote(path):
        return path
    from .object_io import get_io_client
    from .s3 import S3ReadableFile
    client = get_io_client(io_config)
    return pa.PythonFile(S3ReadableFile(client.source_for(path), path),
                         mode="r")


def _open_full(path: str, io_config=None):
    """Path (local) or an in-memory buffer of the whole object (remote) —
    for single-pass formats (csv/json)."""
    if not _is_remote(path):
        return path
    from .object_io import get_io_client
    client = get_io_client(io_config)
    return pa.BufferReader(client.get(path))


def infer_schema(path: str, file_format: str,
                 options: Dict[str, Any], io_config=None) -> Schema:
    if file_format == "parquet":
        return Schema.from_arrow(pq.read_schema(_open_ranged(path, io_config)))
    if file_format == "csv":
        ropts, popts, copts = _csv_options(options)
        with pacsv.open_csv(_open_full(path, io_config), read_options=ropts,
                            parse_options=popts,
                            convert_options=copts) as rdr:
            return Schema.from_arrow(rdr.schema)
    if file_format == "json":
        t = pajson.read_json(_open_full(path, io_config))
        return Schema.from_arrow(t.schema)
    if file_format == "warc":
        from .warc import WARC_SCHEMA
        return WARC_SCHEMA
    raise ValueError(f"unknown format {file_format}")


def _csv_options(options: Dict[str, Any]):
    ropts = pacsv.ReadOptions(
        column_names=options.get("column_names"),
        autogenerate_column_names=not options.get("has_headers", True)
        and options.get("column_names") is None)
    popts = pacsv.ParseOptions(
        delimiter=options.get("delimiter") or ",",
        quote_char=options.get("quote") or '"',
        escape_char=options.get("escape_char") or False,
        newlines_in_values=options.get("allow_variable_columns", False))
    copts = pacsv.ConvertOptions()
    if options.get("schema") is not None:
        sch: Schema = options["schema"]
        copts.column_types = {f.name: f.dtype.to_arrow() for f in sch}
    return ropts, popts, copts


def make_scan_tasks(path: str, file_format: str, schema: Schema,
                    pushdowns: Pushdowns, options: Dict[str, Any],
                    partition_values: Dict[str, Any],
                    io_config=None) -> List[ScanTask]:
    """Per-file scan tasks, with parquet row-group pruning + split."""
    if file_format == "parquet":
        try:
            md = pq.ParquetFile(_open_ranged(path, io_config)).metadata
        except Exception:
            md = None
        if md is not None:
            groups = _prune_row_groups(md, pushdowns.filters, schema)
            nrows = sum(md.row_group(g).num_rows for g in groups) \
                if groups is not None else md.num_rows
            size = sum(md.row_group(g).total_byte_size for g in groups) \
                if groups is not None else \
                sum(md.row_group(i).total_byte_size for i in range(md.num_row_groups))
            task = ScanTask([path], "parquet", schema, pushdowns, nrows, size,
                            [groups] if groups is not None else None,
                            options, partition_values, io_config=io_config)
            task.pq_metadata = md  # reused by split_scan_tasks: one footer read
            return [task]
    if _is_remote(path):
        try:
            from .object_io import get_io_client
            size = get_io_client(io_config).source_for(path).get_size(path)
        except Exception:
            size = None
    else:
        size = os.path.getsize(path) if os.path.exists(path) else None
    return [ScanTask([path], file_format, schema, pushdowns, None, size, None,
                     options, partition_values, io_config=io_config)]


def _prune_row_groups(md, filters: Optional[Expression],
                      schema: Schema) -> Optional[List[int]]:
    """Zone-map pruning: drop row groups whose min/max can't satisfy the
    filter (reference: ``daft-parquet/src/statistics``). Conservative — only
    simple ``col <op> literal`` conjuncts are used."""
    if filters is None:
        return None
    bounds = _extract_bounds(filters)
    if not bounds:
        return None
    keep = []
    name_to_idx = None
    for g in range(md.num_row_groups):
        rg = md.row_group(g)
        if name_to_idx is None:
            name_to_idx = {rg.column(i).path_in_schema: i
                           for i in range(rg.num_columns)}
        ok = True
        for (cname, op, lit) in bounds:
            ci = name_to_idx.get(cname)
            if ci is None:
                continue
            stats = rg.column(ci).statistics
            if stats is None or not stats.has_min_max:
                continue
            mn, mx = stats.min, stats.max
            try:
                if op == "lt" and not (mn < lit):
                    ok = False
                elif op == "le" and not (mn <= lit):
                    ok = False
                elif op == "gt" and not (mx > lit):
                    ok = False
                elif op == "ge" and not (mx >= lit):
                    ok = False
                elif op == "eq" and not (mn <= lit <= mx):
                    ok = False
            except TypeError:
                continue
            if not ok:
                break
        if ok:
            keep.append(g)
    return keep


def _extract_bounds(e: Expression):
    """Top-level AND conjuncts of form col <cmp> lit."""
    out = []

    def walk(x: Expression):
        if x.op == "and":
            walk(x.args[0])
            walk(x.args[1])
            return
        if x.op in ("lt", "le", "gt", "ge", "eq"):
            l, r = x.args
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
            if l.op == "lit" and r._unalias().op == "col":
                l, r = r, l
                op = flip[x.op]
            else:
                op = x.op
            li = l._unalias()
            if li.op == "col" and r.op == "lit":
                v = r.params[0]
                import datetime
                if isinstance(v, (datetime.date, datetime.datetime)):
                    # parquet stats for date32 come back as datetime.date
                    out.append((li.params[0], op, v))
                elif isinstance(v, (int, float, str, bytes)):
                    out.append((li.params[0], op, v))
    walk(e)
    return out


def read_scan_task(task: ScanTask) -> List[RecordBatch]:
    batches: List[RecordBatch] = []
    cols = list(task.pushdowns.columns) if task.pushdowns.columns is not None \
        else None
    phys_cols = None
    if cols is not None:
        phys_cols = [c for c in cols if c not in task.partition_values]
    io_config = getattr(task, "io_config", None)
    cached_md = getattr(task, "pq_metadata", None)
    for i, path in enumerate(task.paths):
        if task.file_format == "parquet":
            # reuse the footer metadata fetched at scan-planning time — a
            # remote file then needs only its row-group range requests
            md = cached_md if (cached_md is not None and i == 0
                               and len(task.paths) == 1) else None
            f = pq.ParquetFile(_open_ranged(path, io_config), metadata=md)
            rg = task.row_groups[i] if task.row_groups else None
            file_cols = None
            if phys_cols is not None:
                names = set(f.schema_arrow.names)
                file_cols = [c for c in phys_cols if c in names]
            if rg is None:
                t = f.read(columns=file_cols)
            else:
                t = f.read_row_groups(rg, columns=file_cols) if rg else \
                    f.schema_arrow.empty_table()
        elif task.file_format == "csv":
            ropts, popts, copts = _csv_options(task.format_options)
            if phys_cols is not None:
                copts.include_columns = phys_cols
                copts.include_missing_columns = True
            t = pacsv.read_csv(_open_full(path, io_config), read_options=ropts,
                               parse_options=popts, convert_options=copts)
        elif task.file_format == "json":
            t = pajson.read_json(_open_full(path, io_config))
            if phys_cols is not None:
                keep = [c for c in phys_cols if c in t.column_names]
                t = t.select(keep)
        elif task.file_format == "warc":
            from .warc import read_warc_file
            # limit can only pre-apply when no residual filter runs after
            limit = task.pushdowns.limit if task.pushdowns.filters is None \
                else None
            t = read_warc_file(path, limit=limit)
            if phys_cols is not None:
                keep = [c for c in phys_cols if c in t.column_names]
                t = t.select(keep)
        else:
            raise ValueError(f"unknown format {task.file_format}")
        rb = RecordBatch.from_arrow_table(t)
        if task.partition_values:
            n = len(rb)
            extra = []
            for k, v in task.partition_values.items():
                if cols is not None and k not in cols:
                    continue
                if k in rb.schema:
                    continue
                dt = task.schema[k].dtype if k in task.schema else None
                s = Series.from_pylist([v] * n, k)
                if dt is not None:
                    s = s.cast(dt)
                extra.append(s)
            if extra:
                rb = RecordBatch.from_series(rb.columns() + extra)
        batches.append(rb)
    return batches
