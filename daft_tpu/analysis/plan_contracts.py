"""Declarative plan-node and optimizer-rule contract registry.

Every ``LogicalPlan`` / ``PhysicalPlan`` node type is declared here exactly
once, with the properties the rest of the engine is allowed to rely on:

- **schema derivation** — where ``schema()`` comes from (``leaf``: fixed at
  construction from the source; ``computed``: an explicit ``Schema`` passed
  to the constructor; ``child``: inherited verbatim from the first child).
- **partitioning / ordering derivation** — how the node transforms the
  partition-membership and sort-order properties of its input. These are
  prose contracts, but they are what the runtime plan sanitizer
  (``analysis/plan_sanitizer.py``) spot-checks: ``membership_check`` nodes
  get sampled hash-membership re-verification, ``order_check`` nodes get
  output sort-order verification, ``row_conservation`` nodes get row-count
  conservation accounting.
- **field inventory** — ``semantic_fields`` are the constructor attributes
  that define what the node MEANS (keys, join type, mode, expressions);
  ``estimate_fields`` are constructor-declared advisory fields that
  planners may rewrite from measurements without changing semantics;
  ``late_fields`` are attributes legitimately attached after construction
  (caches and planner annotations). ``analysis/rule_plans.py`` proves this
  inventory against the AST in both directions: an undeclared constructor
  assignment is a finding, and so is a declared field the constructor no
  longer assigns.

``RULE_CONTRACTS`` registers every ``Optimizer`` ``Rule`` subclass as
schema-preserving or schema-rewriting; the sanitizer asserts root-schema
equality after each rule application for the preserving ones, and
``rule_plans`` flags any unregistered rule class.

``REPLAN_MUTABLE`` is the closed set of (class, field) pairs the
distributed re-planner (``distributed/replan.py``) and adaptive layer may
mutate in place on an already-built plan, each with the reason the
mutation is semantics-free. Any other attribute store on a non-``self``
object in those modules is a finding.

To add a new plan node: declare a ``NodeContract`` here (the lint run
fails until you do), give it an explicit partitioning derivation — silent
"arbitrary" defaults are how co-partitioning bugs survive — and set the
runtime-check flags that apply. To add a new optimizer rule: append a
``RuleContract`` stating whether it preserves the root schema.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

# --------------------------------------------------------------- contracts


@dataclasses.dataclass(frozen=True)
class NodeContract:
    name: str
    layer: str                     # "logical" | "physical"
    schema: str                    # "leaf" | "computed" | "child"
    partitioning: str              # derivation of the output partitioning
    ordering: str                  # derivation of the output sort order
    rewrite_safety: str            # "frozen" | "estimate" | "strategy"
    semantic_fields: Tuple[str, ...]
    estimate_fields: Tuple[str, ...] = ()
    late_fields: Tuple[str, ...] = ()
    row_conservation: bool = False
    membership_check: bool = False
    order_check: bool = False


@dataclasses.dataclass(frozen=True)
class RuleContract:
    name: str
    schema_preserving: bool
    note: str


@dataclasses.dataclass(frozen=True)
class MutableField:
    cls: str
    field: str
    reason: str


def _n(name, layer, schema, partitioning, ordering, rewrite_safety,
       semantic_fields, **kw) -> NodeContract:
    return NodeContract(name, layer, schema, partitioning, ordering,
                        rewrite_safety, tuple(semantic_fields), **kw)


# ------------------------------------------------------- logical registry
# ``semantic_fields`` lists the public constructor self-assignments
# (underscore-prefixed attributes are internal caches owned by the class).

LOGICAL_NODES: Dict[str, NodeContract] = {c.name: c for c in [
    _n("Source", "logical", "leaf",
       "scan-task count (scan) / partition-list length (in-memory)",
       "none", "frozen",
       ("scan_op", "partitions", "pushdowns"),
       late_fields=("materialized_tasks",)),
    _n("Project", "logical", "computed", "inherits child", "preserves",
       "frozen", ("exprs",), row_conservation=True),
    _n("UDFProject", "logical", "computed", "inherits child", "preserves",
       "frozen", ("exprs", "concurrency"), row_conservation=True),
    _n("Filter", "logical", "child", "inherits child (subset per part)",
       "preserves", "frozen", ("predicate",)),
    _n("Limit", "logical", "child", "inherits child (prefix truncation)",
       "preserves", "frozen", ("limit", "offset")),
    _n("Explode", "logical", "computed", "inherits child (rows multiply "
       "in place)", "preserves row groups", "frozen", ("exprs",)),
    _n("Unpivot", "logical", "computed", "inherits child (rows multiply "
       "in place)", "preserves row groups", "frozen",
       ("ids", "values", "variable_name", "value_name")),
    _n("Sort", "logical", "child", "range(sort_by) over child partition "
       "count", "establishes sort_by", "frozen",
       ("sort_by", "descending", "nulls_first"), row_conservation=True),
    _n("TopN", "logical", "child", "single partition", "establishes "
       "sort_by", "frozen", ("sort_by", "descending", "nulls_first",
                             "limit")),
    _n("Repartition", "logical", "child", "explicit spec", "destroys",
       "frozen", ("spec",), row_conservation=True),
    _n("Distinct", "logical", "child", "inherits child", "destroys",
       "frozen", ("on",)),
    _n("Aggregate", "logical", "computed", "hash(group_by) after engine "
       "exchange; single partition when ungrouped", "destroys", "frozen",
       ("aggs", "group_by")),
    _n("Pivot", "logical", "computed", "hash(group_by) after engine "
       "exchange", "destroys", "frozen",
       ("group_by", "pivot_col", "value_col", "agg_expr", "names")),
    _n("Window", "logical", "computed", "hash(partition_by) after engine "
       "exchange", "preserves within partitions", "frozen",
       ("window_exprs", "partition_by", "order_by", "descending",
        "nulls_first", "frame"), row_conservation=True),
    _n("Concat", "logical", "child", "sum of both children's partitions",
       "destroys", "frozen", (), row_conservation=True),
    _n("Join", "logical", "computed", "hash(left_on/right_on) after "
       "engine exchange, or broadcast keeps probe-side partitioning",
       "destroys", "frozen",
       ("left_on", "right_on", "how", "strategy", "prefix", "suffix")),
    _n("Sample", "logical", "child", "inherits child (subset per part)",
       "preserves", "frozen",
       ("fraction", "size", "with_replacement", "seed")),
    _n("MonotonicallyIncreasingId", "logical", "computed",
       "inherits child", "preserves", "frozen", ("column_name",),
       row_conservation=True),
    _n("Sink", "logical", "computed", "single partition (manifest)",
       "none", "frozen", ("info",)),
]}


# ------------------------------------------------------ physical registry

PHYSICAL_NODES: Dict[str, NodeContract] = {c.name: c for c in [
    _n("ScanSource", "physical", "computed", "one partition per scan task",
       "none", "frozen", ("tasks",)),
    _n("InMemorySource", "physical", "computed", "one partition per "
       "in-memory micropartition", "none", "frozen", ("partitions",)),
    _n("Project", "physical", "computed", "inherits child", "preserves",
       "frozen", ("exprs",), row_conservation=True),
    _n("UDFProject", "physical", "computed", "inherits child",
       "preserves", "frozen", ("exprs", "concurrency"),
       row_conservation=True),
    _n("Filter", "physical", "child", "inherits child (subset per part)",
       "preserves", "frozen", ("predicate",)),
    _n("Limit", "physical", "child", "inherits child (prefix "
       "truncation)", "preserves", "frozen", ("limit", "offset")),
    _n("Explode", "physical", "computed", "inherits child (rows multiply "
       "in place)", "preserves row groups", "frozen", ("exprs",)),
    _n("Unpivot", "physical", "computed", "inherits child (rows multiply "
       "in place)", "preserves row groups", "frozen",
       ("ids", "values", "variable_name", "value_name")),
    _n("Sample", "physical", "child", "inherits child (subset per part)",
       "preserves", "frozen",
       ("fraction", "size", "with_replacement", "seed")),
    _n("MonotonicallyIncreasingId", "physical", "computed",
       "inherits child", "preserves", "frozen", ("column_name",),
       row_conservation=True),
    _n("Aggregate", "physical", "computed", "partial: inherits child; "
       "final/single: grouped output per input partition (exchange "
       "upstream provides co-partitioning)", "destroys", "estimate",
       ("aggs", "group_by", "mode"),
       estimate_fields=("group_rows_est", "group_ndv"),
       late_fields=("group_ndv_footer",)),
    _n("DeviceFragmentAgg", "physical", "computed", "inherits source",
       "destroys", "frozen", ("predicate", "aggs", "group_by", "mode")),
    _n("DeviceExchangeAgg", "physical", "computed", "hash(group_by) over "
       "mesh shards (disjoint key sets, one partition per shard)",
       "destroys", "frozen", ("aggs", "group_by")),
    _n("FusedRegion", "physical", "computed", "inherits source (chain/"
       "topk single output for topk)", "topk establishes sort_by; else "
       "preserves", "estimate",
       ("shape", "source", "exprs", "predicate", "fallback", "fused_ops",
        "sort_by", "descending", "nulls_first", "limit", "build",
        "left_on", "right_on", "aggs", "group_by", "mode"),
       estimate_fields=("group_rows_est", "group_ndv")),
    _n("Dedup", "physical", "child", "inherits child", "destroys",
       "frozen", ("on",)),
    _n("Pivot", "physical", "computed", "inherits child", "destroys",
       "frozen", ("group_by", "pivot_col", "value_col", "names")),
    _n("Window", "physical", "computed", "inherits child (exchange "
       "upstream provides hash(partition_by))", "preserves within "
       "partitions", "frozen",
       ("window_exprs", "partition_by", "order_by", "descending",
        "nulls_first", "frame"), row_conservation=True),
    _n("Sort", "physical", "child", "range(sort_by) buckets in range "
       "order, or one fully-sorted partition", "establishes sort_by",
       "frozen", ("sort_by", "descending", "nulls_first"),
       row_conservation=True, order_check=True),
    _n("TopN", "physical", "child", "single partition",
       "establishes sort_by", "frozen",
       ("sort_by", "descending", "nulls_first", "limit"),
       order_check=True),
    _n("Exchange", "physical", "child", "kind(by): hash membership h(k) "
       "% n, range boundaries, round-robin split, or gather to 1",
       "destroys (hash/random/split) / range order across buckets",
       "strategy",
       ("kind", "num_partitions", "by", "descending", "engine_inserted"),
       estimate_fields=("join_side",),
       row_conservation=True, membership_check=True),
    _n("StageInput", "physical", "computed", "upstream stage's exchanged "
       "output partitioning", "none", "frozen", ("stage_id",)),
    _n("Concat", "physical", "child", "left partitions then right "
       "partitions", "destroys", "frozen", (), row_conservation=True),
    _n("HashJoin", "physical", "computed", "hash: co-partitioned inputs "
       "give hash(keys) output; broadcast: inherits probe side",
       "destroys", "estimate",
       ("left_on", "right_on", "how", "strategy"),
       estimate_fields=("left_bytes_est", "right_bytes_est")),
    _n("CrossJoin", "physical", "computed", "inherits left", "destroys",
       "frozen", ()),
    _n("Write", "physical", "computed", "single partition (manifest)",
       "none", "frozen", ("info",)),
]}

# Attributes the physical translator may attach to ANY physical node
# after construction (planner annotations shared across node types).
PHYSICAL_SHARED_LATE_FIELDS: Tuple[str, ...] = ("shared_consumers",)


# --------------------------------------------------------- rule registry
# Every ``Rule`` subclass in ``logical/optimizer.py``. ``schema_preserving``
# means the ROOT schema (names + dtypes, in order) is identical before and
# after ``apply`` — internal nodes may change freely. The runtime sanitizer
# asserts this per rule application.

RULE_CONTRACTS: Dict[str, RuleContract] = {c.name: c for c in [
    RuleContract("SimplifyExpressions", True,
                 "rewrites expressions to equivalent simpler forms"),
    RuleContract("PushDownFilter", True,
                 "moves Filter below row-local ops; predicates unchanged"),
    RuleContract("PushDownProjection", True,
                 "prunes unused columns below the root projection"),
    RuleContract("PushDownLimit", True,
                 "pushes Limit into sources; fuses Sort+Limit into TopN"),
    RuleContract("DropRepartition", True,
                 "removes redundant repartitions of identical specs"),
    RuleContract("MaterializeScans", True,
                 "binds scan pushdowns; column pruning already applied"),
    RuleContract("EliminateCrossJoin", True,
                 "converts cross join + equi-filter into an equi-join"),
    RuleContract("ReorderJoins", True,
                 "re-orders the join tree; wraps in a Project restoring "
                 "the original column order"),
    RuleContract("SimplifyNullFilteredJoin", True,
                 "strengthens outer joins under null-rejecting filters"),
    RuleContract("PushDownAntiSemiJoin", True,
                 "pushes semi/anti joins below row-local left-side ops"),
    RuleContract("FilterNullJoinKey", True,
                 "adds not-null key filters on non-preserved join sides"),
    RuleContract("SemiJoinReduction", True,
                 "inserts internal __sjr*__ semi-join reducers; output "
                 "columns unchanged"),
    RuleContract("PushDownJoinPredicate", True,
                 "clones literal key predicates across equi-joins"),
]}


# ------------------------------------------------- replan mutability set
# The ONLY in-place attribute mutations the distributed re-planner and
# AQE layers may perform on already-built plan/stage objects. Everything
# here is advisory (estimates) or a declared execution-strategy swap;
# none of it changes keys, join types, schemas, or expressions.

REPLAN_MUTABLE: Tuple[MutableField, ...] = (
    MutableField("Aggregate", "group_rows_est",
                 "measured output rows replace the planner's estimate"),
    MutableField("Aggregate", "group_ndv",
                 "measured key NDV replaces the footer-derived estimate"),
    MutableField("Aggregate", "group_ndv_footer",
                 "stash-once of the original footer NDV for explain"),
    MutableField("HashJoin", "left_bytes_est",
                 "measured build/probe bytes re-pick broadcast vs hash"),
    MutableField("HashJoin", "right_bytes_est",
                 "measured build/probe bytes re-pick broadcast vs hash"),
    MutableField("Boundary", "kind",
                 "broadcast demotion swaps hash shuffle for gather; "
                 "execution strategy only, downstream join is re-keyed "
                 "to match"),
    MutableField("Boundary", "num_partitions",
                 "partition count is execution strategy, not semantics"),
    MutableField("BoundaryActuals", "ndv",
                 "measured key NDV recorded as evidence"),
    MutableField("BoundaryActuals", "exact_ndv",
                 "marks the NDV evidence as exact, not estimated"),
)

REPLAN_MUTABLE_FIELDS = frozenset(m.field for m in REPLAN_MUTABLE)


def registered_estimate_fields() -> frozenset:
    """All estimate/late fields declared across the physical registry."""
    out = set()
    for c in PHYSICAL_NODES.values():
        out.update(c.estimate_fields)
        out.update(c.late_fields)
    return frozenset(out)
