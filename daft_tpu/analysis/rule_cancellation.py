"""Rule family 7 — cancellation responsiveness of partition-drain loops.

The serving plane's cancellation contract (r11) is cooperative: a fired
``CancelToken`` unwinds at the next check. The executors check at every
*yield* boundary — which covers pipelined loops for free — but a
blocking drain (sort consume, exchange fanout, join bucket store, merge
agg) iterates its whole input before yielding anything, so a loop
without its own poll turns INTERRUPT into "runs to completion while
holding admission". This family proves every morsel/partition/fetch
drain loop in the execution and serving modules reaches a cancellation
check.

A loop is credited when its body (or a same-module helper it calls):

- checks a token — ``tok.check()`` / ``token.is_set()`` /
  ``self._poll_cancel()`` and friends;
- ``yield``\\ s — the driver loop's boundary check covers it;
- ``put()``\\ s into a pipeline channel — ``Channel.put`` polls the
  pipeline's cancel event on every blocked attempt.

Loops whose responsiveness lives in the *iterator* (e.g. pipeline
``Channel.__iter__`` polls per get) carry a pragma naming the mechanism
— the sanctioned escape hatch the family's zero-findings bar demands.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from . import dataflow
from .dataflow import ModuleIndex
from .framework import Finding, SourceFile
from .rule_resources import walk_local

#: modules whose loops drain morsels/partitions/fetch results
SCOPE_PREFIXES = (
    "daft_tpu/execution/executor.py",
    "daft_tpu/execution/pipeline.py",
    "daft_tpu/serving/",
)

#: terminal names that identify a partition/morsel/fetch stream
STREAM_NAMES = frozenset({
    "stream", "parts", "partitions", "morsels", "buf", "lbuf", "rbuf",
    "child", "fetches", "results", "batches",
})

#: calls that produce a partition stream
STREAM_CALLS = frozenset({
    "_exec", "_exec_node", "run_iter", "stream_batches", "materialize",
})

#: call last-names that ARE a cancellation check
CHECK_CALLS = frozenset({
    "check", "check_cancel", "_check_cancel", "poll_cancel",
    "_poll_cancel",
})

#: receivers a bare ``.check()`` / ``.is_set()`` must ride to count
_TOKENISH = ("token", "tok", "cancel")

RULE_IDS = {
    "uncancellable-loop": (
        "cancellation",
        "poll the CancelToken in the loop body (self._poll_cancel() / "
        "tok.check()) or pragma the mechanism that already covers it"),
}


def _call_last(call: ast.Call) -> str:
    return dataflow._call_last_name(call)


def _iter_terminal(expr: ast.AST) -> Optional[str]:
    """The terminal identifier of an iterated expression, looking
    through enumerate/zip/iter/reversed wrappers and subscripts."""
    if isinstance(expr, ast.Call) and _call_last(expr) in (
            "enumerate", "zip", "iter", "reversed", "list"):
        for a in expr.args:
            t = _iter_terminal(a)
            if t is not None:
                return t
        return None
    if isinstance(expr, ast.IfExp):
        return _iter_terminal(expr.body) or _iter_terminal(expr.orelse)
    if isinstance(expr, ast.Subscript):
        return _iter_terminal(expr.value)
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_stream_iter(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and _call_last(sub) in STREAM_CALLS:
            return True
    t = _iter_terminal(expr)
    return t is not None and t in STREAM_NAMES


def _tokenish_recv(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = dataflow.dotted(call.func.value).lower()
    return any(t in recv for t in _TOKENISH)


def _body_credited(body: List[ast.stmt], defs, depth: int = 1) -> bool:
    for stmt in body:
        # a yield/put/check inside a nested def (a callback defined in
        # the loop body) runs on some other call, not on this drain
        # iteration — it must not credit the loop; walk_local handles
        # defs nested deeper, the isinstance skips one AS the statement
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for sub in walk_local(stmt):
            if isinstance(sub, (ast.Yield, ast.YieldFrom)):
                return True
            if not isinstance(sub, ast.Call):
                continue
            last = _call_last(sub)
            if last in CHECK_CALLS and (
                    last != "check" or _tokenish_recv(sub)
                    or not isinstance(sub.func, ast.Attribute)):
                return True
            if last == "is_set" and _tokenish_recv(sub):
                return True
            if last == "put" and isinstance(sub.func, ast.Attribute):
                return True  # Channel.put polls the pipeline cancel event
            if depth > 0:
                callee = defs.get(last)
                if callee is not None and _body_credited(
                        callee.body, defs, depth - 1):
                    return True
    return False


def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if not any(sf.path == p or sf.path.startswith(p)
                   for p in SCOPE_PREFIXES):
            continue
        idx = ModuleIndex(sf.tree)
        for fname, fn in idx.functions:
            for sub in walk_local(fn):
                if not isinstance(sub, (ast.For, ast.AsyncFor)):
                    continue
                if not _is_stream_iter(sub.iter):
                    continue
                if _body_credited(sub.body, idx.defs):
                    continue
                out.append(Finding(
                    "uncancellable-loop", sf.path, sub.lineno,
                    f"loop over {ast.unparse(sub.iter)[:60]} in "
                    f"{fname}() drains a partition stream without a "
                    f"CancelToken check — INTERRUPT would run it to "
                    f"completion while holding admission"))
    return out
