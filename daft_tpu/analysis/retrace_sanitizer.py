"""Runtime retrace sanitizer (opt-in: ``DAFT_TPU_SANITIZE=1`` +
``DAFT_TPU_SANITIZE_RETRACE=<budget>``).

``rule_shapes`` proves statically that row counts reach shapes only
through the size-class chokepoint and that every jit program is
memoized; this sanitizer proves the *consequence* at test time: a
registered dispatch site re-traces only when its declared signature
changes.  The recompile tax ROADMAP item 1 measures (23.3s hot device q1
vs 2.2s host; 55s warm-up) is exactly what this turns from a profile
into a failing test.

Mechanics:

- ``enable()`` registers a ``jax.monitoring`` duration listener; JAX
  fires ``/jax/core/compile/jaxpr_trace_duration`` once per tracing
  cache miss (a re-trace) and ``…/backend_compile_duration`` once per
  XLA compile — the exact events the tax is made of.
- Dispatch chokepoints wrap their jitted call in
  ``dispatch_scope(site_id, signature_key)``.  The site must be declared
  in ``analysis/dispatch_registry.py``; the key spells everything the
  site's trace cache key is ALLOWED to depend on (capacity class,
  out-cap bucket, strategy, …).  A trace event inside the scope charges
  that (site, key); exceeding ``traces_per_key × DAFT_TPU_SANITIZE_RETRACE``
  is a budget violation: the same signature traced twice means the
  surrounding code leaked shape instability (a raw row count, a fresh
  wrapper object, a non-weak-typed literal) into the cache key.
- Trace events OUTSIDE any scope are attributed to the innermost
  ``daft_tpu`` stack frame and counted (``unscoped``) but never
  budget-enforced — tests and benches call kernels directly on purpose.
- ``tests/conftest.py`` reports at session end and FAILS the session on
  any budget violation; per-query deltas land in
  ``explain(analyze=True)`` / ``/metrics`` / the flight recorder via
  ``observability.RuntimeStatsContext`` (the lock-sanitizer pattern).

Off by default and allocation-free when off: ``dispatch_scope`` returns
a shared no-op singleton, and ``enable()`` is never called unless both
knobs arm it.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

from . import dispatch_registry

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS_DIR = os.path.dirname(os.path.abspath(__file__))

#: jax's monitoring event names (stable since 0.4.x; re-spelled here so
#: enable() works even if jax._src.dispatch moves the constants)
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class RetraceSanitizer:
    """Per-(site, signature) trace accounting + budget enforcement.
    One global instance backs the armed session; tests may build their
    own and drive :meth:`note_event` directly."""

    def __init__(self, budget_multiplier: int = 1):
        self._meta = threading.Lock()
        self.budget_multiplier = max(int(budget_multiplier), 1)
        self._scopes = threading.local()
        # monotonic counters
        self.traces = 0               # scoped + unscoped trace events
        self.compiles = 0
        self.compile_seconds = 0.0
        self.unscoped_traces = 0
        # per-site / per-key books
        self._site_traces: Dict[str, int] = {}
        self._key_traces: Dict[Tuple[str, object], int] = {}
        self._unscoped_sites: Dict[str, int] = {}
        self.violations: List[str] = []
        self._violation_keys: set = set()

    # ---- scopes ------------------------------------------------------
    def _stack(self) -> List[list]:
        st = getattr(self._scopes, "stack", None)
        if st is None:
            st = []
            self._scopes.stack = st
        return st

    def push(self, site_id: str, key: object) -> None:
        # [site, key, traced?] — one logical dispatch traces ONE program
        # but fires a trace event per nested jit boundary it traces
        # through; only the FIRST event in a scope entry charges the
        # budget (a retrace is a LATER entry tracing again)
        self._stack().append([site_id, key, False])

    def pop(self) -> None:
        st = self._stack()
        if st:
            st.pop()

    # ---- event intake ------------------------------------------------
    def note_event(self, event: str, duration: float) -> None:
        if event == COMPILE_EVENT:
            with self._meta:
                self.compiles += 1
                self.compile_seconds += duration
            return
        if event != TRACE_EVENT:
            return
        st = self._stack()
        if st:
            entry = st[-1]
            if entry[2]:    # nested trace of the same dispatch
                with self._meta:
                    self.traces += 1
                return
            entry[2] = True
            self._charge(entry[0], entry[1])
        else:
            site = _engine_frame() or "foreign"
            with self._meta:
                self.traces += 1
                self.unscoped_traces += 1
                self._unscoped_sites[site] = \
                    self._unscoped_sites.get(site, 0) + 1

    def _charge(self, site_id: str, key: object) -> None:
        budget = dispatch_registry.budget_for(site_id)
        with self._meta:
            self.traces += 1
            self._site_traces[site_id] = \
                self._site_traces.get(site_id, 0) + 1
            try:
                kk = (site_id, key)
                n = self._key_traces.get(kk, 0) + 1
                self._key_traces[kk] = n
            except TypeError:   # unhashable key: site-level count only
                return
            if budget is None:
                return          # exempt site (bench / AOT warm-up)
            if n > budget * self.budget_multiplier \
                    and kk not in self._violation_keys:
                self._violation_keys.add(kk)
                s = dispatch_registry.site(site_id)
                contract = f" (contract: {s.budget})" if s else ""
                self.violations.append(
                    f"{site_id}: {n} traces for one signature "
                    f"{_fmt_key(key)} — budget is "
                    f"{budget * self.budget_multiplier} per "
                    f"signature{contract}")

    # ---- reporting ---------------------------------------------------
    def summary(self) -> dict:
        with self._meta:
            return {
                "traces": self.traces,
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 6),
                "unscoped_traces": self.unscoped_traces,
                "site_traces": dict(self._site_traces),
                "unscoped_sites": dict(self._unscoped_sites),
                "violations": list(self.violations),
            }

    def report(self) -> str:
        s = self.summary()
        lines = [
            f"retrace sanitizer: {s['traces']} traces, "
            f"{s['compiles']} XLA compiles "
            f"({s['compile_seconds']:.2f}s compiling), "
            f"{s['unscoped_traces']} unscoped",
        ]
        for site, n in sorted(s["site_traces"].items()):
            lines.append(f"  {site}: {n} trace(s)")
        if s["violations"]:
            lines.append(f"RETRACE BUDGET VIOLATIONS "
                         f"({len(s['violations'])}):")
            lines.extend(f"  {v}" for v in s["violations"])
        else:
            lines.append("no retrace-budget violations")
        return "\n".join(lines)


def _fmt_key(key: object, limit: int = 120) -> str:
    try:
        s = repr(key)
    except Exception:
        s = "<unreprable>"
    return s if len(s) <= limit else s[:limit] + "…"


def _engine_frame() -> Optional[str]:
    """file:line of the innermost daft_tpu frame (excluding this
    package's analysis machinery), for unscoped-trace attribution."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        af = os.path.abspath(fn)
        if af.startswith(_PKG_ROOT + os.sep) \
                and not af.startswith(_ANALYSIS_DIR + os.sep):
            rel = os.path.relpath(af, os.path.dirname(_PKG_ROOT))
            return f"unscoped:{rel.replace(os.sep, '/')}:{f.f_lineno}"
        f = f.f_back
    return None


# ----------------------------------------------------------- global state

_global: Optional[RetraceSanitizer] = None
_enabled = False


class _Scope:
    """Reusable scope guard; one allocation per dispatch, none when the
    sanitizer is off (the module hands out ``_NOOP`` instead)."""

    __slots__ = ("_site", "_key")

    def __init__(self, site_id: str, key: object):
        self._site = site_id
        self._key = key

    def __enter__(self):
        san = _global
        if san is not None:
            san.push(self._site, self._key)
        return self

    def __exit__(self, *exc):
        san = _global
        if san is not None:
            san.pop()
        return False


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopScope()


def dispatch_scope(site_id: str, key: object):
    """Enter around a jitted dispatch: trace events inside are charged
    to ``(site_id, key)``.  The shared no-op singleton when disarmed —
    zero allocation on the hot path."""
    if not _enabled:
        return _NOOP
    return _Scope(site_id, key)


def scoped_callable(site_id: str, key: object, fn):
    """Wrap an ESCAPING jitted callable (one handed back to callers,
    like the memoized mesh-exchange programs) so every call runs under
    its dispatch scope.  The per-call signature extends ``key`` with the
    argument shapes/dtypes — one program legitimately traces once per
    input shape class, and only a repeat of the SAME shapes is a
    retrace.  The wrapper checks the armed flag per call: programs
    built before ``enable()`` still get charged after it."""

    def call(*args, **kwargs):
        if not _enabled:
            return fn(*args, **kwargs)
        shapes = tuple(
            (getattr(a, "shape", None), str(getattr(a, "dtype", "")))
            for a in args)
        with _Scope(site_id, (key, shapes)):
            return fn(*args, **kwargs)

    call.__wrapped__ = fn
    return call


def _listener(event: str, duration: float, **kwargs) -> None:
    san = _global
    if san is not None:
        san.note_event(event, duration)


def enabled_by_env() -> bool:
    from . import knobs
    return bool(knobs.env_bool("DAFT_TPU_SANITIZE")) \
        and (knobs.env_int("DAFT_TPU_SANITIZE_RETRACE") or 0) > 0


def budget_multiplier_from_env() -> int:
    from . import knobs
    return max(knobs.env_int("DAFT_TPU_SANITIZE_RETRACE") or 1, 1)


def enable(multiplier: Optional[int] = None) -> None:
    """Install the jax.monitoring listener + arm the global sanitizer.
    Idempotent; call as early as possible (``daft_tpu/__init__`` arms it
    next to the lock sanitizer so even import-time jits are seen)."""
    global _global, _enabled
    if _enabled:
        return
    import jax.monitoring as monitoring
    # daft-lint: allow(unguarded-global-mutation) -- single-threaded
    # bootstrap: enable() runs in conftest/__init__ before engine threads
    _global = RetraceSanitizer(
        multiplier if multiplier is not None
        else budget_multiplier_from_env())
    monitoring.register_event_duration_secs_listener(_listener)
    # daft-lint: allow(unguarded-global-mutation) -- same bootstrap; the
    # flag flips only after the listener + sanitizer are fully installed
    _enabled = True


def disable() -> None:
    """Disarm and best-effort unregister the listener (jax only exposes
    clear-all, so we surgically drop ours from the private list; if that
    ever breaks, the listener no-ops on a None global anyway)."""
    global _global, _enabled
    if not _enabled:
        return
    # daft-lint: allow(unguarded-global-mutation) -- mirror of enable():
    # teardown runs on the single main thread at session/test end
    _enabled = False
    # daft-lint: allow(unguarded-global-mutation) -- same teardown; the
    # listener no-ops on a None global either way
    _global = None
    try:
        from jax._src import monitoring as _m
        _m._event_duration_secs_listeners = [
            cb for cb in _m.get_event_duration_listeners()
            if cb is not _listener]
    except Exception:
        pass


def is_enabled() -> bool:
    return _enabled


def sanitizer() -> Optional[RetraceSanitizer]:
    return _global


def summary() -> dict:
    return _global.summary() if _global is not None else {}


def report() -> str:
    return _global.report() if _global is not None \
        else "retrace sanitizer: disabled"


# -------------------------------------------- observability integration

def counters_snapshot() -> Dict[str, float]:
    """Monotonic counters for per-query deltas (observability pattern:
    snapshot at query start, diff at finish)."""
    san = _global
    if not _enabled or san is None:
        return {}
    s = san.summary()
    return {"traces": s["traces"],
            "compiles": s["compiles"],
            "compile_seconds": s["compile_seconds"],
            "unscoped_traces": s["unscoped_traces"],
            "violations": len(s["violations"])}


def counters_delta(before: Dict[str, float],
                   after: Dict[str, float]) -> Dict[str, float]:
    out = {k: round(after.get(k, 0) - before.get(k, 0), 6)
           for k in after}
    # total violations is a level, not a delta — report the absolute too
    san = _global
    if _enabled and san is not None:
        out["total_violations"] = len(san.summary()["violations"])
    return out
