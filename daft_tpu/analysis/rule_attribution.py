"""Rule family 8 — per-query attribution propagation onto worker threads.

Under the concurrent serving plane every shared-plane counter (scan io,
shuffle, recovery, device-kernel MFU) credits the query whose thread
bumped it — but only because every spawn site *threads the attribution
through*: pool submits wrap the callable in ``observability.
run_attributed`` / ``tracing.run_attached``, and long-lived stage
threads install ``observability.attributed(...)`` / ``tracing.attach``
inside their target. One unwrapped spawn and that worker's counters
silently land on the wrong query (or nowhere) — a regression no test
notices until two queries overlap just so.

The rule: in the engine modules (executor, pipeline, serving scheduler,
distributed worker planes, read planner), every ``<pool>.submit(fn,
...)`` must pass an attribution wrapper as the callable, and every
``threading.Thread(target=g)`` whose target is a same-module def must
have ``g`` (transitively, bounded) install attribution. Targets that
cannot be resolved statically (foreign bound methods like
``server.serve_forever``) are skipped — they are infra, not query
workers. Maintenance threads that genuinely touch no plane counters
carry a pragma saying so.
"""

from __future__ import annotations

import ast
from typing import List, Set

from . import dataflow
from .dataflow import ModuleIndex
from .framework import Finding, SourceFile

#: modules whose thread spawns run query work against shared planes
SCOPE = (
    "daft_tpu/execution/executor.py",
    "daft_tpu/execution/pipeline.py",
    "daft_tpu/serving/scheduler.py",
    "daft_tpu/distributed/worker.py",
    "daft_tpu/distributed/remote_worker.py",
    "daft_tpu/io/read_planner.py",
)

#: callables that wrap attribution around a submitted function
WRAPPERS = frozenset({"run_attributed", "run_attached"})

#: calls whose presence in a thread target means it installs the
#: attribution / span scope itself
INSTALLERS = {"attributed", "attach", "run_attributed", "run_attached",
              "cancel_scope", "nested_scope"}

RULE_IDS = {
    "unattributed-worker": (
        "attribution",
        "wrap the callable in observability.run_attributed(current_"
        "attribution(), fn, ...) / tracing.run_attached, or install "
        "observability.attributed(...) inside the thread target"),
}


def _call_last(call: ast.Call) -> str:
    return dataflow._call_last_name(call)


def _is_poolish(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute) \
            or call.func.attr != "submit":
        return False
    recv = dataflow.dotted(call.func.value).lower()
    return "pool" in recv


def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if sf.path not in SCOPE:
            continue
        idx = ModuleIndex(sf.tree)
        installers: Set[str] = idx.calls_anywhere(set(INSTALLERS))
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_poolish(node):
                if not node.args:
                    continue
                fn_arg = node.args[0]
                last = ""
                if isinstance(fn_arg, (ast.Attribute, ast.Name)):
                    last = fn_arg.attr if isinstance(fn_arg,
                                                     ast.Attribute) \
                        else fn_arg.id
                if last in WRAPPERS or last in installers:
                    continue
                out.append(Finding(
                    "unattributed-worker", sf.path, node.lineno,
                    f"pool submit of {ast.unparse(fn_arg)[:40]!r} without "
                    f"an attribution wrapper — this worker's plane "
                    f"counters credit the wrong query under concurrency"))
            elif _call_last(node) == "Thread":
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                if target is None:
                    continue
                tname = None
                if isinstance(target, ast.Attribute):
                    base = dataflow.dotted(target.value)
                    if base == "self":
                        tname = target.attr
                elif isinstance(target, ast.Name):
                    tname = target.id
                if tname is None:
                    continue  # foreign bound method: infra, not a worker
                if idx.defs.get(tname) is None:
                    continue
                if tname in installers:
                    continue
                out.append(Finding(
                    "unattributed-worker", sf.path, node.lineno,
                    f"thread target {tname}() never installs "
                    f"observability.attributed / tracing.attach — query "
                    f"work on this thread is invisible to per-query "
                    f"stats isolation"))
    return out
