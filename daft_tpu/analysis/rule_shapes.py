"""Rule family — shape stability & retrace discipline (round 16).

The device tier loses to the host on hot TPC-H not because the kernels
are slow but because the *dispatch surroundings* re-trace and re-compile
(ROADMAP item 1: 23.3s device vs 2.2s host on hot q1, 55s warm-up).
This family makes "this dispatch is shape-stable" a proven invariant:

- ``dispatch-site-unregistered`` / ``dispatch-site-stale`` — every
  ``jax.jit`` / ``pallas_call`` construction site in the engine tree is
  declared ONCE in ``analysis/dispatch_registry.py`` with its trace
  signature and retrace budget; the AST scan proves the registry neither
  under- nor over-claims.
- ``shape-unbucketed`` — raw row-count-derived values (``len(batch)``,
  ``.num_rows``, ``.row_count``) must reach argument shapes and
  shape-like static args (``out_cap=``, ``capacity=``, array-constructor
  shapes) only through the sanctioned ``column.bucket_capacity``
  size-class chokepoint.  An un-bucketed row count in a shape is a fresh
  XLA program per literal row count — the recompile tax in one line.
- ``jit-not-memoized`` — a ``jax.jit(...)`` constructed inside a
  function body without a memo store (module-level cache dict, object
  attribute, or a declared-``global`` rebind) is a fresh Python callable
  per call, which can never hit jax's trace cache.  The sanctioned shape
  is ``pipeline.py``'s ``_mask_cache`` pattern; the historical first
  hit was ``parallel/exchange.py`` returning a fresh ``jax.jit(mapped)``
  per mesh exchange.

The runtime twin of this family is ``analysis/retrace_sanitizer.py``,
which charges real JAX trace events against the same registry's budgets.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import dispatch_registry
from .framework import Finding, SourceFile, call_name, dotted_name

RULE_IDS: Dict[str, Tuple[str, str]] = {
    "dispatch-site-unregistered": (
        "shapes", "declare the jit/pallas construction site in "
                  "analysis/dispatch_registry.py"),
    "dispatch-site-stale": (
        "shapes", "drop (or repoint) the registry entry — no jit/pallas "
                  "construction there anymore"),
    "shape-unbucketed": (
        "shapes", "route the row count through column.bucket_capacity "
                  "(the size-class chokepoint) before it becomes a "
                  "shape"),
    "jit-not-memoized": (
        "shapes", "memoize the jitted program in a module-level cache "
                  "(the pipeline._mask_cache pattern) keyed on its "
                  "static signature"),
}

#: modules whose shapes feed device programs — the taint rule's scope
_SHAPE_SCOPE_PREFIXES = ("daft_tpu/device/", "daft_tpu/parallel/")
_SHAPE_SCOPE_FILES = ("daft_tpu/joins.py", "daft_tpu/functions/image.py",
                      "daft_tpu/window_exec.py")

#: the sanctioned size-class chokepoints: a value that passed through one
#: of these is by construction a canonical bucket, not a raw row count
SANCTIONED_CALLS = ("bucket_capacity", "size_classes", "table_capacity",
                    "join_table_capacity")

#: shape-like keyword sinks at dispatch/kernel calls
SHAPE_KWARGS = {"out_cap", "out_capacity", "capacity",
                "out_capacity_per_shard", "table_cap"}

#: DEVICE array constructors whose first positional argument is a shape
#: — host-side numpy allocations are free to be row-sized (they never
#: become an XLA program shape; the encode path pads them)
_ARRAY_CTORS = {"jnp.zeros", "jnp.full", "jnp.empty", "jnp.ones",
                "jnp.arange", "jax.numpy.zeros", "jax.numpy.full",
                "jax.numpy.empty", "jax.numpy.ones", "jax.numpy.arange"}

#: row-count attribute seeds
_ROWCOUNT_ATTRS = {"num_rows", "row_count"}


# ------------------------------------------------------------ site scan

def _is_jit_ctor(node: ast.Call) -> bool:
    """``jax.jit(...)`` or ``partial(jax.jit, ...)`` construction."""
    name = call_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if name.endswith("partial") and node.args \
            and dotted_name(node.args[0]) in ("jax.jit", "jit"):
        return True
    return False


def _is_pallas_ctor(node: ast.Call) -> bool:
    return call_name(node).endswith("pallas_call")


class _SiteCollector(ast.NodeVisitor):
    """(enclosing function name | MODULE_LEVEL, lineno, kind) for every
    jit/pallas construction in a module."""

    def __init__(self):
        self.sites: List[Tuple[str, int, str]] = []
        self._stack: List[str] = []

    def _enclosing(self) -> str:
        return self._stack[-1] if self._stack \
            else dispatch_registry.MODULE_LEVEL

    def visit_FunctionDef(self, node):
        # a decorator executes in the scope DECLARING the function —
        # record it (and any jit/pallas call inside it) before pushing
        for dec in node.decorator_list:
            if dotted_name(dec) in ("jax.jit", "jit"):
                self.sites.append((self._enclosing(), node.lineno, "jit"))
            else:
                self.visit(dec)
        self._stack.append(node.name)
        for child in node.body:
            self.visit(child)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        if _is_jit_ctor(node):
            self.sites.append((self._enclosing(), node.lineno, "jit"))
        elif _is_pallas_ctor(node):
            self.sites.append((self._enclosing(), node.lineno, "pallas"))
        self.generic_visit(node)


def _collect_sites(sf: SourceFile) -> List[Tuple[str, int, str]]:
    c = _SiteCollector()
    c.visit(sf.tree)
    # a partial(jax.jit, …)(impl) wrap reports the inner partial call
    # too; dedupe per (func, line)
    seen, out = set(), []
    for fn, ln, kind in c.sites:
        if (fn, ln) not in seen:
            seen.add((fn, ln))
            out.append((fn, ln, kind))
    return out


def check_registry(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    scanned: Dict[str, List[Tuple[str, int, str]]] = {}
    for sf in sources:
        if not sf.path.startswith("daft_tpu/") \
                or sf.path.startswith("daft_tpu/analysis/"):
            continue
        sites = _collect_sites(sf)
        scanned[sf.path] = sites
        allowed = dispatch_registry.MODULE_FUNCS.get(sf.path, set())
        for fn, ln, kind in sites:
            if fn not in allowed:
                out.append(Finding(
                    "dispatch-site-unregistered", sf.path, ln,
                    f"{kind} program constructed in {fn}() but "
                    f"({sf.path}, {fn}) is not declared in "
                    f"analysis/dispatch_registry.py — every dispatch "
                    f"site needs a trace-signature contract"))
    # reverse direction: registry entries must resolve to real sites
    for site in dispatch_registry.SITES:
        if site.module not in scanned:
            continue  # partial-tree scan: can't judge staleness
        found = {fn for fn, _ln, _k in scanned[site.module]}
        for fn in site.funcs:
            if fn not in found:
                out.append(Finding(
                    "dispatch-site-stale", site.module, 1,
                    f"registry site {site.id!r} claims a jit/pallas "
                    f"construction in {fn}() but none exists — stale "
                    f"contract"))
    return out


# --------------------------------------------------------- jit memo rule

def _iter_scoped_functions(tree: ast.Module):
    """Every (FunctionDef, its own direct AST nodes) — nested defs are
    yielded separately and EXCLUDED from the parent's node set, so a
    memo decision is judged against the function that actually runs
    per call."""
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        own: List[ast.AST] = []
        stack: List[ast.AST] = list(fn.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the nested def's decorators execute in THIS scope
                own.extend(n.decorator_list)
                continue
            own.append(n)
            stack.extend(ast.iter_child_nodes(n))
        yield fn, own


def _memo_stored(fn: ast.AST, own_nodes: List[ast.AST],
                 jit_call: ast.Call) -> bool:
    """True when the jit result (directly, via its assigned name, or via
    an object constructed from it) is stored into a cache: a Subscript
    or Attribute target, or a declared-``global`` name."""
    # the statement whose value expression contains the jit call
    stmt = None
    for n in own_nodes:
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if any(x is jit_call for x in ast.walk(n)):
                stmt = n
                break
    if stmt is None:
        return False
    targets = stmt.targets if isinstance(stmt, ast.Assign) \
        else [stmt.target]
    for tgt in targets:
        if isinstance(tgt, (ast.Subscript, ast.Attribute)):
            return True
    globals_: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Global):
            globals_.update(n.names)
    tainted: Set[str] = set()
    for tgt in targets:
        for n in ast.walk(tgt):
            if isinstance(n, ast.Name):
                tainted.add(n.id)
    if tainted & globals_:
        return True
    # follow the name through later statements: a store into a
    # Subscript/Attribute (or a re-assignment that keeps the taint)
    for _ in range(4):
        grew = False
        for n in own_nodes:
            if not isinstance(n, ast.Assign):
                continue
            names = {x.id for x in ast.walk(n.value)
                     if isinstance(x, ast.Name)}
            if not names & tainted:
                continue
            for tgt in n.targets:
                if isinstance(tgt, (ast.Subscript, ast.Attribute)):
                    return True
                for x in ast.walk(tgt):
                    if isinstance(x, ast.Name) and x.id not in tainted:
                        tainted.add(x.id)
                        grew = True
            if tainted & globals_:
                return True
        if not grew:
            break
    return False


def check_jit_memo(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if not sf.path.startswith("daft_tpu/") \
                or sf.path.startswith("daft_tpu/analysis/"):
            continue
        for fn, own in _iter_scoped_functions(sf.tree):
            owner = dispatch_registry.memo_owner(sf.path, fn.name)
            if owner in ("caller", "exempt"):
                # the registry declares who holds this program's memo
                # (caller-owned cache) or that re-jitting is the point
                # (bench/warm-up harnesses timing compiles)
                continue
            for n in own:
                if isinstance(n, ast.Call) and _is_jit_ctor(n):
                    if not _memo_stored(fn, own, n):
                        out.append(Finding(
                            "jit-not-memoized", sf.path, n.lineno,
                            f"jax.jit(...) constructed inside "
                            f"{fn.name}() without a memo store — a "
                            f"fresh callable per call can never hit "
                            f"jax's trace cache (every call re-traces)"))
    return out


# ------------------------------------------------------ shape taint rule

def _contains_sanctioned(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            name = call_name(n)
            if name.split(".")[-1] in SANCTIONED_CALLS:
                return True
    return False


def _is_seed(expr: ast.AST) -> bool:
    """A raw row-count expression: ``len(...)`` or ``.num_rows`` /
    ``.row_count`` attribute reads."""
    if isinstance(expr, ast.Call) and call_name(expr) == "len":
        return True
    if isinstance(expr, ast.Attribute) and expr.attr in _ROWCOUNT_ATTRS:
        return True
    return False


#: calls a row count flows THROUGH unchanged; every other call's result
#: is a fresh value the taint does not survive (a kernel returning group
#: blocks from a tainted plane is not itself a raw row count)
_PASSTHROUGH_CALLS = {"min", "max", "int", "round", "abs", "float", "len"}


def _taint_signal(expr: ast.AST, tainted: Set[str]) -> bool:
    """True when ``expr`` evaluates to a raw row count: it contains a
    seed or a tainted name OUTSIDE non-passthrough call arguments, and
    no sanctioned size-class chokepoint on the way."""
    if _contains_sanctioned(expr):
        return False
    stack = [expr]
    while stack:
        n = stack.pop()
        if _is_seed(n):
            return True
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
        if isinstance(n, ast.Call):
            # len(x) seeds via _is_seed above; min/max/etc. pass the
            # count through; other calls LAUNDER the taint (their result
            # is not a row count even when their arguments were)
            if call_name(n).split(".")[-1] in _PASSTHROUGH_CALLS:
                stack.extend(n.args)
                stack.extend(kw.value for kw in n.keywords)
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


def _direct_nodes(fn: ast.AST):
    """(own AST nodes, nested function defs) — a nested def is its own
    scope; judging its sinks against the parent's taint conflates two
    bindings of the same name (the exchange closures rebind ``fk``)."""
    own: List[ast.AST] = []
    nested: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested.append(n)
            continue
        own.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return own, nested


def _local_bindings(fn: ast.AST, own: List[ast.AST]) -> Set[str]:
    bound: Set[str] = set()
    a = fn.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        bound.add(arg.arg)
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    for n in own:
        if isinstance(n, ast.Assign):
            for tgt in n.targets:
                for x in ast.walk(tgt):
                    if isinstance(x, ast.Name):
                        bound.add(x.id)
        elif isinstance(n, (ast.For, ast.comprehension)):
            for x in ast.walk(n.target):
                if isinstance(x, ast.Name):
                    bound.add(x.id)
    return bound


def _tainted_names(fn: ast.AST, own: List[ast.AST],
                   inherited: Set[str]) -> Set[str]:
    """Names carrying a raw (un-bucketed) row count in THIS scope, by
    fixpoint over its direct assignments.  Starts from the closure's
    taint minus locally re-bound names; an assignment whose value passes
    through a sanctioned size-class chokepoint stays clean."""
    tainted = set(inherited) - _local_bindings(fn, own)
    for _ in range(6):
        grew = False
        for n in own:
            if not isinstance(n, ast.Assign):
                continue
            if _taint_signal(n.value, tainted):
                for tgt in n.targets:
                    for x in ast.walk(tgt):
                        if isinstance(x, ast.Name) \
                                and x.id not in tainted:
                            tainted.add(x.id)
                            grew = True
        if not grew:
            break
    return tainted


def _expr_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    return _taint_signal(expr, tainted)


def check_shape_taint(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        in_scope = sf.path in _SHAPE_SCOPE_FILES or any(
            sf.path.startswith(p) for p in _SHAPE_SCOPE_PREFIXES)
        if not in_scope:
            continue
        top = [n for n in sf.tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        top.extend(m for c in sf.tree.body if isinstance(c, ast.ClassDef)
                   for m in c.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)))
        stack = [(fn, set()) for fn in top]
        while stack:
            fn, inherited = stack.pop()
            own, nested = _direct_nodes(fn)
            tainted = _tainted_names(fn, own, inherited)
            for n in own:
                if not isinstance(n, ast.Call):
                    continue
                for kw in n.keywords:
                    if kw.arg in SHAPE_KWARGS \
                            and _expr_tainted(kw.value, tainted):
                        out.append(Finding(
                            "shape-unbucketed", sf.path, n.lineno,
                            f"raw row-count-derived value reaches "
                            f"{kw.arg}= at {call_name(n) or 'a call'} — "
                            f"a fresh XLA program per literal row "
                            f"count; bucket it first"))
                if call_name(n) in _ARRAY_CTORS and n.args \
                        and _expr_tainted(n.args[0], tainted):
                    out.append(Finding(
                        "shape-unbucketed", sf.path, n.lineno,
                        f"raw row-count-derived shape at "
                        f"{call_name(n)}() — pad to a size-class "
                        f"bucket so literal row counts share one "
                        f"program"))
            stack.extend((nf, tainted) for nf in nested)
    return out


def check(sources: List[SourceFile]) -> List[Finding]:
    out = check_registry(sources)
    out.extend(check_jit_memo(sources))
    out.extend(check_shape_taint(sources))
    return out
