"""The engine's declarative jit/Pallas dispatch-site registry.

Every place the engine *constructs* a ``jax.jit`` program or a
``pallas_call`` is declared here exactly once: which module, which
enclosing function, what the trace signature is allowed to depend on,
and how many traces one signature may legitimately cost.  Two consumers
keep the table honest:

- ``rule_shapes`` (static): any jit/pallas construction site in the
  engine tree that is NOT declared here is a finding
  (``dispatch-site-unregistered``), and any declared site that no longer
  exists is one too (``dispatch-site-stale``) — the registry can neither
  under- nor over-claim.
- ``retrace_sanitizer`` (runtime): dispatch chokepoints enter a
  ``dispatch_scope(site_id, signature_key)`` around the jitted call;
  JAX trace events that fire inside the scope are charged against the
  site's declared per-signature budget, and exceeding it fails the test
  session (``DAFT_TPU_SANITIZE=1`` + ``DAFT_TPU_SANITIZE_RETRACE``).

The budget contract is the shape-discipline invariant of ROADMAP item 1
stated declaratively: *a dispatch site re-traces only when its declared
signature changes* — e.g. the fused fragment traces once per
(program, capacity class, out-cap bucket, strategy, donation,
scalar-plane shapes), never per raw row count.  Row counts must reach
shapes only through the ``column.bucket_capacity`` size-class
chokepoint, which ``rule_shapes``' taint rule enforces statically.

This module must stay import-light (dataclasses only): the lint rules
AND the runtime sanitizer both import it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

#: kwarg-ish module qualifier for sites living at module level
MODULE_LEVEL = "<module>"


@dataclasses.dataclass(frozen=True)
class DispatchSite:
    id: str          # stable site id ("fragment.packed", …)
    module: str      # repo-relative path of the constructing module
    funcs: Tuple[str, ...]  # enclosing function names of the jit/pallas
    # construction site(s); MODULE_LEVEL for top-level decorators/wraps
    signature: str   # what the trace cache key may depend on (doc + the
    # contract the runtime scope key must spell)
    budget: str      # human budget contract for the docs table
    traces_per_key: int = 1   # max traces one signature key may cost
    exempt: bool = False      # bench/warm-up sites that TIME compiles on
    # purpose: counted, never budget-enforced
    memo: str = "local"       # who owns the program memo: "local" (the
    # constructing function must store it — rule_shapes enforces the
    # _mask_cache pattern) or "caller" (the construction is returned
    # and the CALLERS hold the cache, e.g. compile_projection →
    # runtime._projection_cache / fragment._fused_cache)


def _s(id_, module, funcs, signature, budget, traces_per_key=1,
       exempt=False, memo="local"):
    return DispatchSite(id_, module, tuple(funcs), signature, budget,
                        traces_per_key, exempt, memo)


SITES: Tuple[DispatchSite, ...] = (
    # ------------------------------------------------------ device tier
    _s("kernels.argsort", "daft_tpu/device/kernels.py",
       (MODULE_LEVEL,),
       "(n_keys, key dtypes, capacity class, descending, nulls_first)",
       "one trace per key-plane layout x size class"),
    _s("kernels.grouped_agg", "daft_tpu/device/kernels.py",
       (MODULE_LEVEL,),
       "(n_keys, n_vals, dtypes, ops, capacity class, out_cap bucket)",
       "one trace per agg layout x size class x out-cap bucket"),
    _s("kernels.join_fused", "daft_tpu/device/kernels.py",
       ("join_fused_kernel",),
       "(capacity classes, out_capacity bucket, donate)",
       "one trace per build/probe size class x out bucket"),
    _s("pallas.hash_agg", "daft_tpu/device/pallas_kernels.py",
       ("hash_grouped_agg_kernel", "_agg_build_call"),
       "(n_keys, n_vals, ops, out_cap, table_cap, interpret, block)",
       "one trace per hash-agg program shape (memoized in "
       "_hash_agg_jit_cache)"),
    _s("pallas.hash_join", "daft_tpu/device/pallas_kernels.py",
       ("hash_join_kernel", "_join_build_call", "_join_probe_call"),
       "(donate, out_capacity, interpret, block sizes)",
       "one trace per hash-join program shape (memoized in "
       "_hash_join_jit_cache)"),
    _s("fragment.packed", "daft_tpu/device/fragment.py",
       ("get_fused_agg",),
       "(program, capacity class, out_cap bucket, strategy, donate, "
       "scalar-plane shapes)",
       "one trace per (schema, size-class, strategy), not per row count"),
    _s("fragment.donate", "daft_tpu/device/fragment.py",
       ("donate_fn",),
       "(program, capacity class, out_cap bucket, strategy, "
       "scalar-plane shapes)",
       "donating twin of fragment.packed; same signature contract"),
    _s("region.chain", "daft_tpu/device/fragment.py",
       ("get_fused_region",),
       "(program, capacity class, out-width bucket, scalar-plane shapes)",
       "round 21 fused chain region: one trace per (region program, "
       "size class, transfer-width bucket), never per row count"),
    _s("region.topk", "daft_tpu/device/fragment.py",
       ("get_fused_region",),
       "(program, capacity class, k bucket, scalar-plane shapes)",
       "round 21 fused top-k region: one trace per (region program, "
       "size class, k bucket)"),
    _s("region.join_agg", "daft_tpu/device/fragment.py",
       ("get_fused_join_agg",),
       "(program, probe capacity class, build capacity class, pair-width "
       "bucket W, out_cap bucket, scalar-plane shapes)",
       "round 21 fused join_agg region: one trace per (region program, "
       "probe/build size classes, W bucket, group bucket)"),
    _s("region.build", "daft_tpu/device/fragment.py",
       ("prepare_region_build",),
       "(build capacity class,)",
       "join_agg build-side key sort: one trace per build size class, "
       "reused by every probe morsel of every query"),
    _s("pipeline.mask", "daft_tpu/device/pipeline.py",
       ("_masked_validity",),
       "(validity-plane capacity class,)",
       "one trace per capacity class (live count rides as a traced "
       "scalar, never a literal)"),
    _s("compiler.projection", "daft_tpu/device/compiler.py",
       ("compile_projection",),
       "(expression keys, schema, capacity class, scalar-plane shapes)",
       "one trace per compiled projection x size class (memoized by "
       "callers: runtime._projection_cache / fragment._fused_cache)",
       memo="caller"),
    _s("mfu.bench", "daft_tpu/device/mfu.py",
       ("measure_grouped_agg", "measure_hash_grouped_agg",
        "measure_join", "measure_hash_join", "measure_argsort"),
       "(bench shape grid)",
       "roofline harness: re-times compiles on purpose", exempt=True),
    # warmup.aot constructs no programs of its own — it .lower()s the
    # sites above over the size-class grid — so it claims no
    # construction functions, only a scope id the sanitizer exempts
    _s("warmup.aot", "daft_tpu/device/warmup.py", (),
       "(size-class x strategy warm-up grid)",
       "AOT warm-up: every lower().compile() here is deliberate",
       exempt=True),
    # ----------------------------------------------------- parallel tier
    _s("exchange.shard_map", "daft_tpu/parallel/exchange.py",
       ("shard_map_compat",),
       "(mapped fn code + closure, mesh, in_specs, out_specs, "
       "check_vma, input plane shapes)",
       "one trace per collective program x shard block shape (memoized "
       "in _program_cache)"),
    # ------------------------------------------------------- functions
    _s("image.resize", "daft_tpu/functions/image.py",
       ("_get_resize_jit",),
       "(batch shape, target h/w, clip bounds, out dtype)",
       "one trace per image batch shape x resize spec"),
)

BY_ID: Dict[str, DispatchSite] = {s.id: s for s in SITES}

#: module → allowed enclosing-function names (rule_shapes' coverage map)
MODULE_FUNCS: Dict[str, set] = {}
for _site in SITES:
    MODULE_FUNCS.setdefault(_site.module, set()).update(_site.funcs)


def site(site_id: str) -> Optional[DispatchSite]:
    return BY_ID.get(site_id)


def memo_owner(module: str, func: str) -> Optional[str]:
    """``"local"``/``"caller"`` for a declared (module, enclosing-func)
    construction site, ``"exempt"`` for bench/warm-up sites, or None
    when the site is undeclared (rule_shapes flags those separately)."""
    for s in SITES:
        if s.module == module and func in s.funcs:
            return "exempt" if s.exempt else s.memo
    return None


def budget_for(site_id: str) -> Optional[int]:
    """Max traces per signature key, or None when the site is exempt
    (bench/warm-up) or unknown (unscoped engine traces are counted but
    never budget-enforced)."""
    s = BY_ID.get(site_id)
    if s is None or s.exempt:
        return None
    return s.traces_per_key
