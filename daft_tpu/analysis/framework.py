"""daft-lint core: findings, pragmas, source walking, baseline.

The linter is engine-aware: each rule family encodes a real invariant of
THIS codebase (knob registry discipline, chaos-replay determinism, lock
discipline, jit hygiene) rather than generic style. Rules operate on
parsed ASTs of the repo tree and return :class:`Finding`\\ s.

Suppression is explicit and justified::

    something_flagged()  # daft-lint: allow(<rule-id>) -- why it is safe

The pragma may sit on the finding's line or the line directly above it.
An ``allow(...)`` without a ``-- reason`` string is itself a finding
(``pragma-missing-reason``) — grandfathering without a written
justification is exactly the drift this tool exists to stop.

A committed baseline (``analysis/baseline.json``) can grandfather known
findings; this repo's baseline is **empty** and must stay empty — fix or
pragma-justify, don't baseline.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

#: the canonical scan set: the engine tree, the test tree (knob-usage
#: round-trip), and the bench driver
DEFAULT_SUBDIRS = ("daft_tpu", "tests", "bench.py")

#: chaos-replay-critical modules: any nondeterminism here can break the
#: bit-identical replay contract of the resilience plane (PR 2)
REPLAY_CRITICAL = (
    "daft_tpu/distributed/resilience.py",
    "daft_tpu/distributed/shuffle_service.py",
    "daft_tpu/distributed/worker.py",
    "daft_tpu/distributed/remote_worker.py",
    "daft_tpu/distributed/scheduler.py",
    "daft_tpu/io/read_planner.py",
    "daft_tpu/execution/executor.py",
)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str      # repo-relative, forward slashes
    line: int
    message: str
    family: str = ""   # rule family (filled from the registry)
    hint: str = ""     # one-line fix hint (filled from the registry)

    def key(self) -> str:
        return f"{self.path}:{self.rule}:{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def known_rules() -> Dict[str, Tuple[str, str]]:
    """rule id → (family, one-line fix hint) for EVERY rule the linter
    can emit — the registry behind pragma validation (`allow(<id>)` must
    name a live rule), `--rule` filtering, and the JSON `family`/`hint`
    fields. New rule modules contribute via their ``RULE_IDS`` dict."""
    from . import (rule_attribution, rule_cancellation, rule_donation,
                   rule_plans, rule_resources, rule_shapes)
    out: Dict[str, Tuple[str, str]] = {
        # r10 families, single-sourced here (their modules predate the
        # registry); hints stay one line by policy
        "knob-unregistered": (
            "knobs", "declare the knob in analysis/knobs.py"),
        "knob-direct-read": (
            "knobs", "read via knobs.env_* accessors, not os.environ"),
        "knob-type-mismatch": (
            "knobs", "use the accessor matching the registered type"),
        "knob-unused": (
            "knobs", "drop the stale registry entry or use the knob"),
        "knob-config-drift": (
            "knobs", "sync the registry's config_field with "
                     "ExecutionConfig"),
        "knob-doc-drift": (
            "knobs", "regenerate: python -m daft_tpu.analysis "
                     "--knob-docs --write"),
        "unseeded-random": (
            "determinism", "use a seeded instance keyed on a stable "
                           "identity"),
        "wallclock-decision": (
            "determinism", "inject a clock (RetryPolicy pattern) instead "
                           "of reading time in a decision"),
        "unordered-pool-iteration": (
            "determinism", "iterate futures in submit order, not "
                           "completion order"),
        "blocking-under-lock": (
            "locks", "move the blocking call outside the `with <lock>:` "
                     "scope"),
        "unguarded-global-mutation": (
            "locks", "rebind module state under its lock "
                     "(check-then-set races)"),
        "host-effect-in-jit": (
            "jit", "hoist the host effect out of the traced function"),
        "np-in-jit": (
            "jit", "use jnp on traced values; np only on static "
                   "metadata"),
        "dispatch-contract": (
            "jit", "restore the proven jaxpr shape (operand count / "
                   "kernel structure)"),
        "pragma-missing-reason": (
            "pragma", "append `-- <reason>` to the allow(...) pragma"),
        "pragma-unknown-rule": (
            "pragma", "name a live rule id (see --stats for the list) "
                      "or drop the stale pragma"),
    }
    out.update(rule_resources.RULE_IDS)
    out.update(rule_donation.RULE_IDS)
    out.update(rule_cancellation.RULE_IDS)
    out.update(rule_attribution.RULE_IDS)
    out.update(rule_shapes.RULE_IDS)
    out.update(rule_plans.RULE_IDS)
    return out


_PRAGMA_RE = re.compile(
    r"#\s*daft-lint:\s*allow\(([\w\-,\s]+)\)(?:\s*--\s*(.*\S))?")


@dataclasses.dataclass
class SourceFile:
    """One parsed source file plus its pragma index."""
    path: str                # repo-relative
    abspath: str
    text: str
    tree: ast.Module
    lines: List[str]

    @property
    def pragmas(self) -> Dict[int, Tuple[List[str], Optional[str]]]:
        cached = getattr(self, "_pragmas", None)
        if cached is None:
            cached = {}
            for i, line in enumerate(self.lines, start=1):
                m = _PRAGMA_RE.search(line)
                if m:
                    rules = [r.strip() for r in m.group(1).split(",")
                             if r.strip()]
                    cached[i] = (rules, m.group(2))
            self._pragmas = cached
        return cached

    def allowed(self, rule: str, line: int) -> bool:
        """True when the line itself — or the contiguous comment block
        directly above it — carries a pragma for ``rule`` WITH a
        justification (multi-line reasons are encouraged)."""
        entry = self.pragmas.get(line)
        if entry and rule in entry[0] and entry[1]:
            return True
        ln = line - 1
        while 1 <= ln <= len(self.lines) \
                and self.lines[ln - 1].lstrip().startswith("#"):
            entry = self.pragmas.get(ln)
            if entry and rule in entry[0] and entry[1]:
                return True
            ln -= 1
        return False

    def pragma_findings(self) -> List[Finding]:
        """Reason-less pragmas are findings themselves."""
        out = []
        for ln, (rules, reason) in self.pragmas.items():
            if not reason:
                out.append(Finding(
                    "pragma-missing-reason", self.path, ln,
                    f"daft-lint pragma for {', '.join(rules)} has no "
                    f"`-- <reason>` justification"))
        return out


def load_source(abspath: str, root: str) -> Optional[SourceFile]:
    try:
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
        tree = ast.parse(text, filename=abspath)
    except (OSError, SyntaxError):
        return None
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    return SourceFile(rel, abspath, text, tree, text.splitlines())


def walk_sources(root: str,
                 subdirs: Iterable[str] = ("daft_tpu",)) -> List[SourceFile]:
    """Parse every ``*.py`` under ``root/<subdir>`` (skipping caches)."""
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base):
            sf = load_source(base, root)
            if sf:
                out.append(sf)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    sf = load_source(os.path.join(dirpath, fn), root)
                    if sf:
                        out.append(sf)
    return out


def repo_root() -> str:
    """The repo root containing this daft_tpu checkout."""
    here = os.path.dirname(os.path.abspath(__file__))   # …/daft_tpu/analysis
    return os.path.dirname(os.path.dirname(here))


BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def load_baseline(path: Optional[str] = None) -> List[str]:
    path = path or BASELINE_PATH
    try:
        with open(path) as f:
            data = json.load(f)
        return list(data.get("findings", []))
    except (OSError, ValueError):
        return []


def apply_baseline(findings: List[Finding],
                   baseline: Iterable[str]) -> List[Finding]:
    grandfathered = set(baseline)
    return [f for f in findings if f.key() not in grandfathered]


def pragma_rule_findings(sources: List["SourceFile"],
                         rules: Dict[str, Tuple[str, str]]
                         ) -> List[Finding]:
    """A pragma naming a removed/renamed rule id is itself a finding —
    stale suppressions silently stop suppressing the day a rule is
    renamed, so they must not linger."""
    out: List[Finding] = []
    for sf in sources:
        for ln, (names, _reason) in sf.pragmas.items():
            for name in names:
                if name not in rules:
                    out.append(Finding(
                        "pragma-unknown-rule", sf.path, ln,
                        f"pragma allows {name!r}, which is not a rule "
                        f"this linter has — stale suppression"))
    return out


def run_analysis(root: Optional[str] = None,
                 subdirs: Iterable[str] = DEFAULT_SUBDIRS,
                 contracts: bool = True,
                 readme: bool = True,
                 baseline: Optional[List[str]] = None,
                 stats: Optional[Dict] = None) -> List[Finding]:
    """Run every rule family over the tree; returns non-baselined,
    non-pragma'd findings sorted by location. Pass a dict as ``stats``
    to collect the burn-down summary (files scanned, functions
    analyzed, per-family finding counts)."""
    from . import (rule_attribution, rule_cancellation, rule_determinism,
                   rule_donation, rule_jit, rule_knobs, rule_locks,
                   rule_plans, rule_resources, rule_shapes)

    root = root or repo_root()
    sources = walk_sources(root, subdirs)
    rules = known_rules()
    findings: List[Finding] = []
    for sf in sources:
        findings.extend(sf.pragma_findings())
    findings.extend(pragma_rule_findings(sources, rules))

    findings.extend(rule_knobs.check(sources))
    if readme:
        findings.extend(rule_knobs.check_readme(root))
    findings.extend(rule_determinism.check(sources))
    findings.extend(rule_locks.check(sources))
    findings.extend(rule_jit.check(sources))
    if contracts:
        findings.extend(rule_jit.check_dispatch_contracts())
    findings.extend(rule_resources.check(sources))
    findings.extend(rule_donation.check(sources))
    findings.extend(rule_cancellation.check(sources))
    findings.extend(rule_attribution.check(sources))
    findings.extend(rule_shapes.check(sources))
    findings.extend(rule_plans.check(sources))
    if contracts:
        findings.extend(rule_plans.check_fusion_contracts())

    # pragma suppression (a pragma never suppresses the pragma rules)
    by_path = {sf.path: sf for sf in sources}
    kept = []
    for f in findings:
        sf = by_path.get(f.path)
        if (not f.rule.startswith("pragma-") and sf is not None
                and sf.allowed(f.rule, f.line)):
            continue
        kept.append(f)

    kept = apply_baseline(kept, load_baseline() if baseline is None
                          else baseline)
    for f in kept:
        fam, hint = rules.get(f.rule, ("", ""))
        f.family = f.family or fam
        f.hint = f.hint or hint
    kept.sort(key=lambda f: (f.path, f.line, f.rule))

    if stats is not None:
        from . import dataflow
        by_family: Dict[str, int] = {}
        for f in kept:
            by_family[f.family or "?"] = by_family.get(
                f.family or "?", 0) + 1
        stats.update({
            "files_scanned": len(sources),
            "functions_analyzed": sum(
                len(list(dataflow.iter_functions(sf.tree)))
                for sf in sources),
            "rules": sorted(rules),
            "findings_by_family": by_family,
        })
    return kept


# ------------------------------------------------------------- ast utils

def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, else ''."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    return dotted_name(node.func)
