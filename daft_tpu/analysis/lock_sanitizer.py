"""Runtime lock-order sanitizer (opt-in: ``DAFT_TPU_SANITIZE=1``).

Static analysis can prove a blocking call sits under a lock, but not
that lock A is ever taken while lock B is held in one thread and the
inverse in another — the classic latent deadlock that only fires under
production interleavings. This sanitizer proves it at test time:

- ``enable()`` patches the ``threading.Lock``/``threading.RLock``
  factories so every lock *created by engine code* (creation frame
  inside ``daft_tpu/``) is wrapped in a tracking proxy. Foreign locks
  (jax, pyarrow, stdlib machinery) pass through untouched — zero noise,
  bounded overhead.
- Each tracked lock is keyed by its **allocation site** (file:line) —
  stable across lock instances, so per-object locks (one per operator,
  one per cache) aggregate into one graph node and cross-query cycles
  are visible.
- Every acquisition while other tracked locks are held adds
  ``held-site → acquired-site`` edges to a global lock-order graph;
  cycle detection runs on edge insert. A cycle means two code paths
  disagree about acquisition order: a potential deadlock, reported with
  both sites.
- Contended acquisitions (the non-blocking fast-path probe fails) and
  ``time.sleep`` while holding a tracked lock (the runtime twin of the
  static ``blocking-under-lock`` rule) are counted.

``tests/conftest.py`` enables this for the whole suite under
``DAFT_TPU_SANITIZE=1`` and fails the session on any cycle; per-query
deltas land in ``explain(analyze=True)`` / the dashboard via
``observability.RuntimeStatsContext``.

The :class:`LockOrderSanitizer` state is instanceable so tests can
exercise cycle detection in isolation without polluting the global
session graph.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF = os.path.abspath(__file__)


class LockOrderSanitizer:
    """A lock-order graph + counters. One global instance backs the
    ``DAFT_TPU_SANITIZE=1`` session; tests may build their own."""

    def __init__(self):
        self._meta = threading.Lock()   # created pre-patch: never tracked
        self._edges: Dict[str, Set[str]] = {}
        self._edge_witness: Dict[Tuple[str, str], str] = {}
        self._sites: Set[str] = set()
        self._cycles: List[str] = []
        self._cycle_keys: Set[Tuple[str, str]] = set()
        self._held = threading.local()
        self.acquisitions = 0
        self.contended = 0
        self.blocking_while_held = 0
        self._blocking_sites: Set[str] = set()

    # ---- per-thread held stack --------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = []
            self._held.stack = st
        return st

    def held_sites(self) -> List[str]:
        return list(self._stack())

    # ---- graph ------------------------------------------------------
    def note_acquire(self, site: str, contended: bool) -> None:
        stack = self._stack()
        with self._meta:
            self.acquisitions += 1
            if contended:
                self.contended += 1
            self._sites.add(site)
            for held in stack:
                if held != site:
                    self._add_edge(held, site)
        stack.append(site)

    def note_release(self, site: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                break

    def note_blocking(self, what: str) -> None:
        stack = self._stack()
        if not stack:
            return
        with self._meta:
            self.blocking_while_held += 1
            self._blocking_sites.add(f"{what} while holding {stack[-1]}")

    def _add_edge(self, a: str, b: str) -> None:
        # caller holds self._meta
        succ = self._edges.setdefault(a, set())
        if b in succ:
            return
        succ.add(b)
        self._edge_witness[(a, b)] = \
            f"thread {threading.current_thread().name}"
        path = self._find_path(b, a)
        if path is not None:
            key = (min(a, b), max(a, b))
            if key not in self._cycle_keys:
                self._cycle_keys.add(key)
                self._cycles.append(" -> ".join([a, b] + path[1:]))

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src→dst through the edge set (caller holds _meta)."""
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # ---- reporting --------------------------------------------------
    def summary(self) -> dict:
        with self._meta:
            return {
                "locks": len(self._sites),
                "edges": sum(len(s) for s in self._edges.values()),
                "cycles": list(self._cycles),
                "acquisitions": self.acquisitions,
                "contended": self.contended,
                "blocking_while_held": self.blocking_while_held,
                "blocking_sites": sorted(self._blocking_sites),
            }

    def report(self) -> str:
        s = self.summary()
        lines = [
            f"lock-order sanitizer: {s['locks']} lock sites, "
            f"{s['edges']} order edges, {s['acquisitions']} acquisitions "
            f"({s['contended']} contended)",
        ]
        if s["cycles"]:
            lines.append(f"POTENTIAL DEADLOCKS ({len(s['cycles'])} "
                         f"acquisition-order cycles):")
            lines.extend(f"  {c}" for c in s["cycles"])
        else:
            lines.append("no acquisition-order cycles")
        if s["blocking_while_held"]:
            lines.append(f"blocking-while-held events: "
                         f"{s['blocking_while_held']}")
            lines.extend(f"  {b}" for b in s["blocking_sites"])
        return "\n".join(lines)

    # ---- wrapping ---------------------------------------------------
    def track(self, real_lock, site: str):
        """Wrap an existing lock object for this sanitizer instance."""
        return _TrackedLock(real_lock, site, self)


class _TrackedLock:
    """Proxy recording acquisition order. Forwards everything else to
    the real lock — EXCEPT the Condition fast-path internals
    (``_release_save`` etc.), which must fall back to plain
    acquire/release through the proxy so bookkeeping stays truthful."""

    __slots__ = ("_lock", "_site", "_san", "_depth")
    _CONDITION_INTERNALS = ("_release_save", "_acquire_restore", "_is_owned")

    def __init__(self, real_lock, site: str, san: LockOrderSanitizer):
        self._lock = real_lock
        self._site = site
        self._san = san
        self._depth = 0     # reentrant depth (RLock); benign race per-lock

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not blocking:
            got = self._lock.acquire(False)
            if got:
                self._san.note_acquire(self._site, contended=False)
                self._depth += 1
            return got
        # probe first so contention is observable without timing
        if self._lock.acquire(False):
            self._san.note_acquire(self._site, contended=False)
            self._depth += 1
            return True
        self._san.note_acquire(self._site, contended=True)
        try:
            got = self._lock.acquire(True, timeout) if timeout != -1 \
                else self._lock.acquire(True)
        except BaseException:
            # e.g. KeyboardInterrupt delivered mid-acquire: the site was
            # optimistically pushed — pop it or every later acquisition
            # on this thread records false held→acquired edges
            self._san.note_release(self._site)
            raise
        if not got:
            self._san.note_release(self._site)
        else:
            self._depth += 1
        return got

    def release(self):
        self._depth = max(self._depth - 1, 0)
        self._lock.release()
        self._san.note_release(self._site)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    def __getattr__(self, name):
        if name in _TrackedLock._CONDITION_INTERNALS:
            # force Condition onto plain acquire()/release() via the proxy
            raise AttributeError(name)
        return getattr(self._lock, name)

    def __repr__(self):
        return f"<tracked {self._lock!r} from {self._site}>"


# ----------------------------------------------------------- global state

_global = LockOrderSanitizer()
_enabled = False
_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_sleep = time.sleep


def _creation_site() -> Optional[str]:
    """file:line of the engine frame creating the lock, or None when the
    creator is foreign code (jax/pyarrow/stdlib) — those stay untracked."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _SELF and not fn.startswith("<"):
            if os.path.abspath(fn).startswith(_PKG_ROOT + os.sep):
                rel = os.path.relpath(os.path.abspath(fn),
                                      os.path.dirname(_PKG_ROOT))
                return f"{rel.replace(os.sep, '/')}:{f.f_lineno}"
            return None
        f = f.f_back
    return None


def _make_lock():
    real = _real_lock()
    site = _creation_site()
    if site is None:
        return real
    return _global.track(real, site)


def _make_rlock():
    real = _real_rlock()
    site = _creation_site()
    if site is None:
        return real
    return _global.track(real, site)


def _sleep_watched(secs):
    _global.note_blocking(f"time.sleep({secs})")
    return _real_sleep(secs)


def enabled_by_env() -> bool:
    from . import knobs
    return bool(knobs.env_bool("DAFT_TPU_SANITIZE"))


def enable() -> None:
    """Patch the lock factories + time.sleep. Idempotent. Engine locks
    created BEFORE enable() stay untracked — call as early as possible
    (tests/conftest.py enables before importing daft_tpu)."""
    global _enabled
    if _enabled:
        return
    # daft-lint: allow(unguarded-global-mutation) -- single-threaded
    # bootstrap: enable() runs in conftest/CLI before any engine thread
    _enabled = True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    time.sleep = _sleep_watched


def disable() -> None:
    global _enabled
    if not _enabled:
        return
    # daft-lint: allow(unguarded-global-mutation) -- mirror of enable():
    # teardown runs on the single main thread at session end
    _enabled = False
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    time.sleep = _real_sleep


def is_enabled() -> bool:
    return _enabled


def sanitizer() -> LockOrderSanitizer:
    return _global


def summary() -> dict:
    return _global.summary()


def report() -> str:
    return _global.report()


# -------------------------------------------- observability integration

def counters_snapshot() -> Dict[str, float]:
    """Monotonic counters for per-query deltas (observability pattern:
    snapshot at query start, diff at finish)."""
    if not _enabled:
        return {}
    s = _global.summary()
    return {"acquisitions": s["acquisitions"],
            "contended": s["contended"],
            "blocking_while_held": s["blocking_while_held"]}


def counters_delta(before: Dict[str, float],
                   after: Dict[str, float]) -> Dict[str, float]:
    out = {k: after.get(k, 0) - before.get(k, 0) for k in after}
    # graph size is a level, not a delta — report current absolutes
    if _enabled:
        s = _global.summary()
        out["graph_locks"] = s["locks"]
        out["graph_edges"] = s["edges"]
        out["graph_cycles"] = len(s["cycles"])
    return out
