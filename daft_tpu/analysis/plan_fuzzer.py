"""daft-lint differential plan fuzzer (``python -m daft_tpu.analysis
--fuzz``).

Seeded, fully deterministic: each seed expands to a random relational
program (join / filter / project / group-agg / distinct / sort / top-n)
over a nullable multi-dtype schema, which is then executed through a
matrix of engine modes and compared — bit-identical — against the
*unoptimized* reference (the raw logical plan translated and run on the
pull interpreter, no optimizer rules, no fusion, no spill planning):

- ``optimized``   — the full optimizer + default native runner
- ``fused``       — whole-region device compilation (``tpu_fusion=1``)
- ``spilled``     — forced grace/spill join planning (``tpu_spill_join=1``)
- ``replanned``   — the AQE loop + runtime replanning (``enable_aqe``,
  ``tpu_adaptive``) instead of the static plan
- ``combined``    — the distributed runner with map-side shuffle
  combine forced on (``DAFT_TPU_SHUFFLE_COMBINE=1``)

Result rows are canonicalized (row-sorted on a total normalization of
every cell) before comparison, so legal row-order differences between
modes never count as mismatches — value differences always do. Float
aggregation is restricted to order-independent reductions (min/max;
sums only over ints) so "bit-identical" is a sound oracle under
re-partitioned addition orders.

On mismatch the failing op chain is greedily minimized (drop ops while
the mismatch persists) and reported with its seed — the repro is just
``seed + ops`` because the tables regenerate deterministically.

Knobs: ``DAFT_TPU_FUZZ_SEED`` (base seed), ``DAFT_TPU_FUZZ_COUNT``
(seeds per run), both mirrored on ``ExecutionConfig``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
from typing import Dict, List, Optional, Tuple

from . import knobs

MODES = ("optimized", "fused", "spilled", "replanned", "combined")

_STRINGS = ("ant", "bee", "cat", "dog", "elk", "fox", None)


def fuzz_seed_base() -> int:
    v = knobs.env_int("DAFT_TPU_FUZZ_SEED", None)
    if v is not None:
        return int(v)
    try:
        from ..context import get_context
        return int(get_context().execution_config.tpu_fuzz_seed)
    except Exception:
        return 0


def fuzz_count() -> int:
    v = knobs.env_int("DAFT_TPU_FUZZ_COUNT", None)
    if v is not None:
        return int(v)
    try:
        from ..context import get_context
        return int(get_context().execution_config.tpu_fuzz_count)
    except Exception:
        return 50


# ------------------------------------------------------------------ data


def _gen_tables(rng: random.Random) -> Dict[str, Dict[str, list]]:
    """Two deterministic base tables with disjoint column names (so any
    join grammar is legal), every column nullable, keys low-cardinality
    (so joins and group-bys actually collide)."""
    nl = rng.randint(30, 120)
    nr = rng.randint(10, 60)
    left = {
        "id": list(range(nl)),  # unique: total-order tiebreaker
        "k": [rng.choice((None, 0, 1, 2, 3, 4, 5, 6, 7)) for _ in range(nl)],
        "v": [rng.choice((None, rng.randint(-50, 50))) for _ in range(nl)],
        "f": [rng.choice((None, round(rng.uniform(-5.0, 5.0), 3)))
              for _ in range(nl)],
        "s": [rng.choice(_STRINGS) for _ in range(nl)],
        "b": [rng.choice((None, True, False)) for _ in range(nl)],
    }
    right = {
        "rk": [rng.choice((None, 0, 1, 2, 3, 4, 5, 6, 7))
               for _ in range(nr)],
        "w": [rng.choice((None, rng.randint(0, 20))) for _ in range(nr)],
        "g": [rng.choice((None, round(rng.uniform(0.0, 9.0), 3)))
              for _ in range(nr)],
    }
    return {"left": left, "right": right}


# ------------------------------------------------------------- op algebra


def _apply_op(df, right_df, op):
    """Replay one concrete op spec onto a DataFrame. Specs are plain
    tuples (picklable, printable) so a repro is ``seed + ops``."""
    from .. import col
    kind = op[0]
    if kind == "join":
        return df.join(right_df, left_on="k", right_on="rk", how=op[1])
    if kind == "filter":
        _, name, cmp, const = op
        e = col(name)
        e = {"gt": e > const, "lt": e < const, "ge": e >= const,
             "le": e <= const, "eq": e == const}[cmp]
        return df.where(e)
    if kind == "filter_null":
        _, name, keep_null = op
        e = col(name).is_null()
        return df.where(e if keep_null else ~e)
    if kind == "project":
        _, names, computed = op
        exprs = [col(n) for n in names]
        if computed is not None:
            exprs.append((col(computed) * 2 + 1).alias(computed + "_x2"))
        return df.select(*exprs)
    if kind == "groupby":
        _, keys, aggs = op
        exprs = []
        for fn, name in aggs:
            e = col(name)
            e = {"sum": e.sum, "min": e.min, "max": e.max,
                 "count": e.count}[fn]()
            exprs.append(e.alias(f"{fn}_{name}"))
        return df.groupby(*keys).agg(*exprs)
    if kind == "distinct":
        return df.distinct()
    if kind == "sort":
        _, names, descs = op
        return df.sort(list(names), desc=list(descs))
    if kind == "topn":
        _, n, names, descs = op
        return df.sort(list(names), desc=list(descs)).limit(n)
    raise ValueError(f"unknown op {op!r}")


def build_df(tables: Dict[str, Dict[str, list]], ops: List[tuple]):
    import daft_tpu as dt
    df = dt.from_pydict(tables["left"])
    right = dt.from_pydict(tables["right"])
    for op in ops:
        df = _apply_op(df, right, op)
    return df


def _cols_by_kind(df) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {"i": [], "f": [], "s": [], "b": []}
    for field in df.schema():
        t = str(field.dtype).lower()
        if "bool" in t:
            out["b"].append(field.name)
        elif "int" in t:
            out["i"].append(field.name)
        elif "float" in t or "double" in t:
            out["f"].append(field.name)
        elif "utf8" in t or "str" in t:
            out["s"].append(field.name)
    return out


def gen_case(seed: int) -> Tuple[Dict[str, Dict[str, list]], List[tuple]]:
    """Expand one seed into (tables, op chain). Every candidate op is
    validated against the live schema as it is appended — an op the
    schema can't host is simply skipped, keeping generation total."""
    rng = random.Random(seed * 2654435761 % (2 ** 31))
    tables = _gen_tables(rng)
    ops: List[tuple] = []

    def try_push(op, df):
        try:
            nxt = _apply_op(df, _right, op)
            nxt.schema()  # force plan-time validation
        except Exception:
            return df
        ops.append(op)
        return nxt

    import daft_tpu as dt
    df = dt.from_pydict(tables["left"])
    _right = dt.from_pydict(tables["right"])

    if rng.random() < 0.65:
        df = try_push(("join",
                       rng.choice(("inner", "left", "semi", "anti"))), df)

    for _ in range(rng.randint(0, 3)):
        kinds = _cols_by_kind(df)
        num = kinds["i"] + kinds["f"]
        if num and rng.random() < 0.8:
            name = rng.choice(num)
            cmp = rng.choice(("gt", "lt", "ge", "le", "eq"))
            const = (rng.randint(-10, 10) if name in kinds["i"]
                     else round(rng.uniform(-5.0, 5.0), 2))
            df = try_push(("filter", name, cmp, const), df)
        else:
            anyc = [c for v in kinds.values() for c in v]
            if anyc:
                df = try_push(("filter_null", rng.choice(anyc),
                               rng.random() < 0.3), df)

    if rng.random() < 0.5:
        kinds = _cols_by_kind(df)
        anyc = [c for v in kinds.values() for c in v]
        if len(anyc) >= 2:
            keep = rng.sample(anyc, rng.randint(1, len(anyc) - 1))
            num = [c for c in kinds["i"] + kinds["f"] if c not in keep]
            computed = rng.choice(num) if num and rng.random() < 0.6 \
                else None
            df = try_push(("project", sorted(keep), computed), df)

    roll = rng.random()
    if roll < 0.4:
        kinds = _cols_by_kind(df)
        keyable = kinds["i"] + kinds["s"] + kinds["b"]
        if keyable:
            keys = rng.sample(keyable, min(len(keyable),
                                           rng.randint(1, 2)))
            aggs = []
            for c in kinds["i"]:
                if c not in keys and rng.random() < 0.7:
                    aggs.append((rng.choice(("sum", "min", "max",
                                             "count")), c))
            for c in kinds["f"]:
                # floats: order-independent reductions only, so the
                # bit-identical oracle survives re-partitioned addition
                if c not in keys and rng.random() < 0.7:
                    aggs.append((rng.choice(("min", "max", "count")), c))
            if aggs:
                df = try_push(("groupby", sorted(keys), aggs), df)
    elif roll < 0.55:
        df = try_push(("distinct",), df)

    kinds = _cols_by_kind(df)
    anyc = sorted(c for v in kinds.values() for c in v)
    if anyc and rng.random() < 0.6:
        if rng.random() < 0.5:
            by = rng.sample(anyc, min(len(anyc), rng.randint(1, 2)))
            df = try_push(("sort", by,
                           [rng.random() < 0.5 for _ in by]), df)
        else:
            # top-n must follow a TOTAL order or the cut itself is
            # nondeterministic across modes: sort by every column
            df = try_push(("topn", rng.randint(1, 12), anyc,
                           [rng.random() < 0.5 for _ in anyc]), df)
    return tables, ops


# ------------------------------------------------------- oracle & modes


def _norm(v):
    if v is None:
        return ("n",)
    if isinstance(v, bool):
        return ("b", v)
    if isinstance(v, float):
        return ("f", repr(v))  # exact: bit-identical, NaN-stable
    if isinstance(v, int):
        return ("i", v)
    return ("s", str(v))


def canonical_rows(pydict: Dict[str, list]) -> List[tuple]:
    cols = sorted(pydict)
    rows = list(zip(*(pydict[c] for c in cols))) if cols else []
    return sorted((tuple(_norm(v) for v in r) for r in rows))


def _concat_pydict(parts, schema) -> Dict[str, list]:
    out: Dict[str, list] = {name: [] for name in schema.column_names}
    for p in parts:
        d = p.to_pydict()
        for name in out:
            out[name].extend(d.get(name, []))
    return out


def run_reference(df) -> Dict[str, list]:
    """The differential oracle: translate the RAW logical plan (no
    optimizer) and run it on the pull interpreter — no fusion, no spill
    planning, no AQE, single partition stream."""
    from ..execution.executor import LocalExecutor
    from ..physical.translate import translate
    plan = translate(df._builder._plan)
    return _concat_pydict(list(LocalExecutor().run(plan)), df.schema())


@contextlib.contextmanager
def _mode_ctx(mode: str):
    from ..context import execution_config_ctx, get_context
    if mode == "optimized":
        with execution_config_ctx():
            yield
    elif mode == "fused":
        with execution_config_ctx(tpu_fusion="1"):
            yield
    elif mode == "spilled":
        with execution_config_ctx(tpu_spill_join="1"):
            yield
    elif mode == "replanned":
        with execution_config_ctx(enable_aqe=True, tpu_adaptive=True):
            yield
    elif mode == "combined":
        ctx = get_context()
        with ctx._lock:
            old_runner = ctx._runner
        from ..runners.distributed_runner import DistributedRunner
        # daft-lint: allow(knob-direct-read) -- save/restore of the raw
        # env value around the forced-combine run, not a parse site
        prev = os.environ.get("DAFT_TPU_SHUFFLE_COMBINE")
        os.environ["DAFT_TPU_SHUFFLE_COMBINE"] = "1"
        try:
            ctx.set_runner(DistributedRunner(num_workers=2))
            with execution_config_ctx():
                yield
        finally:
            if prev is None:
                os.environ.pop("DAFT_TPU_SHUFFLE_COMBINE", None)
            else:
                os.environ["DAFT_TPU_SHUFFLE_COMBINE"] = prev
            ctx.set_runner(old_runner)
    else:
        raise ValueError(f"unknown mode {mode!r}")


def run_mode(tables, ops, mode: str) -> Dict[str, list]:
    with _mode_ctx(mode):
        return build_df(tables, ops).to_pydict()


# ------------------------------------------------------------- the loop


@dataclasses.dataclass
class Mismatch:
    seed: int
    mode: str
    ops: List[tuple]
    detail: str

    def repro(self) -> str:
        lines = [f"seed={self.seed} mode={self.mode}",
                 "minimized ops:"]
        lines.extend(f"  {op!r}" for op in self.ops)
        lines.append(f"detail: {self.detail}")
        lines.append("replay: from daft_tpu.analysis import plan_fuzzer; "
                     f"plan_fuzzer.replay({self.seed}, {self.mode!r})")
        return "\n".join(lines)


@dataclasses.dataclass
class FuzzResult:
    seeds_run: int = 0
    cases_compared: int = 0
    mismatches: List[Mismatch] = dataclasses.field(default_factory=list)
    errors: List[str] = dataclasses.field(default_factory=list)
    sanitizer_violations: int = 0

    def summary(self) -> Dict[str, int]:
        return {"seeds_run": self.seeds_run,
                "cases_compared": self.cases_compared,
                "mismatches": len(self.mismatches),
                "errors": len(self.errors),
                "sanitizer_violations": self.sanitizer_violations}


def _diff_detail(ref_rows, got_rows) -> str:
    if len(ref_rows) != len(got_rows):
        return (f"row count: reference {len(ref_rows)} vs mode "
                f"{len(got_rows)}")
    for i, (a, b) in enumerate(zip(ref_rows, got_rows)):
        if a != b:
            return f"first differing canonical row {i}: {a!r} vs {b!r}"
    return "rows differ"


def _compare(tables, ops, mode: str) -> Optional[str]:
    """None if mode agrees with the reference, else a human detail."""
    ref = canonical_rows(run_reference(build_df(tables, ops)))
    got = canonical_rows(run_mode(tables, ops, mode))
    if ref == got:
        return None
    return _diff_detail(ref, got)


def _minimize(tables, ops: List[tuple], mode: str) -> List[tuple]:
    """Greedy delta-debug: drop ops one at a time while the mismatch
    persists; the survivor is the minimal failing chain."""
    ops = list(ops)
    shrunk = True
    while shrunk and len(ops) > 1:
        shrunk = False
        for i in range(len(ops)):
            trial = ops[:i] + ops[i + 1:]
            try:
                if _compare(tables, trial, mode) is not None:
                    ops = trial
                    shrunk = True
                    break
            except Exception:
                continue  # dropping this op broke the plan: keep it
    return ops


def replay(seed: int, mode: str) -> Optional[str]:
    """Re-run one seed against one mode; returns the mismatch detail or
    None. The entry point mismatch repros print."""
    tables, ops = gen_case(seed)
    return _compare(tables, ops, mode)


def run_fuzz(count: Optional[int] = None, seed: Optional[int] = None,
             modes: Optional[Tuple[str, ...]] = None,
             log=None) -> FuzzResult:
    base = fuzz_seed_base() if seed is None else seed
    n = fuzz_count() if count is None else count
    modes = modes or MODES
    res = FuzzResult()

    from . import plan_sanitizer
    viol0 = len(plan_sanitizer.summary().get("violations", [])) \
        if plan_sanitizer.is_enabled() else 0

    for i in range(n):
        s = base + i
        try:
            tables, ops = gen_case(s)
            ref = canonical_rows(run_reference(build_df(tables, ops)))
        except Exception as e:  # a generation/reference bug, not a diff
            res.errors.append(f"seed {s}: reference failed: {e!r}")
            continue
        res.seeds_run += 1
        for mode in modes:
            try:
                got = canonical_rows(run_mode(tables, ops, mode))
            except Exception as e:
                res.mismatches.append(Mismatch(
                    s, mode, ops, f"mode raised: {e!r}"))
                continue
            res.cases_compared += 1
            if got != ref:
                small = _minimize(tables, ops, mode)
                detail = _compare(tables, small, mode) \
                    or _diff_detail(ref, got)
                res.mismatches.append(Mismatch(s, mode, small, detail))
        if log is not None and (i + 1) % 10 == 0:
            log(f"plan fuzzer: {i + 1}/{n} seeds, "
                f"{len(res.mismatches)} mismatches")

    if plan_sanitizer.is_enabled():
        res.sanitizer_violations = \
            len(plan_sanitizer.summary().get("violations", [])) - viol0
    return res
