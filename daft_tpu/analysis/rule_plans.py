"""Rule family — plan contracts (round 22, daft-lint v4).

The engine rewrites plans in four independent places (optimizer rule
batches, physical translation + fusion, distributed re-planning, and
exchange strategy swaps), and nothing but incidental parity tests proved
those rewrites preserve semantics — the r19 ``_hash_array``
nullable-promotion bug (silently broken co-partitioning in every
hash-partitioned join) is the canonical escape. This family makes the
planner layer's contracts explicit and proven both ways against
``analysis/plan_contracts.py``:

- ``plan-node-unregistered`` / ``plan-node-stale`` — every
  ``LogicalPlan`` / ``PhysicalPlan`` subclass is declared once in the
  registry with schema/partitioning/ordering derivations, and every
  registry entry names a real class. A new physical node with no
  declared partitioning derivation is a finding, because silent
  "arbitrary" defaults are how co-partitioning bugs survive.
- ``plan-field-undeclared`` / ``plan-field-stale`` — the registry's
  field inventory (semantic + estimate fields) matches the constructor's
  ``self.X = …`` assignments exactly, both directions.
- ``plan-schema-convention`` — a physical node's declared schema
  derivation class ("child" vs "computed") matches what its constructor
  actually passes to ``super().__init__``.
- ``plan-rule-unregistered`` / ``plan-rule-stale`` — every ``Optimizer``
  ``Rule`` subclass is registered as schema-preserving or
  schema-rewriting (the runtime sanitizer enforces the claim per
  application).
- ``plan-foreign-field`` — ``distributed/replan.py`` /
  ``physical/adaptive.py`` may mutate ONLY the registered estimate /
  strategy fields on already-built plan objects, never semantic fields
  (keys, join type, schema); dynamic ``setattr`` is banned there
  outright so the set stays statically checkable.
- ``plan-fusion-fallback-schema`` — a functional check: exemplar plans
  for each region grammar are fused and every formed region's schema
  must equal its fallback subtree's schema field-for-field (fusion is an
  execution strategy, never a semantics change).

The runtime twin of this family is ``analysis/plan_sanitizer.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from . import plan_contracts
from .framework import Finding, SourceFile, call_name

RULE_IDS: Dict[str, Tuple[str, str]] = {
    "plan-node-unregistered": (
        "plans", "declare a NodeContract for the plan node in "
                 "analysis/plan_contracts.py (schema, partitioning, "
                 "ordering, fields)"),
    "plan-node-stale": (
        "plans", "drop (or repoint) the registry entry — no such plan "
                 "node class exists anymore"),
    "plan-field-undeclared": (
        "plans", "add the constructor field to the node's NodeContract "
                 "(semantic_fields or estimate_fields)"),
    "plan-field-stale": (
        "plans", "the NodeContract declares a field the constructor no "
                 "longer assigns — drop or repoint it"),
    "plan-schema-convention": (
        "plans", "make the constructor's super().__init__ schema "
                 "argument match the contract's declared derivation "
                 "(child.schema() vs explicit schema)"),
    "plan-rule-unregistered": (
        "plans", "register the optimizer Rule subclass in "
                 "plan_contracts.RULE_CONTRACTS as schema-preserving or "
                 "schema-rewriting"),
    "plan-rule-stale": (
        "plans", "drop the RULE_CONTRACTS entry — no such Rule subclass "
                 "exists anymore"),
    "plan-foreign-field": (
        "plans", "replan/adaptive may mutate only the fields in "
                 "plan_contracts.REPLAN_MUTABLE; register the field "
                 "with a reason or stop mutating it"),
    "plan-fusion-fallback-schema": (
        "plans", "keep the FusedRegion's schema identical to its "
                 "fallback subtree's schema — fusion must never change "
                 "semantics"),
}

_LOGICAL_PATH = "daft_tpu/logical/plan.py"
_PHYSICAL_PATH = "daft_tpu/physical/plan.py"
_OPTIMIZER_PATH = "daft_tpu/logical/optimizer.py"
_REPLAN_PATHS = ("daft_tpu/distributed/replan.py",
                 "daft_tpu/physical/adaptive.py")

#: non-node helper classes living in the plan modules
_NON_NODE_CLASSES = {"ClusteringSpec", "LogicalPlan", "PhysicalPlan"}


# ------------------------------------------------------- class inventory

def _init_fields(cls: ast.ClassDef) -> List[Tuple[str, int]]:
    """Public ``self.X = …`` (and annotated / tuple-unpacked) targets in
    ``__init__``, with line numbers. Underscore-prefixed attributes are
    internal caches owned by the class and stay out of the contract."""
    out: List[Tuple[str, int]] = []
    for item in cls.body:
        if not (isinstance(item, ast.FunctionDef)
                and item.name == "__init__"):
            continue
        for stmt in ast.walk(item):
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                for e in elts:
                    if isinstance(e, ast.Attribute) \
                            and isinstance(e.value, ast.Name) \
                            and e.value.id == "self" \
                            and not e.attr.startswith("_"):
                        out.append((e.attr, stmt.lineno))
    return out


def _super_schema_arg(cls: ast.ClassDef):
    """The schema argument of the ``super().__init__(children, schema)``
    call in a physical node's constructor (or None)."""
    for item in cls.body:
        if not (isinstance(item, ast.FunctionDef)
                and item.name == "__init__"):
            continue
        for stmt in ast.walk(item):
            if isinstance(stmt, ast.Call) \
                    and call_name(stmt).endswith("__init__") \
                    and isinstance(stmt.func, ast.Attribute) \
                    and isinstance(stmt.func.value, ast.Call) \
                    and call_name(stmt.func.value) == "super" \
                    and len(stmt.args) >= 2:
                return stmt.args[1]
    return None


def _is_child_schema_call(expr: ast.AST) -> bool:
    """``<child>.schema()`` — the "inherit from first child" convention."""
    return (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "schema")


def _node_classes(sf: SourceFile, base: str) -> List[ast.ClassDef]:
    out = []
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef) \
                and node.name not in _NON_NODE_CLASSES \
                and any(isinstance(b, ast.Name) and b.id == base
                        for b in node.bases):
            out.append(node)
    return out


def _check_layer(sf: SourceFile, base: str,
                 registry: Dict[str, "plan_contracts.NodeContract"],
                 check_schema_convention: bool) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[str] = set()
    for cls in _node_classes(sf, base):
        seen.add(cls.name)
        contract = registry.get(cls.name)
        if contract is None:
            out.append(Finding(
                "plan-node-unregistered", sf.path, cls.lineno,
                f"{base} subclass {cls.name} has no NodeContract in "
                f"analysis/plan_contracts.py — every plan node needs a "
                f"declared schema/partitioning/ordering derivation"))
            continue
        declared = set(contract.semantic_fields) \
            | set(contract.estimate_fields)
        assigned = _init_fields(cls)
        assigned_names = {name for name, _ln in assigned}
        for name, ln in assigned:
            if name not in declared:
                out.append(Finding(
                    "plan-field-undeclared", sf.path, ln,
                    f"{cls.name}.__init__ assigns self.{name} but the "
                    f"NodeContract does not declare it — add it to "
                    f"semantic_fields or estimate_fields"))
        for name in sorted(declared - assigned_names):
            out.append(Finding(
                "plan-field-stale", sf.path, cls.lineno,
                f"NodeContract for {cls.name} declares field {name!r} "
                f"but the constructor no longer assigns it"))
        if check_schema_convention:
            arg = _super_schema_arg(cls)
            if arg is not None:
                is_child = _is_child_schema_call(arg)
                if contract.schema == "child" and not is_child:
                    out.append(Finding(
                        "plan-schema-convention", sf.path, cls.lineno,
                        f"{cls.name} is declared schema='child' but its "
                        f"constructor does not pass "
                        f"<child>.schema() to super().__init__"))
                elif contract.schema != "child" and is_child:
                    out.append(Finding(
                        "plan-schema-convention", sf.path, cls.lineno,
                        f"{cls.name} is declared schema="
                        f"{contract.schema!r} but its constructor "
                        f"inherits the child schema verbatim — declare "
                        f"it 'child' or pass an explicit schema"))
    for name, contract in sorted(registry.items()):
        if name not in seen:
            out.append(Finding(
                "plan-node-stale", sf.path, 1,
                f"NodeContract {name!r} ({contract.layer}) names a plan "
                f"node class that no longer exists"))
    return out


# ---------------------------------------------------------- rule registry

def _check_rules(sf: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[str] = set()
    for cls in sf.tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        if not any(isinstance(b, ast.Name) and b.id == "Rule"
                   for b in cls.bases):
            continue
        seen.add(cls.name)
        if cls.name not in plan_contracts.RULE_CONTRACTS:
            out.append(Finding(
                "plan-rule-unregistered", sf.path, cls.lineno,
                f"optimizer Rule subclass {cls.name} is not registered "
                f"in plan_contracts.RULE_CONTRACTS — declare it "
                f"schema-preserving or schema-rewriting"))
    for name in sorted(plan_contracts.RULE_CONTRACTS):
        if name not in seen:
            out.append(Finding(
                "plan-rule-stale", sf.path, 1,
                f"RULE_CONTRACTS entry {name!r} names a Rule subclass "
                f"that no longer exists"))
    return out


# ------------------------------------------------------- replan mutation

def _check_replan_mutations(sf: SourceFile) -> List[Finding]:
    """Non-``self`` attribute stores in the re-planning modules must hit
    only registered mutable fields; ``setattr`` is banned outright (a
    dynamic attribute name defeats this rule)."""
    out: List[Finding] = []
    allowed = plan_contracts.REPLAN_MUTABLE_FIELDS
    for stmt in ast.walk(sf.tree):
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Call) and call_name(stmt) == "setattr":
            out.append(Finding(
                "plan-foreign-field", sf.path, stmt.lineno,
                "setattr() on a plan object in a re-planning module — "
                "use an explicit attribute assignment so the mutable "
                "field set stays statically checkable"))
            continue
        for t in targets:
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            for e in elts:
                if not isinstance(e, ast.Attribute):
                    continue
                root = e.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id == "self":
                    continue
                if e.attr not in allowed:
                    out.append(Finding(
                        "plan-foreign-field", sf.path, e.lineno,
                        f"re-planning code mutates .{e.attr} on an "
                        f"already-built plan object, which is not in "
                        f"plan_contracts.REPLAN_MUTABLE — semantic "
                        f"fields are frozen after planning"))
    return out


# -------------------------------------------------- fusion fallback check

def check_fusion_contracts() -> List[Finding]:
    """Functional check: build exemplar queries for each region grammar
    (chain / topk / join_agg), force fusion, and prove every region that
    forms keeps its schema identical to its fallback subtree's schema.
    Mirrors ``rule_jit.check_dispatch_contracts`` — a contract proven
    against the real planner, not the AST."""
    out: List[Finding] = []
    try:
        import daft_tpu
        from daft_tpu import col
        from daft_tpu.context import ExecutionConfig
        from daft_tpu.device import runtime as drt
        from daft_tpu.physical import fusion
        from daft_tpu.physical import plan as pp
        from daft_tpu.physical.translate import translate
    except Exception as exc:  # pragma: no cover - import skew
        return [Finding("plan-fusion-fallback-schema",
                        "daft_tpu/physical/fusion.py", 1,
                        f"fusion contract check could not import the "
                        f"engine: {exc!r}")]
    if not drt.device_enabled():
        return out  # no device tier in this interpreter: nothing to fuse

    cfg = ExecutionConfig(tpu_fusion="1")
    left = daft_tpu.from_pydict({
        "k": [1, 2, 3, 4, 5, 6, 7, 8],
        "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0],
    })
    # build-side column names must be disjoint from the probe source's
    # (the join_agg grammar keys its joined plane dict by raw name)
    right = daft_tpu.from_pydict({"rk": [1, 2, 3, 4],
                                  "w": [10, 20, 30, 40]})
    exemplars = {
        "chain": left.where(col("k") > 1)
                     .select(col("k"), (col("v") * 2).alias("v2")),
        "topk": left.where(col("k") > 1)
                    .select(col("k"), col("v"))
                    .sort("k").limit(3),
        "join_agg": left.join(right, left_on="k", right_on="rk")
                        .groupby("w").agg(col("v").sum()),
    }
    for shape, df in exemplars.items():
        try:
            plan = translate(df._builder.optimize()._plan)
            fused = fusion.fuse_regions(plan, cfg)
        except Exception as exc:
            out.append(Finding(
                "plan-fusion-fallback-schema",
                "daft_tpu/physical/fusion.py", 1,
                f"fusion contract exemplar {shape!r} failed to plan: "
                f"{exc!r}"))
            continue
        stack, seen = [fused], set()
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            if isinstance(n, pp.FusedRegion):
                rf = list(n.schema().fields)
                ff = list(n.fallback.schema().fields)
                if rf != ff:
                    out.append(Finding(
                        "plan-fusion-fallback-schema",
                        "daft_tpu/physical/fusion.py", 1,
                        f"{n.shape} region schema "
                        f"{[f.name for f in rf]} != fallback schema "
                        f"{[f.name for f in ff]} on exemplar "
                        f"{shape!r} — fusion changed semantics"))
                stack.append(n.fallback)
            stack.extend(n.children)
    return out


# ----------------------------------------------------------------- entry

def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if sf.path == _LOGICAL_PATH:
            out.extend(_check_layer(sf, "LogicalPlan",
                                    plan_contracts.LOGICAL_NODES,
                                    check_schema_convention=False))
        elif sf.path == _PHYSICAL_PATH:
            out.extend(_check_layer(sf, "PhysicalPlan",
                                    plan_contracts.PHYSICAL_NODES,
                                    check_schema_convention=True))
        elif sf.path == _OPTIMIZER_PATH:
            out.extend(_check_rules(sf))
        if sf.path in _REPLAN_PATHS:
            out.extend(_check_replan_mutations(sf))
    return out
