"""Rule family 1 — knob registry discipline.

Invariant: every ``DAFT_TPU_*`` environment knob is declared once in
``analysis/knobs.py`` and parsed once (the typed accessors there). The
rule flags:

- ``knob-unregistered`` — an env read (or typed-accessor call) naming a
  ``DAFT_TPU_*`` knob the registry doesn't know;
- ``knob-direct-read`` — a registered knob read through raw
  ``os.environ`` / ``os.getenv`` instead of the registry accessor
  (a second parse site: int-vs-bytes-vs-bool drift starts here);
- ``knob-type-mismatch`` — an accessor call whose type disagrees with
  the registry declaration (the same knob parsed two different ways);
- ``knob-unused`` — a registered knob that appears nowhere in the code;
- ``knob-config-drift`` — registry ``config_field`` entries that don't
  match ``ExecutionConfig``, or tpu-spelled ``ExecutionConfig`` fields
  missing from the registry;
- ``knob-doc-drift`` — README generated knob tables stale vs the
  registry (see ``knobs.readme_drift``).
"""

from __future__ import annotations

import ast
import os
import re
from typing import List

from . import knobs
from .framework import Finding, SourceFile, call_name, dotted_name

REGISTRY_MODULE = "daft_tpu/analysis/knobs.py"

_ACCESSOR_TYPES = {
    "env_int": "int", "env_float": "float", "env_bool": "bool",
    "env_bytes": "bytes", "env_str": "str",
}
_PRESENCE_ACCESSORS = ("env_raw", "env_is_set")


def _literal_knob(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("DAFT_TPU_"):
        return node.value
    return None


_KNOB_NAME_RE = re.compile(r"DAFT_TPU_[A-Z0-9_]+")


def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    seen_anywhere = set()

    for sf in sources:
        if sf.path != REGISTRY_MODULE:
            # the registry's own literals must not count as "usage";
            # full-token extraction, not substring: DAFT_TPU_DEVICE must
            # not be "seen" inside DAFT_TPU_DEVICE_FORCE
            seen_anywhere.update(
                m for m in _KNOB_NAME_RE.findall(sf.text)
                if m in knobs.REGISTRY)
        if not sf.path.startswith("daft_tpu/") or sf.path == REGISTRY_MODULE:
            continue
        for node in ast.walk(sf.tree):
            # raw env reads: os.environ.get / os.getenv / os.environ[...]
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name.endswith("environ.get") or name.endswith("getenv"):
                    knob = _literal_knob(node.args[0]) if node.args else None
                    if knob is None:
                        continue
                    if knob not in knobs.REGISTRY:
                        out.append(Finding(
                            "knob-unregistered", sf.path, node.lineno,
                            f"env read of unregistered knob {knob} — declare "
                            f"it in {REGISTRY_MODULE}"))
                    else:
                        out.append(Finding(
                            "knob-direct-read", sf.path, node.lineno,
                            f"{knob} read through os.environ — use the "
                            f"registry accessor (analysis.knobs.env_*) so "
                            f"the knob has one parse site"))
                else:
                    short = name.rsplit(".", 1)[-1]
                    if short in _ACCESSOR_TYPES or short in \
                            _PRESENCE_ACCESSORS:
                        knob = _literal_knob(node.args[0]) \
                            if node.args else None
                        if knob is None:
                            continue
                        reg = knobs.REGISTRY.get(knob)
                        if reg is None:
                            out.append(Finding(
                                "knob-unregistered", sf.path, node.lineno,
                                f"accessor read of unregistered knob {knob}"))
                        elif short in _ACCESSOR_TYPES \
                                and reg.type != _ACCESSOR_TYPES[short]:
                            out.append(Finding(
                                "knob-type-mismatch", sf.path, node.lineno,
                                f"{knob} is registered as {reg.type!r} but "
                                f"read via {short}()"))
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and dotted_name(node.value).endswith("environ"):
                knob = _literal_knob(node.slice)
                if knob is None:
                    continue
                if knob not in knobs.REGISTRY:
                    out.append(Finding(
                        "knob-unregistered", sf.path, node.lineno,
                        f"env read of unregistered knob {knob}"))
                else:
                    out.append(Finding(
                        "knob-direct-read", sf.path, node.lineno,
                        f"{knob} read through os.environ[...] — use the "
                        f"registry accessor"))

    for name, k in knobs.REGISTRY.items():
        if name not in seen_anywhere:
            out.append(Finding(
                "knob-unused", REGISTRY_MODULE, 1,
                f"{name} is registered (owner {k.module}) but appears "
                f"nowhere in the scanned tree — stale registry entry?"))

    out.extend(_config_drift(sources))
    return out


def _config_drift(sources: List[SourceFile]) -> List[Finding]:
    """Registry.config_field ↔ ExecutionConfig field cross-check."""
    ctx = next((sf for sf in sources
                if sf.path == "daft_tpu/context.py"), None)
    if ctx is None:
        return []
    fields = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef) and node.name == "ExecutionConfig":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
    out = []
    mirrored = set()
    for name, k in knobs.REGISTRY.items():
        if not k.config_field:
            continue
        mirrored.add(k.config_field)
        if k.config_field not in fields:
            out.append(Finding(
                "knob-config-drift", REGISTRY_MODULE, 1,
                f"{name} claims ExecutionConfig.{k.config_field} but that "
                f"field does not exist"))
        if f"DAFT_{k.config_field.upper()}" != name:
            out.append(Finding(
                "knob-config-drift", REGISTRY_MODULE, 1,
                f"{name}: config_field {k.config_field!r} does not spell "
                f"the env name (context auto-parses DAFT_<FIELD>)"))
    for f in fields:
        env_name = f"DAFT_{f.upper()}"
        if env_name.startswith("DAFT_TPU_") \
                and env_name not in knobs.REGISTRY:
            out.append(Finding(
                "knob-config-drift", "daft_tpu/context.py", 1,
                f"ExecutionConfig.{f} is env-parsable as {env_name} but "
                f"that knob is not registered"))
    return out


def check_readme(root: str) -> List[Finding]:
    path = os.path.join(root, "README.md")
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return [Finding("knob-doc-drift", "README.md", 1,
                        "README.md is missing")]
    return [Finding("knob-doc-drift", "README.md", 1, p)
            for p in knobs.readme_drift(text)]
