"""Rule family 6 — donated-buffer safety for the device kernel plane.

The r12 megakernels donate input HBM to the fused program
(``donate_argnums`` / ``FusedAggProgram.donate_fn``): after a donating
dispatch the donated planes are DEAD — XLA has reused their memory for
the program's intermediates. Reading them afterwards returns garbage (or
crashes on silicon with a deleted-buffer error that CPU runs never see,
which is exactly why this must be a static check). Two rules:

- ``donated-buffer-read`` — taint the argument positions named by a
  ``donate_argnums`` jit wrapper (or a same-module helper that forwards
  its parameters into one — the ``_dispatch_packed`` pattern) at each
  dispatch site, propagate forward over the CFG, kill the taint on
  rebind (the overflow re-dispatch's ``dt = reencode()``), and flag any
  later read of a *plane-carrying* attribute (``.columns``,
  ``.row_mask``, ``.data``, ``.validity``) of a tainted name — in the
  dispatching function, or via a one-level same-module callee that reads
  planes off the corresponding parameter. Scalar metadata
  (``.row_count``, ``.capacity``, dictionaries) stays host-side and is
  deliberately NOT flagged.
- ``donation-unguarded`` — the static proof that
  ``DeviceTable.resident`` guards every donation of a potentially
  cache-shared table: a ``donate`` flag must derive from a direct
  ``.resident`` read, a call to a helper whose body reads ``.resident``
  (``_donation_ok``), or be a plain parameter passthrough (the caller
  already proved it). A bare ``donate=True`` or a guard that never
  consults residency donates buffers the HBM cache may still be serving.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import dataflow
from .dataflow import ModuleIndex
from .framework import Finding, SourceFile
from .rule_resources import _header_parts, walk_local

#: modules the donation discipline applies to (the device kernel plane)
DEVICE_MODULES = (
    "daft_tpu/device/fragment.py",
    "daft_tpu/device/kernels.py",
    "daft_tpu/device/pallas_kernels.py",
    "daft_tpu/device/runtime.py",
)

#: attributes that reach the donated device planes; everything else on a
#: DeviceTable (row_count, capacity, dictionaries) is host metadata
PLANE_ATTRS = frozenset({"columns", "row_mask", "data", "validity"})

RULE_IDS = {
    "donated-buffer-read": (
        "donation",
        "re-encode (dt = reencode()) or drop the donated object before "
        "touching its planes; donated HBM is dead after dispatch"),
    "donation-unguarded": (
        "donation",
        "derive the donate flag from DeviceTable.resident (e.g. via "
        "_donation_ok) so cache-shared buffers are never donated"),
}


def _call_last(call: ast.Call) -> str:
    return dataflow._call_last_name(call)


def _donating_jit_names(fn: ast.AST) -> Set[str]:
    """Local names bound (possibly conditionally) to
    ``jax.jit(..., donate_argnums=<non-empty-able>)`` wrappers."""
    out: Set[str] = set()
    for sub in walk_local(fn):
        if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)):
            continue
        v = sub.value
        if isinstance(v, ast.Call) and _call_last(v) == "jit":
            for kw in v.keywords:
                if kw.arg == "donate_argnums" \
                        and not (isinstance(kw.value, ast.Tuple)
                                 and not kw.value.elts):
                    out.add(sub.targets[0].id)
    return out


def _donate_positions(fn: ast.AST, name: str) -> Optional[Tuple[int, ...]]:
    """The positions a donating wrapper donates, when statically evident
    (a tuple literal, possibly behind ``<tuple> if donate else ()``)."""
    for sub in walk_local(fn):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                and isinstance(sub.targets[0], ast.Name) \
                and sub.targets[0].id == name \
                and isinstance(sub.value, ast.Call):
            for kw in sub.value.keywords:
                if kw.arg != "donate_argnums":
                    continue
                v = kw.value
                if isinstance(v, ast.IfExp):
                    v = v.body
                if isinstance(v, ast.Tuple) and all(
                        isinstance(e, ast.Constant) for e in v.elts):
                    return tuple(int(e.value) for e in v.elts)
    return None


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def _forwarding_donors(idx: ModuleIndex) -> Dict[str, Set[int]]:
    """Same-module helpers that forward parameters into a donating
    dispatch (``_dispatch_packed``): helper name → the indices of ITS
    parameters whose values may be donated. One call level, which is the
    depth the codebase uses."""
    out: Dict[str, Set[int]] = {}
    for _, fn in idx.functions:
        donors = _donating_jit_names(fn)
        donate_fn_vars = {
            s.targets[0].id for s in walk_local(fn)
            if isinstance(s, ast.Assign) and len(s.targets) == 1
            and isinstance(s.targets[0], ast.Name)
            and isinstance(s.value, ast.IfExp)
            and isinstance(s.value.body, ast.Call)
            and _call_last(s.value.body) == "donate_fn"}
        if not donors and not donate_fn_vars:
            continue
        params = _param_names(fn)
        tainted_params: Set[int] = set()
        # which locals derive from which parameter (single assignment
        # depth — enough for the arrays/valids-from-dt pattern)
        derived: Dict[str, Set[str]] = {p: {p} for p in params}
        for s in walk_local(fn):
            if isinstance(s, ast.Assign) and len(s.targets) == 1 \
                    and isinstance(s.targets[0], ast.Name):
                roots = {n.id for n in ast.walk(s.value)
                         if isinstance(n, ast.Name)}
                derived[s.targets[0].id] = set().union(
                    *(derived.get(r, set()) for r in roots)) or set()
        for sub in walk_local(fn):
            if not isinstance(sub, ast.Call):
                continue
            callee = sub.func
            callee_name = callee.id if isinstance(callee, ast.Name) else ""
            if callee_name in donors:
                pos = _donate_positions(fn, callee_name) or tuple(
                    range(len(sub.args)))
                for i in pos:
                    if i < len(sub.args):
                        for n in ast.walk(sub.args[i]):
                            if isinstance(n, ast.Name):
                                for root in derived.get(n.id, set()):
                                    if root in params:
                                        tainted_params.add(
                                            params.index(root))
            elif callee_name in donate_fn_vars:
                for i in (0, 1):
                    if i < len(sub.args):
                        for n in ast.walk(sub.args[i]):
                            if isinstance(n, ast.Name):
                                for root in derived.get(n.id, set()):
                                    if root in params:
                                        tainted_params.add(
                                            params.index(root))
        if tainted_params:
            out[fn.name] = tainted_params
    return out


def _plane_readers(idx: ModuleIndex) -> Dict[str, Set[int]]:
    """helper name → parameter indices whose PLANE_ATTRS the helper
    reads (the one-level callee side of donated-then-read)."""
    out: Dict[str, Set[int]] = {}
    for _, fn in idx.functions:
        params = _param_names(fn)
        hit: Set[int] = set()
        for sub in walk_local(fn):
            if isinstance(sub, ast.Attribute) and sub.attr in PLANE_ATTRS \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in params:
                hit.add(params.index(sub.value.id))
        if hit:
            out[fn.name] = hit
    return out


def _donation_sites(fn: ast.AST, forwarding: Dict[str, Set[int]],
                    idx: ModuleIndex
                    ) -> List[Tuple[ast.Call, Set[str], Set[str]]]:
    """(call, tainted local names, donate-flag names) for every donating
    dispatch in fn. The flag names drive the correlated-kill rule: a
    rebind under ``if <flag>:`` kills the taint unconditionally, because
    the taint only exists when the flag was true."""
    donors = _donating_jit_names(fn)
    sites: List[Tuple[ast.Call, Set[str], Set[str]]] = []
    for sub in walk_local(fn):
        if not isinstance(sub, ast.Call):
            continue
        callee = sub.func
        name = callee.id if isinstance(callee, ast.Name) else ""
        tainted: Set[str] = set()
        flags: Set[str] = set()
        if name in donors:
            pos = _donate_positions(fn, name) or ()
            for i in pos:
                if i < len(sub.args) and isinstance(sub.args[i], ast.Name):
                    tainted.add(sub.args[i].id)
        elif name in forwarding:
            callee_def = idx.defs.get(name)
            callee_params = _param_names(callee_def) \
                if callee_def is not None else []
            flag = _donate_flag_value(sub, callee_def, callee_params)
            if isinstance(flag, ast.Constant) and not flag.value:
                continue  # statically donate=False
            if isinstance(flag, ast.Name):
                flags.add(flag.id)
            for i in forwarding[name]:
                if i < len(sub.args) and isinstance(sub.args[i], ast.Name):
                    tainted.add(sub.args[i].id)
            for kw in sub.keywords:
                if kw.arg in callee_params and isinstance(kw.value,
                                                          ast.Name):
                    # keyword passthrough into a tainted param position
                    if callee_params.index(kw.arg) in forwarding[name]:
                        tainted.add(kw.value.id)
        if tainted:
            sites.append((sub, tainted, flags))
    return sites


def _donate_flag_value(call: ast.Call, callee_def,
                       callee_params: List[str]) -> Optional[ast.AST]:
    """The expression the call passes for the callee's ``donate``
    parameter — positionally, by keyword, or the default (a missing
    donate=False default means the call does not donate)."""
    if "donate" not in callee_params:
        return None
    di = callee_params.index("donate")
    if di < len(call.args):
        return call.args[di]
    for kw in call.keywords:
        if kw.arg == "donate":
            return kw.value
    if callee_def is not None:
        a = callee_def.args
        defaults = a.defaults
        params = a.posonlyargs + a.args
        off = len(params) - len(defaults)
        if di >= off:
            return defaults[di - off]
    return None


def _check_donated_reads(sf: SourceFile, idx: ModuleIndex,
                         out: List[Finding]) -> None:
    forwarding = _forwarding_donors(idx)
    readers = _plane_readers(idx)
    for fname, fn in idx.functions:
        sites = _donation_sites(fn, forwarding, idx)
        if not sites:
            continue
        cfg = idx.cfg(fn)
        for call, tainted, flags in sites:
            stmt = _stmt_of(fn, cfg, call)
            if stmt is None:
                continue
            # taint flows from the dispatch's NORMAL successors only: an
            # exception raised BY the dispatch (a trace-time failure like
            # HashKeyWidthError) means no executable consumed the
            # buffers, so that path re-dispatches legitimately
            start_nodes = []
            for node in cfg.nodes_for(stmt):
                start_nodes.extend(t for t, is_exc in node.succ
                                   if not is_exc)
            # forward reach from the dispatch, killed at rebinds; a
            # rebind under `if <donate-flag>:` kills on BOTH branches —
            # the flag false means nothing was donated in the first
            # place (correlated-branch soundness)
            kills = _rebind_stmts(fn, tainted)
            for sub2 in walk_local(fn):
                if isinstance(sub2, ast.If) \
                        and isinstance(sub2.test, ast.Name) \
                        and sub2.test.id in flags \
                        and any(id(s) in kills
                                for s in ast.walk(sub2)
                                if isinstance(s, ast.stmt)):
                    kills.add(id(sub2))
            reads = _plane_read_stmts(fn, tainted, readers, idx)
            seen: Set[int] = set()
            stack = list(start_nodes)
            while stack:
                n = stack.pop()
                if id(n) in seen:
                    continue
                seen.add(id(n))
                if n.stmt is not None and id(n.stmt) in kills:
                    continue
                hit = reads.get(id(n.stmt)) if n.stmt is not None else None
                if hit is not None:
                    out.append(Finding(
                        "donated-buffer-read", sf.path, hit[1],
                        f"{hit[0]} is read at line {hit[1]} after the "
                        f"donating dispatch at line {call.lineno} in "
                        f"{fname}() — donated planes are dead; re-encode "
                        f"before reuse"))
                    reads.pop(id(n.stmt))
                for t, _ in n.succ:
                    stack.append(t)


def _rebind_stmts(fn: ast.AST, names: Set[str]) -> Set[int]:
    out: Set[int] = set()
    for sub in walk_local(fn):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Name) and t.id in names:
                    out.add(id(sub))
    return out


def _plane_read_stmts(fn: ast.AST, names: Set[str],
                      readers: Dict[str, Set[int]], idx: ModuleIndex
                      ) -> Dict[int, Tuple[str, int]]:
    """id(stmt) → (description, line) for statements whose CFG-visible
    header reads donated planes of a tainted name (directly, or by
    passing it to a same-module plane-reading helper)."""
    out: Dict[int, Tuple[str, int]] = {}
    for stmt in walk_local(fn):
        if not isinstance(stmt, ast.stmt):
            continue
        for part in _header_parts(stmt):
            for sub in walk_local(part):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr in PLANE_ATTRS \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id in names:
                    out.setdefault(
                        id(stmt),
                        (f"{sub.value.id}.{sub.attr}", sub.lineno))
                if isinstance(sub, ast.Call):
                    cn = sub.func.id if isinstance(sub.func, ast.Name) \
                        else ""
                    if cn in readers:
                        for i in readers[cn]:
                            if i < len(sub.args) \
                                    and isinstance(sub.args[i], ast.Name) \
                                    and sub.args[i].id in names:
                                out.setdefault(
                                    id(stmt),
                                    (f"{sub.args[i].id} (via {cn}(), "
                                     f"which reads its planes)",
                                     sub.lineno))
    return out


def _stmt_of(fn, cfg, target):
    from .rule_resources import _stmt_of as impl
    return impl(fn, cfg, target)


# --------------------------------------------------- donation-unguarded

def _resident_summary(idx: ModuleIndex) -> Set[str]:
    """Functions whose body reads ``.resident`` (one level)."""
    out: Set[str] = set()
    for _, fn in idx.functions:
        for sub in walk_local(fn):
            if isinstance(sub, ast.Attribute) and sub.attr == "resident":
                out.add(fn.name)
                break
    return out


def _check_unguarded(sf: SourceFile, idx: ModuleIndex,
                     out: List[Finding]) -> None:
    resident_fns = _resident_summary(idx)
    for fname, fn in idx.functions:
        params = set(_param_names(fn))
        for sub in walk_local(fn):
            expr = None
            line = 0
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and sub.targets[0].id == "donate":
                expr, line = sub.value, sub.lineno
            elif isinstance(sub, ast.Call):
                for kw in sub.keywords:
                    if kw.arg == "donate":
                        expr, line = kw.value, kw.value.lineno
            if expr is None:
                continue
            if isinstance(expr, ast.Constant) and expr.value is False:
                continue
            if isinstance(expr, ast.Name) and expr.id in params | {
                    "donate"}:
                continue  # passthrough: the producer site is checked
            ok = False
            for n in ast.walk(expr):
                if isinstance(n, ast.Attribute) and n.attr == "resident":
                    ok = True
                if isinstance(n, ast.Call):
                    cn = dataflow._call_last_name(n)
                    if cn in resident_fns:
                        ok = True
            if not ok:
                out.append(Finding(
                    "donation-unguarded", sf.path, line,
                    f"donate flag in {fname}() never consults "
                    f"DeviceTable.resident — a cache-shared table's "
                    f"buffers must not be donated (use _donation_ok)"))
    # bare `.donate_fn()` selections must live in a function that guards
    # (directly or via a resident-reading helper feeding the selector)
    for fname, fn in idx.functions:
        if fn.name == "donate_fn":
            continue
        for sub in walk_local(fn):
            if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute) \
                    and sub.func.attr == "donate_fn":
                guarded = False
                for n in walk_local(fn):
                    if isinstance(n, ast.Attribute) \
                            and n.attr == "resident":
                        guarded = True
                    if isinstance(n, ast.Name) and n.id == "donate":
                        guarded = True  # flag-driven; the flag is checked
                if not guarded:
                    out.append(Finding(
                        "donation-unguarded", sf.path, sub.lineno,
                        f"donate_fn() selected in {fname}() without a "
                        f"donate flag or resident guard in scope"))


# ---------------------------------------------------------------- check

def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if sf.path not in DEVICE_MODULES:
            continue
        idx = ModuleIndex(sf.tree)
        _check_donated_reads(sf, idx, out)
        _check_unguarded(sf, idx, out)
    return out
