"""Intraprocedural CFG + one-level-summary interprocedural dataflow.

The r10 rule families match single statements; the lifecycle invariants
grown by the serving/tracing/kernel planes (r11–r13) are *paired*:
admission acquired at submit must be released on every done / failed /
cancelled path, every started trace must close, a donated device buffer
must never be touched after dispatch. Proving those needs flow: this
module builds a per-function control-flow graph over the Python AST —
including the try/except/finally/with edges where lifecycle bugs
actually hide — and a must-reach-on-all-paths solver on top of it.

Design notes (the RacerD lesson from the static-analysis literature:
compositional per-function summaries, not whole-program models):

- ``finally`` blocks are *instantiated per continuation* (normal exit,
  exception, return, break, continue each get their own copy), so a
  release in a ``finally`` is credited on exactly the paths that really
  run it, and an exception edge can never "borrow" a release that only
  happens on the normal path.
- Exception edges are conservative: any statement containing a call (or
  an explicit ``raise`` / ``assert``) may transfer to the innermost
  handler chain, or out of the function. This is where acquire/release
  pairs break in practice — a helper call between acquire and the
  ``try`` that was supposed to protect it.
- Call summaries are one level (iterated to a small fixpoint): a helper
  that performs the paired release on *all* of its own paths credits the
  call site in its caller, so release-in-a-helper idioms don't need
  pragmas.

Solver credit semantics: a credit (release) node credits every edge
leaving it, including its own exception edge — attempting the release is
the strongest guarantee any path can carry.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple


class Node:
    """One CFG node: a simple statement, a branch header, or a synthetic
    entry/exit/dispatch point. ``succ`` holds ``(target, is_exc_edge)``."""

    __slots__ = ("stmt", "line", "kind", "succ", "branch")

    def __init__(self, stmt: Optional[ast.AST], kind: str = "stmt"):
        self.stmt = stmt
        self.line = getattr(stmt, "lineno", 0)
        self.kind = kind
        self.succ: List[Tuple["Node", bool]] = []
        #: for If headers: (body_entry, orelse_entry) — lets contract
        #: rules start tracking on the branch where a conditional
        #: acquire actually succeeded
        self.branch: Optional[Tuple["Node", "Node"]] = None

    def edge(self, target: "Node", exc: bool = False) -> None:
        self.succ.append((target, exc))

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<Node {self.kind}@{self.line}>"


def _can_raise(node: ast.AST) -> bool:
    """Conservative may-raise: calls and explicit raises. Attribute /
    subscript errors exist but flagging them would drown the signal —
    lifecycle leaks happen across *call* boundaries."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Call, ast.Raise, ast.Assert)):
            return True
    return False


def _const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


class CFG:
    """Control-flow graph of one function body."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.entry = Node(None, "entry")
        self.exit = Node(None, "exit")
        self.nodes: List[Node] = [self.entry, self.exit]
        #: id(stmt) → every node instantiated for it (finally regions
        #: are duplicated per continuation, so one stmt may own several)
        self.by_stmt: Dict[int, List[Node]] = {}
        first = self._build(list(fn.body), self.exit, self.exit,
                            self.exit, self.exit, self.exit)
        self.entry.edge(first)

    # ------------------------------------------------------------ build
    def _new(self, stmt: Optional[ast.AST], kind: str = "stmt") -> Node:
        n = Node(stmt, kind)
        self.nodes.append(n)
        if stmt is not None:
            self.by_stmt.setdefault(id(stmt), []).append(n)
        return n

    def _build(self, stmts: List[ast.stmt], nxt: Node, exc: Node,
               brk: Node, cnt: Node, ret: Node) -> Node:
        """Wire ``stmts`` so control enters at the returned node and
        leaves to the given continuations."""
        entry = nxt
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, entry, exc, brk, cnt, ret)
        return entry

    def _stmt(self, s: ast.stmt, nxt: Node, exc: Node, brk: Node,
              cnt: Node, ret: Node) -> Node:
        if isinstance(s, ast.Return):
            n = self._new(s)
            n.edge(ret)
            if s.value is not None and _can_raise(s.value):
                n.edge(exc, exc=True)
            return n
        if isinstance(s, ast.Raise):
            n = self._new(s)
            n.edge(exc, exc=True)
            return n
        if isinstance(s, ast.Break):
            n = self._new(s)
            n.edge(brk)
            return n
        if isinstance(s, ast.Continue):
            n = self._new(s)
            n.edge(cnt)
            return n
        if isinstance(s, ast.If):
            n = self._new(s)
            body = self._build(s.body, nxt, exc, brk, cnt, ret)
            orelse = self._build(s.orelse, nxt, exc, brk, cnt, ret)
            n.edge(body)
            if orelse is not body:
                n.edge(orelse)
            n.branch = (body, orelse)
            if _can_raise(s.test):
                n.edge(exc, exc=True)
            return n
        if isinstance(s, (ast.While,)):
            n = self._new(s)
            body = self._build(s.body, n, exc, nxt, n, ret)
            n.edge(body)
            if not _const_true(s.test):
                # the else: clause of a loop is rare; fold it into nxt
                n.edge(self._build(s.orelse, nxt, exc, brk, cnt, ret)
                       if s.orelse else nxt)
            if _can_raise(s.test):
                n.edge(exc, exc=True)
            return n
        if isinstance(s, (ast.For, ast.AsyncFor)):
            n = self._new(s)
            body = self._build(s.body, n, exc, nxt, n, ret)
            n.edge(body)
            n.edge(self._build(s.orelse, nxt, exc, brk, cnt, ret)
                   if s.orelse else nxt)
            if _can_raise(s.iter):
                n.edge(exc, exc=True)
            return n
        if isinstance(s, (ast.With, ast.AsyncWith)):
            # the context managers' __exit__ runs on every path out of
            # the body; exceptions keep propagating (suppression is rare
            # enough to ignore), so the body simply inherits our
            # continuations. The header models the __enter__ calls.
            n = self._new(s)
            body = self._build(s.body, nxt, exc, brk, cnt, ret)
            n.edge(body)
            n.edge(exc, exc=True)  # __enter__ may raise
            return n
        if isinstance(s, ast.Try):
            return self._try(s, nxt, exc, brk, cnt, ret)
        # simple statement (incl. nested def/class, which we do not
        # descend into — nested functions get their own CFGs)
        n = self._new(s)
        n.edge(nxt)
        if _can_raise(s) and not isinstance(
                s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            n.edge(exc, exc=True)
        return n

    def _try(self, s: ast.Try, nxt: Node, exc: Node, brk: Node,
             cnt: Node, ret: Node) -> Node:
        if s.finalbody:
            # one finally copy per live continuation: the release-in-
            # finally credit must hold on exactly the paths that run it
            fin_nxt = self._build(s.finalbody, nxt, exc, brk, cnt, ret)
            fin_exc = self._build(s.finalbody, exc, exc, brk, cnt, ret)
            fin_brk = self._build(s.finalbody, brk, exc, brk, cnt, ret)
            fin_cnt = self._build(s.finalbody, cnt, exc, brk, cnt, ret)
            fin_ret = self._build(s.finalbody, ret, exc, brk, cnt, ret)
        else:
            fin_nxt, fin_exc = nxt, exc
            fin_brk, fin_cnt, fin_ret = brk, cnt, ret
        if s.handlers:
            dispatch = self._new(None, "dispatch")
            caught_all = False
            for h in s.handlers:
                h_entry = self._build(h.body, fin_nxt, fin_exc,
                                      fin_brk, fin_cnt, fin_ret)
                dispatch.edge(h_entry)
                if h.type is None or (isinstance(h.type, ast.Name)
                                      and h.type.id == "BaseException"):
                    caught_all = True
            if not caught_all:
                # the exception may match no handler and escape
                dispatch.edge(fin_exc)
            body_exc = dispatch
        else:
            body_exc = fin_exc
        orelse = self._build(s.orelse, fin_nxt, fin_exc, fin_brk,
                             fin_cnt, fin_ret) if s.orelse else fin_nxt
        return self._build(s.body, orelse, body_exc, fin_brk, fin_cnt,
                           fin_ret)

    # ----------------------------------------------------------- lookup
    def nodes_for(self, stmt: ast.AST) -> List[Node]:
        return self.by_stmt.get(id(stmt), [])


# -------------------------------------------------------------- solver

def find_escape(cfg: CFG, starts: Iterable[Node],
                credit: Callable[[Node], bool],
                exc_only: bool = False) -> Optional[Tuple[int, bool]]:
    """Is there a path from ``starts`` to function exit that never passes
    a credit node? Returns ``(line, via_exception)`` of the escaping
    step, or None when every such path is credited.

    ``exc_only`` restricts the violation to paths that traverse at least
    one exception edge — the mode for contracts whose normal-path release
    is handed off dynamically (trace recorders adopted by the executor)
    but whose exception edges must still clean up.

    A credit node credits every edge leaving it (including its own
    exception edge): attempting the release is all any path can do.
    """
    seen: Set[Tuple[int, bool]] = set()
    # (node, saw_exc, last_line, last_was_exc)
    stack: List[Tuple[Node, bool, int, bool]] = []
    for n in starts:
        stack.append((n, False, n.line, False))
    best: Optional[Tuple[int, bool]] = None
    while stack:
        node, saw_exc, line, was_exc = stack.pop()
        key = (id(node), saw_exc)
        if key in seen:
            continue
        seen.add(key)
        if node.kind == "exit":
            if saw_exc or not exc_only:
                cand = (line, was_exc)
                if best is None or (cand[1] and not best[1]):
                    best = cand
                if best[1]:
                    return best
            continue
        if credit(node):
            continue  # every edge out of a credit node is credited
        nline = node.line or line
        for tgt, is_exc in node.succ:
            stack.append((tgt, saw_exc or is_exc,
                          nline if node.line else line, is_exc))
    return best


def hits_on_all_paths(cfg: CFG, credit: Callable[[Node], bool]) -> bool:
    """True when every entry→exit path passes a credit node — the
    summary predicate: "this helper releases on the caller's behalf"."""
    return find_escape(cfg, [cfg.entry], credit) is None


# ------------------------------------------------------- function index

def iter_functions(tree: ast.Module):
    """Every function/method in the module, with its dotted display
    name (``Class.method`` for methods)."""
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield (f"{prefix}{child.name}", child)
                yield from walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)
    yield from walk(tree, "")


class ModuleIndex:
    """Per-module function index with lazily built CFGs and one-level
    release summaries, shared by the flow-sensitive rule families."""

    def __init__(self, tree: ast.Module):
        self.functions: List[Tuple[str, ast.AST]] = list(iter_functions(tree))
        #: last-name → def (first wins), the lightweight call-graph key
        self.defs: Dict[str, ast.AST] = {}
        for name, fn in self.functions:
            self.defs.setdefault(fn.name, fn)
        self._cfgs: Dict[int, CFG] = {}

    def cfg(self, fn: ast.AST) -> CFG:
        c = self._cfgs.get(id(fn))
        if c is None:
            c = CFG(fn)
            self._cfgs[id(fn)] = c
        return c

    def release_summaries(
            self, is_release: Callable[[ast.AST], bool]) -> Set[str]:
        """Names of functions that perform a matching release on ALL of
        their own paths — iterated to a fixpoint so a helper calling a
        releasing helper is credited too (the "one level" the contract
        rules need, and then some)."""
        summary: Set[str] = set()
        changed = True
        rounds = 0
        while changed and rounds < 4:
            changed = False
            rounds += 1
            for name, fn in self.functions:
                if fn.name in summary:
                    continue

                def credit(node: Node, _sum=frozenset(summary)) -> bool:
                    for sub in node_header_calls(node):
                        if is_release(sub):
                            return True
                        if _call_last_name(sub) in _sum:
                            return True
                    return False

                if hits_on_all_paths(self.cfg(fn), credit):
                    summary.add(fn.name)
                    changed = True
        return summary

    def calls_anywhere(self, names: Set[str], depth: int = 3) -> Set[str]:
        """Names of functions that (transitively, bounded) call one of
        ``names`` anywhere in their body — the attribution-installer
        summary, where presence (not all-paths) is the right question."""
        installed: Set[str] = set()
        for _ in range(depth):
            grew = False
            for _, fn in self.functions:
                if fn.name in installed:
                    continue
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call):
                        last = _call_last_name(sub)
                        if last in names or last in installed:
                            installed.add(fn.name)
                            grew = True
                            break
            if not grew:
                break
        return installed


def stmt_header_parts(stmt: ast.AST) -> List[ast.AST]:
    """The expressions a CFG node for ``stmt`` actually represents —
    compound statements contribute only their header, so a call in an
    If *body* can't credit the If header node."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, (ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def node_header_calls(node: Node) -> List[ast.Call]:
    """Every call the CFG node itself evaluates (headers only, no
    descent into nested function/class definitions)."""
    if node.stmt is None:
        return []
    out: List[ast.Call] = []
    for part in stmt_header_parts(node.stmt):
        stack = [part]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
    return out


def _call_last_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def dotted(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, else ''."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""
