"""Rule family 2 — chaos-replay determinism.

The resilience plane's contract (PR 2) is that a seeded chaos run
replays **bit-identically**: every fault decision hashes stable
planner-minted identities, and every retry/recovery event sequence is a
pure function of (plan, seed). One unseeded ``random.*`` call, one
wall-clock read feeding a *decision*, or one unordered pool iteration in
a replay-critical module silently voids that contract — long after the
CI chaos test was written.

Scope: :data:`framework.REPLAY_CRITICAL` modules only. Flags:

- ``unseeded-random`` — module-level ``random.*`` / ``np.random.*``
  draws (seeded ``random.Random(seed)`` / ``default_rng(seed)``
  instances are fine — the rule flags the shared global streams);
- ``wallclock-decision`` — ``time.time()`` / ``time.monotonic()`` /
  ``time.perf_counter()`` inside an ``if``/``while`` test or a
  comparison: a wall-clock read steering control flow rather than
  feeding a metric. Injected-clock indirection (``self.clock()``) is
  the sanctioned pattern and is not flagged;
- ``unordered-pool-iteration`` — ``as_completed(...)`` /
  ``imap_unordered(...)`` without a downstream re-order: completion
  order is scheduler noise, so any stateful consumer diverges between
  runs.
"""

from __future__ import annotations

import ast
from typing import List

from .framework import REPLAY_CRITICAL, Finding, SourceFile, call_name

_RANDOM_FNS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "random_sample", "rand", "randn",
    "permutation", "bytes", "getrandbits",
}

_CLOCK_CALLS = ("time.time", "time.monotonic", "time.perf_counter",
                "monotonic", "perf_counter")

_UNORDERED = ("as_completed", "imap_unordered")


def _is_unseeded_random(node: ast.Call) -> bool:
    name = call_name(node)
    parts = name.split(".")
    if len(parts) < 2:
        return False
    # random.X(...) / np.random.X(...) — the process-global streams
    if parts[-2] == "random" and parts[-1] in _RANDOM_FNS:
        return True
    return False


def _clock_calls(node: ast.AST) -> List[ast.Call]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and call_name(sub) in _CLOCK_CALLS:
            out.append(sub)
    return out


def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if sf.path not in REPLAY_CRITICAL:
            continue
        decision_clocks = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.If, ast.While)):
                for c in _clock_calls(node.test):
                    decision_clocks.add(id(c))
                    out.append(Finding(
                        "wallclock-decision", sf.path, c.lineno,
                        f"{call_name(c)}() steers an "
                        f"{'if' if isinstance(node, ast.If) else 'while'} "
                        f"branch in a replay-critical module — inject a "
                        f"clock (the RetryPolicy pattern) or justify"))
            elif isinstance(node, ast.Compare):
                for c in _clock_calls(node):
                    if id(c) not in decision_clocks:
                        decision_clocks.add(id(c))
                        out.append(Finding(
                            "wallclock-decision", sf.path, c.lineno,
                            f"{call_name(c)}() inside a comparison in a "
                            f"replay-critical module — decisions must not "
                            f"read the wall clock directly"))
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_unseeded_random(node):
                out.append(Finding(
                    "unseeded-random", sf.path, node.lineno,
                    f"{call_name(node)}() draws from the process-global "
                    f"random stream in a replay-critical module — use a "
                    f"seeded instance keyed on a stable identity"))
            name = call_name(node).rsplit(".", 1)[-1]
            if name in _UNORDERED:
                out.append(Finding(
                    "unordered-pool-iteration", sf.path, node.lineno,
                    f"{name}() yields futures in completion order — "
                    f"replay-critical consumers must re-order results "
                    f"(or iterate the future list in submit order)"))
    return out
