"""Rule family 5 — paired-lifecycle resource contracts (flow-sensitive).

The serving / tracing / shuffle planes run on acquire/release pairs whose
break-even is invisible to single-statement rules: admission bytes
acquired at submit must be released on every done / failed / cancelled
path, a trace recorder registered at query start must be unregistered on
every error path, a ShuffleCache's spill directory must be cleaned up or
handed to the shuffle server, a locally created thread pool must be shut
down. Each invariant is one entry in the declarative :data:`CONTRACTS`
table; the must-reach solver (:mod:`.dataflow`) then proves the paired
release reachable on all exit paths — *including the exception edges*,
which is where every one of the real bugs this family has caught lived.

Adding a contract for new work (the spill / collective-shuffle push) is
one table entry: name the acquire call, the release call(s), the pairing
style, and whether the normal path may hand ownership off dynamically
(``mode="exc"``) or must release locally (``mode="all"``).

Release credit, in decreasing strength:

- a matching release call on the same receiver (event style) or tracked
  name (object style);
- a ``finally`` that releases — the CFG instantiates finally per
  continuation, so this credits exactly the paths that run it;
- a call to a same-module helper that releases on ALL of its own paths
  (one-level call summaries, iterated);
- object style only: ownership transfer — the resource is returned,
  yielded, stored into an attribute/container, or passed whole to
  another call (e.g. ``server.register(cache)``).

A second, syntactic check rides along: ``scope-helper-not-with`` — the
engine's context installers (``cancel_scope``, ``tracing.attach``,
``observability.attributed``, ``nested_scope``, ``tracing.span``) only
uninstall via ``__exit__``, so calling one outside a ``with`` item (and
never entering it) installs a scope that nothing removes.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from . import dataflow
from .dataflow import CFG, ModuleIndex, Node, dotted
from .framework import Finding, SourceFile

#: receiver last-names that look like a memory/admission manager — the
#: engine's uniform naming (self.mem, self.admission, mm, manager)
_MEM_RECV = re.compile(r"(^|\.)(mem|memory|admission|manager|mm)$")


@dataclasses.dataclass(frozen=True)
class Contract:
    rule: str               # finding id (pragma target)
    style: str              # "event" | "object"
    mode: str               # "all" | "exc" (exception edges only)
    acquire: Tuple[str, ...]        # call last-names that acquire
    release: Tuple[str, ...]        # call last-names that release
    hint: str
    #: event style: receiver pattern the acquire must match (None = any)
    recv: Optional[re.Pattern] = None
    #: object style: callee last-names that do NOT take ownership when
    #: the tracked object is passed as an argument
    non_owning: Tuple[str, ...] = ()
    #: modules (path suffixes) whose own definitions are exempt
    defining: Tuple[str, ...] = ()
    #: object style: a release call credits regardless of its arguments
    #: — for resources adopted invisibly through thread-local context
    #: (the stats ctx picks the current trace up via tracing.current()),
    #: where the finalize chokepoint never names the tracked binding
    release_anywhere: bool = False


#: The contract table. New acquire/release pairs (spill partitions,
#: collective-shuffle channels) are declared HERE — one entry, no solver
#: changes. README "Static analysis & sanitizers" documents the format.
CONTRACTS: Tuple[Contract, ...] = (
    Contract(
        rule="memory-admission-leak", style="event", mode="all",
        acquire=("acquire", "try_acquire"), release=("release",),
        recv=_MEM_RECV,
        hint="wrap the post-acquire region in try/finally: "
             "<mgr>.release(n), or release in every handler",
    ),
    Contract(
        rule="trace-recorder-leak", style="object", mode="exc",
        acquire=("maybe_start_trace",),
        release=("finalize_query", "unregister_recorder", "abort_trace",
                 "_end_trace", "set_last_stats"),
        non_owning=("attach", "span", "event", "run_attached",
                    "wire_headers", "SpanContext"),
        defining=("daft_tpu/tracing.py", "daft_tpu/observability.py"),
        release_anywhere=True,
        hint="on the exception path call tracing.abort_trace(ctx) (or "
             "finalize) before re-raising — a registered recorder must "
             "not outlive its query",
    ),
    Contract(
        rule="recorder-registration-leak", style="event", mode="exc",
        acquire=("register_recorder",), release=("unregister_recorder",),
        defining=("daft_tpu/tracing.py",),
        hint="pair register_recorder with unregister_recorder on every "
             "exception path (try/finally or the error handler)",
    ),
    Contract(
        rule="shuffle-cache-leak", style="object", mode="all",
        acquire=("ShuffleCache",), release=("cleanup",),
        defining=("daft_tpu/distributed/shuffle_service.py",),
        hint="cleanup() the cache on failure paths, or register it with "
             "the shuffle server (ownership transfer) before anything "
             "can raise",
    ),
    Contract(
        rule="device-slot-leak", style="object", mode="all",
        acquire=("acquire_slot",), release=("release_slot",),
        defining=("daft_tpu/device/pipeline.py",),
        hint="release_slot(slot) on every decline/error path, or hand "
             "the slot off whole (InflightItem) so the pipeline driver "
             "releases it on drain — an in-flight slot owns window "
             "occupancy AND memory admission",
    ),
    Contract(
        rule="pool-leak", style="object", mode="all",
        acquire=("ThreadPoolExecutor",), release=("shutdown",),
        hint="shutdown() the locally created pool on every exit path, "
             "use `with ThreadPoolExecutor(...)`, or store it on the "
             "owner that shuts it down",
    ),
    Contract(
        rule="spill-store-leak", style="object", mode="all",
        acquire=("SpillBuffer", "PartitionedSpillStore",
                 "SplitSpillBuffer", "materialize", "drain_to_store"),
        release=("close",),
        defining=("daft_tpu/execution/memory.py",
                  "daft_tpu/execution/out_of_core.py"),
        hint="close() the spill buffer/store on every exit path "
             "(try/finally or `with`), or transfer ownership by "
             "returning/storing it — a leaked store strands its spill "
             "directory until GC",
    ),
    Contract(
        rule="spill-writer-pool-leak", style="object", mode="all",
        acquire=("SpillWriterGroup",),
        release=("drain", "close"),
        defining=("daft_tpu/execution/spill_io.py",
                  "daft_tpu/execution/memory.py"),
        hint="drain() (raising — finalize paths) or close() (no-raise "
             "cleanup) the writer group on every exit path, or store it "
             "on the spill store that closes it — an abandoned group "
             "leaves chained writes racing the store's file deletion",
    ),
    Contract(
        rule="collective-lease-leak", style="event", mode="all",
        acquire=("acquire_collective",), release=("release_collective",),
        defining=("daft_tpu/distributed/topology.py",),
        hint="pair topology.acquire_collective(key) with "
             "release_collective in try/finally — a leaked lease makes a "
             "finished collective exchange group look forever in-flight "
             "(the /metrics gauge) and shadows its group key",
    ),
)

#: context installers that only uninstall via __exit__
_SCOPE_HELPERS = ("cancel_scope", "attach", "attributed", "nested_scope",
                  "span")
_SCOPE_DEFINING = ("daft_tpu/tracing.py", "daft_tpu/observability.py",
                   "daft_tpu/execution/cancellation.py")

RULE_IDS: Dict[str, Tuple[str, str]] = {
    c.rule: ("resources", c.hint) for c in CONTRACTS
}
RULE_IDS["scope-helper-not-with"] = (
    "resources",
    "use the installer as a `with` item (or assign then `with name:`) "
    "so the scope uninstalls on every path")


def _call_last(call: ast.Call) -> str:
    return dataflow._call_last_name(call)


def _recv_text(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return ""


def walk_local(node: ast.AST):
    """ast.walk that yields nested function/class/lambda nodes but does
    not descend into their bodies (they own their own CFGs)."""
    stack = [node]
    first = True
    while stack:
        n = stack.pop()
        nested = not first and isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda))
        first = False
        if not nested:
            stack.extend(ast.iter_child_nodes(n))
        yield n


# single-sourced in dataflow (the summaries use the same header rule)
_header_parts = dataflow.stmt_header_parts
node_calls = dataflow.node_header_calls


def _stmt_of(fn: ast.AST, cfg: CFG, target: ast.AST) -> Optional[ast.AST]:
    """The innermost statement owning ``target`` that has CFG nodes."""
    chain: List[ast.AST] = []

    def find(node) -> bool:
        for child in ast.iter_child_nodes(node):
            if child is target or find(child):
                chain.append(node)
                return True
        return False

    find(fn)
    for anc in chain:  # innermost-first
        if cfg.nodes_for(anc):
            return anc
    return None


def _acquire_start_nodes(cfg: CFG, fn: ast.AST,
                         call: ast.Call) -> List[Node]:
    """Where tracking begins for one acquire call: its statement's
    normal successors — or, for a conditional ``try_acquire`` used as an
    If test, the branch where the acquisition actually succeeded."""
    stmt = _stmt_of(fn, cfg, call)
    if stmt is None:
        return []
    starts: List[Node] = []
    negated = None
    if isinstance(stmt, ast.If):
        in_test = any(sub is call for sub in ast.walk(stmt.test))
        if in_test:
            negated = isinstance(stmt.test, ast.UnaryOp) and \
                isinstance(stmt.test.op, ast.Not)
    for node in cfg.nodes_for(stmt):
        if negated is not None and node.branch is not None:
            starts.append(node.branch[1] if negated else node.branch[0])
        else:
            starts.extend(t for t, is_exc in node.succ if not is_exc)
    return starts


# ------------------------------------------------------- event contracts

def _check_event(sf: SourceFile, idx: ModuleIndex, c: Contract,
                 out: List[Finding]) -> None:
    if any(sf.path.endswith(d) for d in c.defining):
        return

    def is_release(call: ast.Call, recv: Optional[str] = None) -> bool:
        if _call_last(call) not in c.release:
            return False
        return recv is None or _recv_text(call) == recv

    summaries = idx.release_summaries(lambda call: is_release(call))

    for name, fn in idx.functions:
        cfg = None
        for sub in walk_local(fn):
            if not (isinstance(sub, ast.Call)
                    and _call_last(sub) in c.acquire):
                continue
            recv = _recv_text(sub)
            if c.recv is not None and not c.recv.search(recv or "-"):
                continue
            cfg = cfg or idx.cfg(fn)
            starts = _acquire_start_nodes(cfg, fn, sub)
            if not starts:
                continue

            def credit(node: Node) -> bool:
                for call in node_calls(node):
                    if is_release(call, recv or None):
                        return True
                    if _call_last(call) in summaries:
                        return True
                return False

            esc = dataflow.find_escape(cfg, starts, credit,
                                       exc_only=(c.mode == "exc"))
            if esc is not None:
                line, via_exc = esc
                how = "on an exception path" if (c.mode == "exc"
                                                 or via_exc) \
                    else "normally"
                out.append(Finding(
                    c.rule, sf.path, sub.lineno,
                    f"{_call_last(sub)}() in {name}() can exit {how} "
                    f"near line {line} without reaching "
                    f"{'/'.join(c.release)} — paired release must cover "
                    f"every {'exception ' if c.mode == 'exc' else ''}path"))


# ------------------------------------------------------ object contracts

def _binding_name(fn: ast.AST, call: ast.Call) -> Optional[str]:
    """The local Name a constructor call is bound to, or None when the
    result escapes immediately (attribute/subscript target, call arg,
    return) or is discarded."""
    for sub in walk_local(fn):
        if isinstance(sub, ast.Assign) and sub.value is call:
            if len(sub.targets) == 1 and isinstance(sub.targets[0],
                                                    ast.Name):
                return sub.targets[0].id
            return None
    return None


def _captured_by_nested_def(fn: ast.AST, name: str) -> bool:
    for sub in walk_local(fn):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef, ast.Lambda)) and sub is not fn:
            for inner in ast.walk(sub):
                if isinstance(inner, ast.Name) and inner.id == name:
                    return True
    return False


def _in_with_item(fn: ast.AST, call: ast.Call) -> bool:
    for sub in walk_local(fn):
        if isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                if item.context_expr is call:
                    return True
    return False


def _globals_of(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in walk_local(fn):
        if isinstance(sub, ast.Global):
            out.update(sub.names)
    return out


def _object_credit_stmt(stmt: ast.AST, name: str, c: Contract) -> bool:
    """Does this statement release or transfer ownership of ``name``?"""
    for part in _header_parts(stmt):
        for sub in walk_local(part):
            if isinstance(sub, ast.Call):
                if _call_last(sub) in c.release \
                        and (c.release_anywhere
                             or _recv_text(sub) == name):
                    return True
                # ownership transfer: the object passed whole as an arg
                # to anything except the known non-owning helpers
                if _call_last(sub) not in c.non_owning:
                    for a in list(sub.args) + [k.value for k in
                                               sub.keywords]:
                        if isinstance(a, ast.Name) and a.id == name:
                            return True
            if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and sub.value is not None:
                # ownership transfer only when the object itself is
                # returned (bare, or as a tuple/list element) — returning
                # `pool.submit(...).result()` hands nothing over
                cands = [sub.value]
                if isinstance(sub.value, (ast.Tuple, ast.List)):
                    cands = list(sub.value.elts)
                for inner in cands:
                    if isinstance(inner, ast.Name) and inner.id == name:
                        return True
            if isinstance(sub, ast.Assign):
                # stored into an attribute / container / another name:
                # ownership moved; also a rebind ends this tracking
                for inner in ast.walk(sub):
                    if isinstance(inner, (ast.Attribute, ast.Subscript)) \
                            and isinstance(getattr(inner, "ctx", None),
                                           ast.Store):
                        for leaf in ast.walk(sub.value):
                            if isinstance(leaf, ast.Name) \
                                    and leaf.id == name:
                                return True
                for t in sub.targets:
                    if isinstance(t, ast.Name) and t.id == name \
                            and sub.value is not None \
                            and not isinstance(sub.value, ast.Call):
                        return True
    return False


def _check_object(sf: SourceFile, idx: ModuleIndex, c: Contract,
                  out: List[Finding]) -> None:
    if any(sf.path.endswith(d) for d in c.defining):
        return
    for name, fn in idx.functions:
        for sub in walk_local(fn):
            if not (isinstance(sub, ast.Call)
                    and _call_last(sub) in c.acquire):
                continue
            if _in_with_item(fn, sub):
                continue  # context-managed: released by __exit__
            bound = _binding_name(fn, sub)
            if bound is None:
                continue  # immediate escape / ownership elsewhere
            if bound in _globals_of(fn):
                continue  # module-global singleton, owner elsewhere
            if _captured_by_nested_def(fn, bound):
                continue  # closure-captured: lifetime is the closure's
            cfg = idx.cfg(fn)
            starts = _acquire_start_nodes(cfg, fn, sub)
            if not starts:
                continue

            def credit(node: Node) -> bool:
                return node.stmt is not None and _object_credit_stmt(
                    node.stmt, bound, c)

            esc = dataflow.find_escape(cfg, starts, credit,
                                       exc_only=(c.mode == "exc"))
            if esc is not None:
                line, via_exc = esc
                how = "on an exception path" if (c.mode == "exc"
                                                 or via_exc) \
                    else "normally"
                out.append(Finding(
                    c.rule, sf.path, sub.lineno,
                    f"{_call_last(sub)}() bound to {bound!r} in {name}() "
                    f"can exit {how} near line {line} without "
                    f"{'/'.join(c.release)}() or an ownership transfer"))


# ------------------------------------------------- scope-helper misuse

def _check_scope_helpers(sf: SourceFile, out: List[Finding]) -> None:
    if any(sf.path.endswith(d) for d in _SCOPE_DEFINING):
        return
    tree = sf.tree
    with_items: Set[int] = set()
    with_names: Set[str] = set()
    arg_positions: Set[int] = set()
    assigns: Dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                with_items.add(id(item.context_expr))
                if isinstance(item.context_expr, ast.Name):
                    with_names.add(item.context_expr.id)
        if isinstance(node, ast.Call):
            for a in list(node.args) + [k.value for k in node.keywords]:
                arg_positions.add(id(a))
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            assigns[id(node.value)] = node.targets[0].id
        if isinstance(node, ast.Return) and node.value is not None:
            arg_positions.add(id(node.value))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        last = _call_last(node)
        if last not in _SCOPE_HELPERS:
            continue
        recv = _recv_text(node)
        # `span`/`attach`/`attributed` must come off the tracing /
        # observability modules (or bare import); an arbitrary `.span()`
        # method on some other object is not ours
        if last in ("span", "attach") and recv not in (
                "", "tracing", "obs", "observability"):
            continue
        if last == "attributed" and recv not in ("", "obs",
                                                 "observability"):
            continue
        if id(node) in with_items or id(node) in arg_positions:
            continue
        bound = assigns.get(id(node))
        if bound is not None and bound in with_names:
            continue  # sp = tracing.span(...); ... with sp: — fine
        out.append(Finding(
            "scope-helper-not-with", sf.path, node.lineno,
            f"{last}() installs a thread scope that only uninstalls via "
            f"__exit__ — use it as a `with` item (or enter the bound "
            f"name in a `with`)"))


# ---------------------------------------------------------------- check

def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if not sf.path.startswith("daft_tpu/"):
            continue
        idx = ModuleIndex(sf.tree)
        for c in CONTRACTS:
            if c.style == "event":
                _check_event(sf, idx, c, out)
            else:
                _check_object(sf, idx, c, out)
        _check_scope_helpers(sf, out)
    return out
