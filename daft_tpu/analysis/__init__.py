"""Engine-aware static analysis + runtime sanitizers (daft-lint).

Import-light on purpose: runtime modules import :mod:`.knobs` (the
declarative ``DAFT_TPU_*`` registry + typed env accessors) on their hot
import path; the AST rule families and the CLI load lazily via
``python -m daft_tpu.analysis`` / :func:`run_analysis`.

Layout:

- ``knobs.py`` — the single knob registry + ``env_*`` accessors +
  README knob-table generation
- ``framework.py`` — findings, ``# daft-lint: allow(<rule>) -- reason``
  pragmas, source walking, baseline
- ``rule_knobs.py`` — knob registry discipline (one parse site, no
  unregistered reads, no code↔README drift)
- ``rule_determinism.py`` — chaos-replay determinism (no unseeded
  random / wall-clock decisions / unordered pool iteration in
  replay-critical modules)
- ``rule_locks.py`` — blocking calls under locks, unguarded
  module-state mutation
- ``rule_jit.py`` — device-kernel jit hygiene + jaxpr dispatch-contract
  re-verification (shared with tests/test_device_kernels.py)
- ``dataflow.py`` — per-function CFGs (try/except/finally/with edges),
  the must-reach-on-all-paths solver, and one-level call summaries —
  the flow engine under the v2 rule families
- ``rule_resources.py`` — declarative acquire/release contract table
  (memory admission, trace recorders, shuffle caches, pools) proved on
  every exit path, incl. exception edges
- ``rule_donation.py`` — donated-buffer safety: no reads of donated
  device planes after dispatch; ``DeviceTable.resident`` guards every
  donation
- ``rule_cancellation.py`` — every partition-drain loop polls the
  CancelToken (or pragmas the mechanism that covers it)
- ``rule_attribution.py`` — thread/pool spawns in engine modules thread
  per-query attribution onto their workers
- ``lock_sanitizer.py`` — runtime lock-order graph + cycle detection
  (``DAFT_TPU_SANITIZE=1``)
"""

from . import knobs  # noqa: F401  (the engine-facing surface)


def run_analysis(*args, **kwargs):
    from .framework import run_analysis as _run
    return _run(*args, **kwargs)
