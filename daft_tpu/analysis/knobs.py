"""The engine's single declarative knob registry.

Every ``DAFT_TPU_*`` environment knob is declared here exactly once:
name, parse type, default, owning module, README table group, and a
one-line doc. Runtime code reads knobs through the typed accessors
(``env_int`` / ``env_float`` / ``env_bool`` / ``env_bytes`` /
``env_str`` / ``env_raw``) so each knob has exactly ONE parse site —
``rule_knobs`` flags direct ``os.environ`` reads of ``DAFT_TPU_*``
names anywhere else, and the README knob tables are *generated* from
this registry (``python -m daft_tpu.analysis --knob-docs``), so code,
config and docs cannot drift silently.

Knobs mirrored by an ``ExecutionConfig`` field record it in
``config_field``; for those the env var is the per-process override and
the config field is the per-query value (``context._exec_config_from_env``
parses the same spelling — the registry documents both).

This module must stay import-light (os + dataclasses only): the whole
engine imports it.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

_FALSY = ("0", "false", "False", "no", "off", "")


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str           # full env var name (DAFT_TPU_…)
    type: str           # "int" | "float" | "bool" | "str" | "bytes"
    default: object     # parsed-type default; None = unset/contextual
    module: str         # owning module (repo-relative path)
    group: str          # README table group (one generated table each)
    doc: str            # one-line effect description for the table
    config_field: str = ""   # mirrored ExecutionConfig field, if any
    default_str: str = ""    # display override for the docs table


def _k(name, type_, default, module, group, doc, config_field="",
       default_str=""):
    return Knob(name, type_, default, module, group, doc, config_field,
                default_str)


_KNOBS: List[Knob] = [
    # ---------------------------------------------------------- core
    _k("DAFT_TPU_DEVICE", "bool", True, "daft_tpu/device/runtime.py",
       "core", "`0` disables the device tier entirely (pure host execution)"),
    _k("DAFT_TPU_DEVICE_FORCE", "str", None, "daft_tpu/device/costmodel.py",
       "core", "force device-vs-host routing: `1`/`device` forces device, "
       "`0`/`host` forces host; unset lets the measured-link cost model "
       "decide"),
    _k("DAFT_TPU_DEVICE_MIN_ROWS", "int", None, "daft_tpu/device/runtime.py",
       "core", "row floor below which ops stay on host (default: 4096 on a "
       "transfer-bound link, 0 when the backend shares host memory)",
       default_str="auto"),
    _k("DAFT_TPU_DEVICE_JOIN", "str", None, "daft_tpu/joins.py",
       "core", "`1`/`0` force-overrides the cost model's device-join "
       "routing; unset = modeled", default_str="auto"),
    _k("DAFT_TPU_DEVICE_INFLIGHT", "int", 2,
       "daft_tpu/device/pipeline.py", "core",
       "in-flight device pipeline slots: morsel N+1's host encode/upload "
       "overlaps morsel N's device compute and morsel N−1's "
       "download/decode; `0` = synchronous dispatch (forced under "
       "`DAFT_TPU_CHAOS_SERIALIZE=1` or an active fault plan)",
       config_field="tpu_device_inflight"),
    _k("DAFT_TPU_NATIVE", "bool", True, "daft_tpu/native/__init__.py",
       "core", "`0` disables the native (C-accelerated) expression paths"),
    _k("DAFT_TPU_ACTOR_POOL", "bool", True, "daft_tpu/actor_pool.py",
       "core", "`0` disables the stateful-UDF actor pool (inline execution)"),
    _k("DAFT_TPU_MEMORY_LIMIT", "bytes", None, "daft_tpu/execution/memory.py",
       "core", "process memory budget for scan admission + spill decisions "
       "(accepts byte suffixes, e.g. `64GiB`); unset = no budget"),
    _k("DAFT_TPU_SPILL_DIR", "str", None, "daft_tpu/execution/memory.py",
       "core", "spill directory root (default: a fresh "
       "`daft_tpu_spill_<pid>` under the system tmpdir)",
       default_str="tmpdir"),
    _k("DAFT_TPU_MESH_DEVICES", "int", None, "daft_tpu/parallel/mesh.py",
       "core", "caps the device-mesh axis length (default: all visible "
       "devices)", default_str="all"),
    _k("DAFT_TPU_MESH_MIN_ROWS", "int", None, "daft_tpu/parallel/mesh.py",
       "core", "force-override for mesh (multi-chip collective) admission: "
       "`0` forces the mesh path, `N` requires ≥N rows; unset lets the "
       "cost model price the collective from the calibrated ICI link rate "
       "(`DAFT_TPU_ICI_MBPS`)", default_str="cost model"),
    _k("DAFT_TPU_REAL_DEVICE", "bool", False, "tests/conftest.py",
       "core", "`1` runs the test suite against the real accelerator "
       "backend (no CPU forcing, no virtual mesh)"),
    # -------------------------------------------------------- device
    _k("DAFT_TPU_BACKEND_TIMEOUT", "float", 60.0,
       "daft_tpu/device/backend.py", "device",
       "seconds to wait for device-backend initialization before falling "
       "back to host"),
    _k("DAFT_TPU_COMPILATION_CACHE", "str", None,
       "daft_tpu/device/backend.py", "device",
       "persistent XLA compilation-cache directory (amortizes remote "
       "compiles across processes)"),
    _k("DAFT_TPU_COMPILE_CACHE", "str", None, "daft_tpu/device/backend.py",
       "device", "legacy alias of `DAFT_TPU_COMPILATION_CACHE`"),
    _k("DAFT_TPU_COMPILE_CACHE_DIR", "str", None,
       "daft_tpu/device/backend.py", "device",
       "explicit persistent XLA compilation-cache directory for ANY "
       "backend (CPU included — same-machine opt-in, bypassing the "
       "TPU-only default): AOT warm-up compiles survive process "
       "restarts"),
    _k("DAFT_TPU_SIZE_CLASSES", "str", "pow2", "daft_tpu/device/column.py",
       "device", "size-class ladder batches pad to: `pow2` (default), "
       "`pow4` (coarser: 4x steps, fewer distinct programs, more "
       "padding), or an explicit comma list of capacities (e.g. "
       "`1024,65536,1048576`); above the ladder top, capacities keep "
       "doubling", config_field="tpu_size_classes"),
    _k("DAFT_TPU_AOT_WARMUP", "bool", False, "daft_tpu/device/warmup.py",
       "device", "`1` AOT-compiles (`jit(...).lower().compile()`) the "
       "device kernel library — and any already-compiled fused "
       "fragments — over the size-class x strategy grid at serving "
       "startup, so first queries re-enter warm programs; pairs with "
       "`DAFT_TPU_COMPILE_CACHE_DIR` to survive restarts",
       config_field="tpu_aot_warmup"),
    _k("DAFT_TPU_FUSION", "str", "auto", "daft_tpu/physical/fusion.py",
       "device", "whole-query fusion regions (round 21): `auto` lets the "
       "cost model price each region (`costmodel.fusion_wins`), `1` "
       "force-admits every planned region, `0` disables the planner pass "
       "entirely", config_field="tpu_fusion"),
    _k("DAFT_TPU_FUSION_MAX_OPS", "int", 8, "daft_tpu/physical/fusion.py",
       "device", "region-size cap: the planner stops growing a fusion "
       "region past this many fused operators (bounds trace size and "
       "retrace surface)", config_field="tpu_fusion_max_ops"),
    _k("DAFT_TPU_HBM_CACHE_BYTES", "bytes", 8 * 1024 ** 3,
       "daft_tpu/device/cache.py", "device",
       "HBM budget for the resident-column cache (byte suffixes accepted)",
       default_str="8GiB"),
    _k("DAFT_TPU_LINK_RTT_MS", "float", None, "daft_tpu/device/costmodel.py",
       "device", "override the measured host↔device link RTT (ms)",
       default_str="measured"),
    _k("DAFT_TPU_LINK_UP_MBPS", "float", None,
       "daft_tpu/device/costmodel.py", "device",
       "override the measured host→device bandwidth (MB/s)",
       default_str="measured"),
    _k("DAFT_TPU_LINK_DOWN_MBPS", "float", None,
       "daft_tpu/device/costmodel.py", "device",
       "override the measured device→host bandwidth (MB/s)",
       default_str="measured"),
    _k("DAFT_TPU_LINK_CACHE", "bool", True, "daft_tpu/device/costmodel.py",
       "device", "`0` disables the persisted link-calibration profile "
       "(re-measures per process)"),
    _k("DAFT_TPU_LINK_CACHE_PATH", "str", None,
       "daft_tpu/device/costmodel.py", "device",
       "path of the persisted link profile (default: under the user cache "
       "dir)", default_str="auto"),
    _k("DAFT_TPU_PEAK_FLOPS", "float", 197e12,
       "daft_tpu/device/costmodel.py", "device",
       "chip peak FLOP/s the MFU ledger normalizes against (default: "
       "v5e bf16)", default_str="197e12"),
    _k("DAFT_TPU_HBM_BPS", "float", 819e9, "daft_tpu/device/costmodel.py",
       "device", "chip HBM bandwidth the roofline normalizes against",
       default_str="819e9"),
    _k("DAFT_TPU_DISPATCH_LOG", "str", None, "daft_tpu/device/costmodel.py",
       "device", "JSONL path appending one record per real device dispatch"),
    _k("DAFT_TPU_CACHE_INVEST", "bool", True,
       "daft_tpu/device/costmodel.py", "device",
       "`0` stops the cost model from pricing upload as an investment for "
       "cacheable (reused) columns"),
    # ------------------------------------------------------- shuffle
    _k("DAFT_TPU_DISTRIBUTED_SHUFFLE", "str", "flight",
       "daft_tpu/distributed/scheduler.py", "shuffle",
       "`driver` routes stage boundaries through the driver instead of "
       "the worker-to-worker shuffle plane"),
    _k("DAFT_TPU_SHUFFLE_TRANSPORT", "str", "flight",
       "daft_tpu/distributed/shuffle_service.py", "shuffle",
       "`flight` (Arrow Flight) or `http` partition transport"),
    _k("DAFT_TPU_SHUFFLE_HOST", "str", "127.0.0.1",
       "daft_tpu/distributed/shuffle_service.py", "shuffle",
       "bind address of the per-host partition server (`0.0.0.0` serves "
       "other hosts)"),
    _k("DAFT_TPU_SHUFFLE_ADVERTISE", "str", None,
       "daft_tpu/distributed/shuffle_service.py", "shuffle",
       "address peers are told to fetch from (default: the bind host, or "
       "`127.0.0.1` when bound to `0.0.0.0`)", default_str="bind host"),
    _k("DAFT_TPU_SHUFFLE_COMPRESSION", "str", "lz4",
       "daft_tpu/distributed/shuffle_service.py", "shuffle",
       "`lz4`/`zstd`/`none` IPC buffer compression for shuffle spill+wire; "
       "auto-falls back to `none` when the codec is missing from the "
       "pyarrow build"),
    _k("DAFT_TPU_SHUFFLE_FETCH_PARALLELISM", "int", 4,
       "daft_tpu/distributed/worker.py", "shuffle",
       "bounded per-source fetch concurrency for a reduce task's stage "
       "input; `DAFT_TPU_CHAOS_SERIALIZE=1` forces 1, and an active "
       "`DAFT_TPU_FAULT_SPEC` defaults it to 1 (set explicitly to combine)"),
    _k("DAFT_TPU_SHUFFLE_COMBINE", "str", "auto",
       "daft_tpu/distributed/scheduler.py", "shuffle",
       "map-side combine: `auto` (cost-model gated), `1` force, `0` "
       "escape hatch"),
    _k("DAFT_TPU_SHUFFLE_WIRE_MBPS", "float", 1000.0,
       "daft_tpu/device/costmodel.py", "shuffle",
       "wire bandwidth the combine and exchange-path cost models assume "
       "(set to the pod's real DCN number)"),
    _k("DAFT_TPU_ICI_MBPS", "float", None,
       "daft_tpu/device/costmodel.py", "shuffle",
       "override the measured intra-mesh (ICI) collective bandwidth "
       "(MB/s) the mesh-admission and exchange-path cost models price "
       "against", default_str="measured"),
    _k("DAFT_TPU_WORKER_TOPOLOGY", "str", None,
       "daft_tpu/distributed/topology.py", "shuffle",
       "mesh-group spec `name=w0,w1;name2=w2` naming which workers share "
       "a device mesh (pod/host); unset autodetects — all in-process "
       "workers share the process mesh when one is up, else every worker "
       "is its own group (Flight-only)",
       config_field="tpu_worker_topology", default_str="autodetect"),
    _k("DAFT_TPU_EXCHANGE_PATH", "str", "auto",
       "daft_tpu/distributed/topology.py", "shuffle",
       "hash-boundary exchange path: `collective` (intra-mesh ICI "
       "all_to_all), `hierarchical` (intra-mesh collective + one Flight "
       "stream per mesh), `flight` (per-worker streams), or `auto` "
       "(topology + cost model decide; chaos serialize forces `flight`)",
       config_field="tpu_exchange_path"),
    _k("DAFT_TPU_SHUFFLE_TIMEOUT", "float", 600.0,
       "daft_tpu/distributed/shuffle_service.py", "shuffle",
       "seconds a partition fetch may take before it fails as retryable"),
    _k("DAFT_TPU_SHUFFLE_TTL", "float", 86400.0,
       "daft_tpu/distributed/shuffle_service.py", "shuffle",
       "idle seconds before an orphaned shuffle directory is swept at "
       "service startup"),
    # ---------------------------------------------------- resilience
    _k("DAFT_TPU_FAULT_SPEC", "str", None,
       "daft_tpu/distributed/resilience.py", "resilience",
       "comma-separated `site:rate[:N][:sticky]` seeded fault-injection "
       "spec (`task`/`fetch`/`crash`/`rpc` sites)"),
    _k("DAFT_TPU_FAULT_SEED", "str", "0",
       "daft_tpu/distributed/resilience.py", "resilience",
       "seed hashed into every fault-injection decision (same seed → "
       "bit-identical chaos replay)"),
    _k("DAFT_TPU_CHAOS_SERIALIZE", "bool", False,
       "daft_tpu/distributed/worker.py", "resilience",
       "`1` serializes task execution (one task with all its retries at a "
       "time) and degrades the fetch/scan fast paths so chaos runs replay "
       "bit-identically"),
    _k("DAFT_TPU_MAX_RETRIES", "int", 3,
       "daft_tpu/distributed/resilience.py", "resilience",
       "bounded per-task retry budget"),
    _k("DAFT_TPU_RETRY_BACKOFF", "float", 0.05,
       "daft_tpu/distributed/resilience.py", "resilience",
       "retry backoff base seconds (deterministic jitter on top)"),
    _k("DAFT_TPU_RETRY_BACKOFF_CAP", "float", 2.0,
       "daft_tpu/distributed/resilience.py", "resilience",
       "retry backoff cap seconds"),
    _k("DAFT_TPU_QUARANTINE_AFTER", "int", 3,
       "daft_tpu/distributed/resilience.py", "resilience",
       "consecutive failures that quarantine a worker"),
    _k("DAFT_TPU_QUARANTINE_S", "float", 30.0,
       "daft_tpu/distributed/resilience.py", "resilience",
       "quarantine duration seconds (timed re-admission, never empty "
       "placement)"),
    _k("DAFT_TPU_TASK_TIMEOUT", "float", 0.0,
       "daft_tpu/distributed/resilience.py", "resilience",
       "seconds before a hung task attempt is abandoned as retryable "
       "(`0` = off)"),
    _k("DAFT_TPU_SPECULATIVE_MULTIPLIER", "float", 4.0,
       "daft_tpu/distributed/resilience.py", "resilience",
       "speculative-execution trigger: multiplier × median sibling "
       "duration (`0` = off)"),
    _k("DAFT_TPU_SPECULATIVE_MIN_S", "float", 0.5,
       "daft_tpu/distributed/resilience.py", "resilience",
       "minimum task age before speculation is considered"),
    _k("DAFT_TPU_WORKER_TIMEOUT", "float", 3600.0,
       "daft_tpu/distributed/remote_worker.py", "resilience",
       "remote-worker RPC timeout seconds"),
    _k("DAFT_TPU_NUM_WORKERS", "int", 0,
       "daft_tpu/runners/distributed_runner.py", "resilience",
       "distributed-runner worker count (`0` = auto from cpu count)",
       default_str="auto"),
    # --------------------------------------------------------- spill
    _k("DAFT_TPU_SPILL_JOIN", "str", "auto",
       "daft_tpu/execution/out_of_core.py", "spill",
       "grace hash join gate: `auto` (cost-model priced via "
       "`spill_plan_wins`), `1` forces partitioned execution, `0` "
       "restores the legacy materialize-then-refan join (no recursion)",
       config_field="tpu_spill_join"),
    _k("DAFT_TPU_SPILL_AGG", "str", "auto",
       "daft_tpu/execution/out_of_core.py", "spill",
       "spill-partitioned aggregation gate: `auto` spills the fused "
       "reducer's group state only when the budget can't hold it, `1` "
       "forces the spilling reducer, `0` declines the fusion for "
       "over-budget states (legacy exchange plan)",
       config_field="tpu_spill_agg"),
    _k("DAFT_TPU_SPILL_PARTITIONS", "int", 0,
       "daft_tpu/execution/out_of_core.py", "spill",
       "forces the first-level radix fanout of grace joins and spilling "
       "reducers; `0` lets planner size/NDV evidence pick the count",
       config_field="tpu_spill_partitions", default_str="evidence"),
    _k("DAFT_TPU_SPILL_MAX_DEPTH", "int", 3,
       "daft_tpu/execution/out_of_core.py", "spill",
       "rotated-radix recursion bound for a bucket that still exceeds "
       "its budget; exhaustion (an unsplittable all-duplicate key) falls "
       "through to an in-memory merge, counted in `depth_exhausted`",
       config_field="tpu_spill_max_depth"),
    _k("DAFT_TPU_SPILL_COMPRESSION", "str", None,
       "daft_tpu/execution/memory.py", "spill",
       "spill-file Arrow IPC buffer codec: `lz4` | `zstd` | `none`; "
       "unset inherits the shuffle plane's "
       "`DAFT_TPU_SHUFFLE_COMPRESSION` (default `lz4`); readers are "
       "self-describing, so mixed-codec spill dirs always read back",
       config_field="tpu_spill_compression", default_str="inherit"),
    _k("DAFT_TPU_SPILL_IO_PARALLELISM", "int", 4,
       "daft_tpu/execution/spill_io.py", "spill",
       "concurrent spill write/read tasks on the bounded spill-IO pool "
       "(writes chain per bucket, so push order is preserved); `0` "
       "restores the serial r19 path, which chaos serialize / an active "
       "fault plan also force", config_field="tpu_spill_io_parallelism"),
    _k("DAFT_TPU_GOVERNOR", "bool", True,
       "daft_tpu/execution/governor.py", "spill",
       "`0` disables the memory governor (RSS-watermark backpressure: "
       "budget/prefetch shrinks + bounded throttles); inert anyway "
       "without `DAFT_TPU_MEMORY_LIMIT` or under the chaos freeze"),
    _k("DAFT_TPU_GOVERNOR_HIGH", "float", 0.85,
       "daft_tpu/execution/governor.py", "spill",
       "RSS fraction of the memory limit that enters the pressured "
       "state (governor actions engage)",
       config_field="tpu_governor_high"),
    _k("DAFT_TPU_GOVERNOR_LOW", "float", 0.70,
       "daft_tpu/execution/governor.py", "spill",
       "RSS fraction of the memory limit that clears the pressured "
       "state — the hysteresis floor, clamped below the high watermark",
       config_field="tpu_governor_low"),
    # ------------------------------------------------------- io-scan
    _k("DAFT_TPU_IO_COALESCE_GAP", "bytes", 1 << 20,
       "daft_tpu/io/read_planner.py", "io-scan",
       "hole tolerance when coalescing needed byte ranges into requests",
       config_field="tpu_io_coalesce_gap", default_str="1MiB"),
    _k("DAFT_TPU_IO_MIN_REQUEST", "bytes", 8 << 20,
       "daft_tpu/io/read_planner.py", "io-scan",
       "request-size floor: sub-floor requests absorb neighbors across "
       "holes smaller than the floor",
       config_field="tpu_io_min_request", default_str="8MiB"),
    _k("DAFT_TPU_IO_RANGE_PARALLELISM", "int", 8,
       "daft_tpu/io/read_planner.py", "io-scan",
       "concurrent range GETs per source (capped by the source's "
       "`max_connections`)", config_field="tpu_io_range_parallelism"),
    _k("DAFT_TPU_IO_PLANNED_READS", "bool", True,
       "daft_tpu/io/read_planner.py", "io-scan",
       "`0` restores the naive per-column-chunk ranged-read path",
       config_field="tpu_io_planned_reads", default_str="1"),
    _k("DAFT_TPU_SCAN_PREFETCH", "int", 2,
       "daft_tpu/io/read_planner.py", "io-scan",
       "ScanTasks resolved ahead of the consumer; `0` disables; "
       "chaos/fault plans force the sequential path",
       config_field="tpu_scan_prefetch"),
    _k("DAFT_TPU_IO_STREAM_CHUNK", "bytes", 8 << 20,
       "daft_tpu/io/read_planner.py", "io-scan",
       "chunk size for streaming remote CSV/JSON reads",
       default_str="8MiB"),
    _k("DAFT_TPU_IO_INFER_BYTES", "bytes", 1 << 20,
       "daft_tpu/io/read_planner.py", "io-scan",
       "head-range budget for remote CSV/JSON schema inference (`0` → "
       "whole object)", default_str="1MiB"),
    # ------------------------------------------------------- serving
    _k("DAFT_TPU_SERVE_CONCURRENCY", "int", 4,
       "daft_tpu/serving/scheduler.py", "serving",
       "worker slots in the query scheduler (concurrently RUNNING "
       "queries)", config_field="tpu_serve_concurrency"),
    _k("DAFT_TPU_SERVE_QUEUE_DEPTH", "int", 64,
       "daft_tpu/serving/scheduler.py", "serving",
       "max queued (not yet running) queries before submissions are "
       "rejected `queue_full`", config_field="tpu_serve_queue_depth"),
    _k("DAFT_TPU_SERVE_QUEUE_TIMEOUT", "float", 30.0,
       "daft_tpu/serving/scheduler.py", "serving",
       "seconds a query may wait (in queue, then again in admission) "
       "before it is rejected `queue_timeout`; `0` waits forever",
       config_field="tpu_serve_queue_timeout"),
    _k("DAFT_TPU_SERVE_PLAN_CACHE_BYTES", "bytes", 64 << 20,
       "daft_tpu/serving/scheduler.py", "serving",
       "LRU budget for the compiled-plan cache (optimized+translated "
       "physical plans keyed by plan fingerprint); `0` disables",
       config_field="tpu_serve_plan_cache_bytes", default_str="64MiB"),
    _k("DAFT_TPU_SERVE_RESULT_CACHE_BYTES", "bytes", 64 << 20,
       "daft_tpu/serving/scheduler.py", "serving",
       "LRU budget for the result cache (materialized PartitionSets for "
       "identical literal-inclusive fingerprints over unchanged "
       "sources); `0` disables",
       config_field="tpu_serve_result_cache_bytes", default_str="64MiB"),
    _k("DAFT_TPU_SERVE_MEMORY", "bytes", None,
       "daft_tpu/serving/scheduler.py", "serving",
       "admission-control byte budget shared by concurrent queries "
       "(default: `DAFT_TPU_MEMORY_LIMIT`, else the breaker budget; "
       "`0` disables admission)", default_str="memory limit"),
    _k("DAFT_TPU_SERVE_OP_TTL", "float", 600.0,
       "daft_tpu/connect/server.py", "serving",
       "seconds a FINISHED reattachable Spark Connect operation retains "
       "its response buffer before the sweep drops it; `0` disables"),
    _k("DAFT_TPU_SERVE_OP_RETAIN_BYTES", "bytes", 64 << 20,
       "daft_tpu/connect/server.py", "serving",
       "per-session retained-response budget across finished "
       "operations (newest kept first); `0` disables",
       default_str="64MiB"),
    # -------------------------------------------------------- fleet
    _k("DAFT_TPU_FLEET_VNODES", "int", 64,
       "daft_tpu/fleet/router.py", "fleet",
       "virtual nodes per replica on the consistent-hash session ring "
       "(more vnodes = smoother session spread, larger ring)",
       config_field="tpu_fleet_vnodes"),
    _k("DAFT_TPU_FLEET_GOSSIP_S", "float", 2.0,
       "daft_tpu/fleet/replica.py", "fleet",
       "seconds between anti-entropy gossip rounds republishing this "
       "replica's learned state (calibration profile + admission "
       "history) to every peer; floored at `0.05`",
       config_field="tpu_fleet_gossip_s"),
    _k("DAFT_TPU_FLEET_DRAIN_TIMEOUT", "float", 10.0,
       "daft_tpu/fleet/router.py", "fleet",
       "seconds a draining replica may finish in-flight queries before "
       "the router cancels the stragglers and re-homes its sessions",
       config_field="tpu_fleet_drain_timeout"),
    _k("DAFT_TPU_FLEET_SIDECAR", "str", None,
       "daft_tpu/fleet/cache_tier.py", "fleet",
       "`host:port` of a fleet cache sidecar (`python -m "
       "daft_tpu.fleet.cache_tier --port N`); when set, replicas consult "
       "it for cross-process result-cache hits", default_str="off"),
    _k("DAFT_TPU_FLEET_SIDECAR_BYTES", "bytes", 256 << 20,
       "daft_tpu/fleet/cache_tier.py", "fleet",
       "LRU byte budget of the cache sidecar's blob store",
       default_str="256MiB"),
    _k("DAFT_TPU_FLEET_PEERS", "str", None,
       "daft_tpu/fleet/replica.py", "fleet",
       "comma-separated control addresses (`host:port`) of the peer "
       "replicas this one gossips with", default_str="none"),
    _k("DAFT_TPU_FLEET_REPLICA_ID", "str", None,
       "daft_tpu/fleet/replica.py", "fleet",
       "stable identity of this replica process (its gossip origin); "
       "`--replica-id` overrides", default_str="replica-0"),
    # ------------------------------------------------------ adaptive
    _k("DAFT_TPU_ADAPTIVE", "bool", False,
       "daft_tpu/distributed/replan.py", "adaptive",
       "`1` enables distributed runtime re-planning: boundary actuals "
       "(exact rows/bytes/NDV from map receipts and in-memory sources) "
       "rewrite downstream fragment estimates and re-pick combine "
       "gating, broadcast demotion, exchange rung and spill fanout "
       "before each stage dispatches; chaos-serialize or an active "
       "fault plan disables it (counted `replan_frozen`)",
       config_field="tpu_adaptive"),
    _k("DAFT_TPU_ADAPTIVE_HISTORY", "int", 512,
       "daft_tpu/physical/adaptive.py", "adaptive",
       "bound on the AdaptivePlanner decision history; appends past the "
       "cap evict the oldest entry (counted `history_evictions`)",
       config_field="tpu_adaptive_history"),
    _k("DAFT_TPU_CALIBRATION", "bool", False,
       "daft_tpu/device/calibration.py", "adaptive",
       "`1` enables the calibrated cost-model profile: observed "
       "`DEV_*` kernel rates, shuffle wire rate, ICI rate and the "
       "footer-NDV ratio override the hard-coded constants once the "
       "sample floor is met; frozen (defaults + no observations) under "
       "chaos-serialize or an active fault plan",
       config_field="tpu_calibration"),
    _k("DAFT_TPU_CALIBRATION_DIR", "str", None,
       "daft_tpu/device/calibration.py", "adaptive",
       "directory persisting one calibration profile per backend "
       "(`calibration_<backend>.json`, atomic rewrite); unset keeps the "
       "profile in-memory for the process lifetime",
       config_field="tpu_calibration_dir", default_str="in-memory"),
    _k("DAFT_TPU_CALIBRATION_ALPHA", "float", 0.2,
       "daft_tpu/device/calibration.py", "adaptive",
       "EWMA weight of one calibration observation (weighted samples "
       "collapse to one update; clamped to (0, 1])",
       config_field="tpu_calibration_alpha"),
    _k("DAFT_TPU_CALIBRATION_MIN_SAMPLES", "int", 8,
       "daft_tpu/device/calibration.py", "adaptive",
       "sample-count floor a learned constant needs before it overrides "
       "the hard-coded default",
       config_field="tpu_calibration_min_samples"),
    # ------------------------------------------------- observability
    _k("DAFT_TPU_XPLANE_DIR", "str", None, "daft_tpu/observability.py",
       "observability", "directory capturing a jax profiler "
       "(xplane/TensorBoard) trace per query"),
    _k("DAFT_TPU_CHROME_TRACE", "str", None, "daft_tpu/observability.py",
       "observability", "`1` or a path; writes a chrome://tracing JSON for "
       "the last execution"),
    _k("DAFT_TPU_PROGRESS", "bool", False, "daft_tpu/observability.py",
       "observability", "`1` enables a tqdm partition-progress bar"),
    _k("DAFT_TPU_OTLP_ENDPOINT", "str", None, "daft_tpu/observability.py",
       "observability", "OTLP/HTTP collector endpoint receiving per-query "
       "operator counters"),
    _k("DAFT_TPU_SANITIZE", "bool", False,
       "daft_tpu/analysis/lock_sanitizer.py", "observability",
       "`1` wraps engine lock acquisition in the runtime lock-order "
       "sanitizer (cycle detection, contention + blocking-while-held "
       "accounting; reported at pytest session end and in "
       "`explain(analyze=True)`)"),
    _k("DAFT_TPU_SANITIZE_RETRACE", "int", 0,
       "daft_tpu/analysis/retrace_sanitizer.py", "observability",
       "with `DAFT_TPU_SANITIZE=1`: arms the runtime retrace sanitizer "
       "— JAX trace events are charged against each registered dispatch "
       "site's per-signature budget x this multiplier; budget "
       "violations fail the pytest session; `0` = off (no listener, "
       "allocation-free scopes)"),
    _k("DAFT_TPU_SANITIZE_PLAN", "bool", False,
       "daft_tpu/analysis/plan_sanitizer.py", "observability",
       "`1` arms the runtime plan sanitizer: root-schema equality after "
       "every optimizer rule application, sampled hash-partition "
       "membership re-verification at exchange/spill boundaries, sort-"
       "order checks after Sort/TopN, and row-count conservation where "
       "the plan-contract registry declares it; violations fail the "
       "pytest session and surface in `explain(analyze=True)`, the "
       "flight recorder, and `/metrics`",
       config_field="tpu_sanitize_plan"),
    _k("DAFT_TPU_SANITIZE_PLAN_SAMPLE", "int", 64,
       "daft_tpu/analysis/plan_sanitizer.py", "observability",
       "rows sampled per boundary partition for the plan sanitizer's "
       "membership/order re-verification (higher = stronger checks, "
       "more re-hash work)",
       config_field="tpu_sanitize_plan_sample"),
    _k("DAFT_TPU_FUZZ_SEED", "int", 0,
       "daft_tpu/analysis/plan_fuzzer.py", "observability",
       "base seed of the differential plan fuzzer (`python -m "
       "daft_tpu.analysis --fuzz`); seed i of a run derives "
       "deterministically from it",
       config_field="tpu_fuzz_seed"),
    _k("DAFT_TPU_FUZZ_COUNT", "int", 50,
       "daft_tpu/analysis/plan_fuzzer.py", "observability",
       "how many fuzzer seeds a `--fuzz` run executes (each seed runs "
       "the full engine-mode matrix and compares answers bit-for-bit)",
       config_field="tpu_fuzz_count"),
    _k("DAFT_TPU_TRACE", "bool", False, "daft_tpu/tracing.py",
       "observability", "`1` enables the query-wide tracing plane: one "
       "span tree per query across scheduler/planner/device/pipeline/"
       "distributed layers, exported as Chrome trace JSON + OTLP spans"),
    _k("DAFT_TPU_TRACE_SAMPLE", "float", 1.0, "daft_tpu/tracing.py",
       "observability", "fraction of queries traced when tracing is on "
       "(deterministic per-query decision hashed from the trace key, "
       "never RNG)"),
    _k("DAFT_TPU_TRACE_DIR", "str", None, "daft_tpu/tracing.py",
       "observability", "directory receiving one perfetto-loadable "
       "`trace_<id>.json` per traced query (unset: traces stay "
       "in-memory for OTLP/flight-recorder export only)"),
    _k("DAFT_TPU_TRACE_MAX_SPANS", "int", 8192, "daft_tpu/tracing.py",
       "observability", "per-query span-buffer bound; spans past it are "
       "counted as dropped, never allocated"),
    _k("DAFT_TPU_OTLP_TIMEOUT", "float", 5.0, "daft_tpu/observability.py",
       "observability", "seconds an OTLP/HTTP export POST may take; a "
       "hung or failing collector is counted in `otlp_export_errors` "
       "and never stalls or fails the query"),
    _k("DAFT_TPU_QUERY_LOG", "str", None, "daft_tpu/tracing.py",
       "observability", "flight-recorder JSONL path persisting every "
       "query's stat blocks + trace summary + slow-query flag "
       "(size-capped rotation; served at `/api/history`)"),
    _k("DAFT_TPU_QUERY_LOG_BYTES", "bytes", 16 << 20,
       "daft_tpu/tracing.py", "observability",
       "flight-recorder rotation cap: when the JSONL exceeds it, it "
       "rotates to `<path>.1` (one generation kept)",
       default_str="16MiB"),
    _k("DAFT_TPU_SLOW_QUERY_MS", "float", 0.0, "daft_tpu/tracing.py",
       "observability", "wall-time threshold flagging a flight-recorder "
       "entry `slow: true` (`0` disables the flag)"),
    # -------------------------------------------------------- kernels
    _k("DAFT_TPU_KERNEL_GROUPBY", "str", "auto",
       "daft_tpu/device/costmodel.py", "kernels",
       "grouped-agg kernel strategy: `hash`/`sort` force one path, "
       "`auto` lets the cost model price one-pass hash vs radix-sort per "
       "dispatch (footer NDV evidence, load factor, key width)"),
    _k("DAFT_TPU_KERNEL_JOIN", "str", "auto",
       "daft_tpu/device/costmodel.py", "kernels",
       "device join kernel strategy: `hash`/`sort` force one path, "
       "`auto` prices hash build/probe vs the fused sort-merge per "
       "dispatch"),
    _k("DAFT_TPU_KERNEL_HASH_LOAD", "float", 0.5,
       "daft_tpu/device/pallas_kernels.py", "kernels",
       "max hash-table load factor: the table holds "
       "`out_cap / load` slots (lower = shorter probe chains, more HBM)"),
    _k("DAFT_TPU_KERNEL_HASH_MAX_BITS", "int", 128,
       "daft_tpu/device/pallas_kernels.py", "kernels",
       "widest packed key set (bits, ≤128) the hash kernels accept; "
       "wider key sets fall back to the LSD-radix sort path"),
    _k("DAFT_TPU_KERNEL_HASH_NDV_FRAC", "float", 0.5,
       "daft_tpu/device/costmodel.py", "kernels",
       "NDV/rows ratio above which the hash grouped-agg declines "
       "(near-unique keys make the table as large as the data — the "
       "one-pass advantage is gone)"),
    _k("DAFT_TPU_KERNEL_MAX_TABLE", "int", 1 << 20,
       "daft_tpu/device/pallas_kernels.py", "kernels",
       "hash-table slot ceiling (the table planes must fit on-chip "
       "memory; larger group budgets stay on the sort path)",
       default_str="1Mi"),
    _k("DAFT_TPU_KERNEL_BLOCK", "int", 1024,
       "daft_tpu/device/pallas_kernels.py", "kernels",
       "rows per Pallas grid step (rounded down to a power of two)"),
    _k("DAFT_TPU_KERNEL_INTERPRET", "str", None,
       "daft_tpu/device/pallas_kernels.py", "kernels",
       "`1` forces Pallas interpreter mode, `0` forces compiled kernels; "
       "unset: interpreter on CPU backends, compiled on silicon",
       default_str="auto"),
]

REGISTRY: Dict[str, Knob] = {k.name: k for k in _KNOBS}

GROUPS: List[str] = []
for _kn in _KNOBS:
    if _kn.group not in GROUPS:
        GROUPS.append(_kn.group)


class UnknownKnobError(KeyError):
    pass


def _checked(name: str, expect_type: Optional[str] = None) -> Knob:
    k = REGISTRY.get(name)
    if k is None:
        raise UnknownKnobError(
            f"{name} is not in the knob registry "
            f"(daft_tpu/analysis/knobs.py) — register it before reading it")
    if expect_type is not None and k.type != expect_type:
        raise TypeError(
            f"{name} is registered as type {k.type!r} but was read as "
            f"{expect_type!r} — one knob, one parse")
    return k


def parse(name: str, raw: str):
    """Parse a raw env string per the knob's registered type."""
    k = _checked(name)
    if k.type == "int":
        return int(raw)
    if k.type == "float":
        return float(raw)
    if k.type == "bool":
        return raw not in _FALSY
    if k.type == "bytes":
        from ..execution.memory import parse_bytes
        return parse_bytes(raw)
    return raw


_MISSING = object()


def _get(name: str, type_: str, default):
    k = _checked(name, type_)
    v = os.environ.get(name)
    if v is None or v == "":
        return k.default if default is _MISSING else default
    return parse(name, v)


def env_raw(name: str) -> Optional[str]:
    """The raw env string, or None when unset/empty. For sites whose
    semantics hinge on *presence* (tri-state force flags)."""
    _checked(name)
    v = os.environ.get(name)
    return None if v is None or v == "" else v


def env_is_set(name: str) -> bool:
    _checked(name)
    return os.environ.get(name) is not None


def env_int(name: str, default=_MISSING) -> Optional[int]:
    return _get(name, "int", default)


def env_float(name: str, default=_MISSING) -> Optional[float]:
    return _get(name, "float", default)


def env_bool(name: str, default=_MISSING) -> Optional[bool]:
    return _get(name, "bool", default)


def env_bytes(name: str, default=_MISSING) -> Optional[int]:
    return _get(name, "bytes", default)


def env_str(name: str, default=_MISSING) -> Optional[str]:
    return _get(name, "str", default)


# ------------------------------------------------------------------ docs

_TABLE_HEADER = "| env var | type | default | effect |\n| --- | --- | --- | --- |"


def _default_cell(k: Knob) -> str:
    if k.default_str:
        return f"`{k.default_str}`"
    if k.default is None:
        return "unset"
    if k.type == "bool":
        return "`1`" if k.default else "`0`"
    return f"`{k.default}`"


def knob_table_markdown(group: str) -> str:
    """One generated markdown table for a registry group."""
    rows = [_TABLE_HEADER]
    for k in _KNOBS:
        if k.group != group:
            continue
        doc = k.doc
        if k.config_field:
            doc += f" (mirrors `ExecutionConfig.{k.config_field}`)"
        rows.append(f"| `{k.name}` | {k.type} | {_default_cell(k)} | {doc} |")
    return "\n".join(rows)


def _marker(group: str, end: bool) -> str:
    word = "END" if end else "BEGIN"
    return f"<!-- knob-table:{group} {word} -->"


def knob_block(group: str) -> str:
    """A full generated README block, markers included."""
    return (f"{_marker(group, False)}\n"
            f"<!-- generated by `python -m daft_tpu.analysis --knob-docs "
            f"--write`; edit daft_tpu/analysis/knobs.py, not this table -->\n"
            f"{knob_table_markdown(group)}\n{_marker(group, True)}")


def readme_drift(readme_text: str) -> List[str]:
    """Human-readable drift problems between the registry and the README's
    generated knob-table blocks (empty list = in sync)."""
    problems = []
    for group in GROUPS:
        begin, end = _marker(group, False), _marker(group, True)
        i, j = readme_text.find(begin), readme_text.find(end)
        if i < 0 or j < 0:
            problems.append(
                f"README is missing the generated knob table for group "
                f"{group!r} (markers {begin} … {end})")
            continue
        current = readme_text[i:j + len(end)]
        if current != knob_block(group):
            problems.append(
                f"README knob table for group {group!r} is stale — "
                f"regenerate with `python -m daft_tpu.analysis --knob-docs "
                f"--write`")
    return problems


def update_readme(readme_path: str, write: bool = True) -> bool:
    """Rewrite every generated knob-table block in the README from the
    registry. Returns True when the file changed (or would change)."""
    with open(readme_path) as f:
        text = f.read()
    new = text
    for group in GROUPS:
        begin, end = _marker(group, False), _marker(group, True)
        i, j = new.find(begin), new.find(end)
        if i < 0 or j < 0:
            continue
        new = new[:i] + knob_block(group) + new[j + len(end):]
    changed = new != text
    if changed and write:
        with open(readme_path, "w") as f:
            f.write(new)
    return changed
