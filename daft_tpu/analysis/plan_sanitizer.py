"""Runtime plan sanitizer (opt-in: ``DAFT_TPU_SANITIZE_PLAN=1``).

``rule_plans`` proves statically that every plan node and optimizer rule
has a declared contract; this sanitizer proves the contracts HOLD while
queries run:

- **Optimizer rules** — after every ``Rule.apply`` the root schema must
  equal the pre-apply schema (names + dtypes, in order) for every rule
  ``plan_contracts.RULE_CONTRACTS`` registers as schema-preserving; an
  unregistered rule applying at runtime is itself a violation.
- **Exchange membership** — at every hash exchange the executor yields
  through, a head sample of each output partition is re-hashed with the
  engine's own ``partition_by_hash`` and must land back in the partition
  it was emitted as. This is the runtime twin of the r19 ``_hash_array``
  nullable-promotion escape: a spill/IPC round-trip that drifts a dtype
  re-hashes the same value differently, and this check catches it on
  every spill-plane, collective, and flight path (workers execute
  reconstructed Exchange nodes through the same wrap).
- **Sort order** — after Sort/TopN, each output partition's key columns
  must be identical to re-sorting that partition with the engine's own
  comparator (NaN-tolerant equality; key columns only, so unstable tie
  order is fine).
- **Row conservation** — where the registry declares it (Exchange,
  Sort, Project, Window, Concat, …), output rows must equal the sum of
  the node's input rows, checked only when the node and all its children
  ran exactly once and drained to completion (a Limit upstream
  legitimately truncates — those nodes simply never complete).

Violations fail the pytest session (``tests/conftest.py``), and
per-query deltas land in ``explain(analyze=True)``, the flight recorder,
and ``/metrics`` via the ``plansan`` stats plane.

Off by default and allocation-free when off: the executor hook returns
the iterator unchanged and the optimizer hook is a no-op.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional

from . import plan_contracts

#: cap on remembered violations (each is a string; a broken rewrite in a
#: tight loop should not OOM the test session before it can fail it)
MAX_VIOLATIONS = 100

#: cap on per-node-execution conservation records kept at once
_MAX_RECORDS = 65536


def _sample_rows() -> int:
    from . import knobs
    n = knobs.env_int("DAFT_TPU_SANITIZE_PLAN_SAMPLE")
    if n is None:
        try:
            from ..context import get_context
            n = get_context().execution_config.tpu_sanitize_plan_sample
        except Exception:
            n = 64
    return max(int(n), 1)


class _NodeRecord:
    __slots__ = ("rows", "parts", "starts", "completed", "ref")

    def __init__(self, node):
        self.rows = 0
        self.parts = 0
        self.starts = 0
        self.completed = False
        # identity guard: records are keyed by id(node), and CPython
        # recycles ids of freed nodes — a dead ref means the key was
        # reused by a DIFFERENT node and the record is stale
        try:
            self.ref = weakref.ref(node)
        except TypeError:
            self.ref = None


class PlanSanitizer:
    """Plan-contract runtime checks + violation accounting. One global
    instance backs the armed session; tests may build their own and
    drive the check methods directly."""

    def __init__(self, sample_rows: Optional[int] = None):
        self._meta = threading.Lock()
        self.sample_rows = sample_rows
        # monotonic counters (the observability plane)
        self.rule_checks = 0
        self.membership_parts = 0
        self.membership_rows = 0
        self.order_parts = 0
        self.conservation_checks = 0
        self.violations: List[str] = []
        # per-node-execution books, keyed by id(node): conservation needs
        # the child counts a sibling wrap recorded
        self._records: Dict[int, _NodeRecord] = {}

    def _violate(self, msg: str) -> None:
        with self._meta:
            if len(self.violations) < MAX_VIOLATIONS:
                self.violations.append(msg)

    # ---- optimizer hook ---------------------------------------------
    def check_rule(self, rule_name: str, before, after) -> None:
        """Root-schema equality after one ``Rule.apply``; ``before`` /
        ``after`` are the plan root schemas."""
        with self._meta:
            self.rule_checks += 1
        contract = plan_contracts.RULE_CONTRACTS.get(rule_name)
        if contract is None:
            self._violate(
                f"optimizer rule {rule_name} applied at runtime but is "
                f"not registered in plan_contracts.RULE_CONTRACTS")
            return
        if not contract.schema_preserving:
            return
        bf, af = list(before.fields), list(after.fields)
        if bf != af:
            self._violate(
                f"schema-preserving rule {rule_name} changed the root "
                f"schema: {[(f.name, str(f.dtype)) for f in bf]} -> "
                f"{[(f.name, str(f.dtype)) for f in af]}")

    # ---- executor hook ----------------------------------------------
    def wrap(self, node, it):
        """Wrap one node execution's output iterator with the boundary
        checks the registry declares for its type."""
        contract = plan_contracts.PHYSICAL_NODES.get(type(node).__name__)
        if contract is None:
            return it
        membership = (contract.membership_check
                      and getattr(node, "kind", "") == "hash"
                      and len(getattr(node, "by", ())) > 0
                      and getattr(node, "num_partitions", 1) > 1)
        order = contract.order_check and getattr(node, "sort_by", ())
        conserve = contract.row_conservation
        # even check-free nodes get row/part books: a parent's
        # conservation proof needs its children's counts
        sample_n = self.sample_rows or _sample_rows()

        def gen():
            rec = self._begin(node)
            samples = [] if membership else None
            try:
                for part in it:
                    rec.rows += len(part)
                    rec.parts += 1
                    if membership \
                            and len(samples) < node.num_partitions:
                        try:
                            samples.append(part.head(sample_n))
                        except Exception:
                            samples.append(None)
                    if order:
                        self._check_order(node, part)
                    yield part
                rec.completed = True
                if membership:
                    self._check_membership(node, rec, samples)
                if conserve:
                    self._check_conservation(node, rec)
            finally:
                self._prune()
        return gen()

    def _begin(self, node) -> _NodeRecord:
        with self._meta:
            rec = self._records.get(id(node))
            if rec is not None and (rec.ref is None
                                    or rec.ref() is not node):
                rec = None  # id recycled onto a different node object
            if rec is None:
                rec = _NodeRecord(node)
                self._records[id(node)] = rec
            else:
                # re-execution of the same node object (AQE rounds,
                # repeated collects): reset the books; conservation
                # only ever compares single-start executions
                rec.rows = 0
                rec.parts = 0
                rec.completed = False
            rec.starts += 1
            return rec

    def _prune(self) -> None:
        with self._meta:
            if len(self._records) > _MAX_RECORDS:
                self._records.clear()

    # ---- membership --------------------------------------------------
    def _check_membership(self, node, rec, samples) -> None:
        """Sampled hash-partition membership: re-hash each output
        partition's head with the engine's own partition_by_hash and
        require it to land back where it was emitted. Skipped when the
        yielded partition count differs from the planned one (AQE bucket
        coalescing re-maps indices — conservation still covers those)."""
        if rec.parts != node.num_partitions:
            return
        n = node.num_partitions
        for i, sample in enumerate(samples):
            if sample is None or len(sample) == 0:
                continue
            try:
                parts = sample.partition_by_hash(list(node.by), n)
            except Exception as exc:
                self._violate(
                    f"Exchange(hash) membership re-hash failed on "
                    f"partition {i}/{n}: {exc!r}")
                return
            with self._meta:
                self.membership_parts += 1
                self.membership_rows += len(sample)
            stray = {j: len(p) for j, p in enumerate(parts)
                     if j != i and len(p) > 0}
            if stray:
                self._violate(
                    f"Exchange(hash) membership violation: "
                    f"{sum(stray.values())} of {len(sample)} sampled "
                    f"rows of output partition {i}/{n} re-hash into "
                    f"partition(s) {sorted(stray)} (keys "
                    f"{[e.name() for e in node.by]}) — partition "
                    f"membership drifted across the boundary")

    # ---- sort order --------------------------------------------------
    def _check_order(self, node, part) -> None:
        """Key columns of an emitted Sort/TopN partition must equal the
        key columns after re-sorting it with the engine's comparator."""
        names = []
        for e in node.sort_by:
            try:
                names.append(e.name())
            except Exception:
                return  # un-named key expression: cannot check cheaply
        try:
            got = part.to_pydict()
            want = part.sort(list(node.sort_by),
                             list(node.descending),
                             list(node.nulls_first)).to_pydict()
        except Exception:
            return
        if any(nm not in got for nm in names):
            return
        with self._meta:
            self.order_parts += 1
        for nm in names:
            if not _values_equal(got[nm], want[nm]):
                self._violate(
                    f"{type(node).__name__} emitted an unsorted "
                    f"partition: key column {nm!r} differs from the "
                    f"engine-sorted order (descending="
                    f"{list(node.descending)}, nulls_first="
                    f"{list(node.nulls_first)})")
                return

    # ---- row conservation -------------------------------------------
    def _check_conservation(self, node, rec: _NodeRecord) -> None:
        """Output rows == sum of input rows, judged only when this node
        and every child executed exactly once and drained fully."""
        if rec.starts != 1:
            return
        with self._meta:
            child_recs = []
            for c in node.children:
                cr = self._records.get(id(c))
                if cr is not None and (cr.ref is None
                                       or cr.ref() is not c):
                    cr = None  # stale record under a recycled id
                child_recs.append(cr)
        total = 0
        for cr in child_recs:
            if cr is None or not cr.completed or cr.starts != 1:
                return  # child bypassed/abandoned/re-run: not judgeable
            total += cr.rows
        with self._meta:
            self.conservation_checks += 1
        if rec.rows != total:
            self._violate(
                f"{type(node).__name__} row-conservation violation: "
                f"{total} rows in, {rec.rows} rows out (registry "
                f"declares this node row-conserving)")

    # ---- reporting ---------------------------------------------------
    def summary(self) -> dict:
        with self._meta:
            return {
                "rule_checks": self.rule_checks,
                "membership_parts": self.membership_parts,
                "membership_rows": self.membership_rows,
                "order_parts": self.order_parts,
                "conservation_checks": self.conservation_checks,
                "violations": list(self.violations),
            }

    def report(self) -> str:
        s = self.summary()
        lines = [
            f"plan sanitizer: {s['rule_checks']} rule applications, "
            f"{s['membership_parts']} membership samples "
            f"({s['membership_rows']} rows re-hashed), "
            f"{s['order_parts']} order checks, "
            f"{s['conservation_checks']} conservation checks",
        ]
        if s["violations"]:
            lines.append(f"PLAN CONTRACT VIOLATIONS "
                         f"({len(s['violations'])}):")
            lines.extend(f"  {v}" for v in s["violations"])
        else:
            lines.append("no plan-contract violations")
        return "\n".join(lines)


def _values_equal(a: list, b: list) -> bool:
    """Element-wise equality, NaN-tolerant (NaN == NaN here: re-sorting
    may not preserve NaN identity but the ordering contract holds)."""
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x == y:
            continue
        if x is None or y is None:
            return False
        if x != x and y != y:  # both NaN
            continue
        return False
    return True


# ----------------------------------------------------------- global state

_global: Optional[PlanSanitizer] = None
_enabled = False


def enabled_by_env() -> bool:
    from . import knobs
    if knobs.env_bool("DAFT_TPU_SANITIZE_PLAN"):
        return True
    try:
        from ..context import get_context
        return bool(get_context().execution_config.tpu_sanitize_plan)
    except Exception:
        return False


def enable(sample_rows: Optional[int] = None) -> None:
    """Arm the global sanitizer. Idempotent; ``daft_tpu/__init__`` arms
    it beside the lock/retrace sanitizers when the knob is set."""
    global _global, _enabled
    if _enabled:
        return
    # daft-lint: allow(unguarded-global-mutation) -- single-threaded
    # bootstrap: enable() runs in conftest/__init__ before engine threads
    _global = PlanSanitizer(sample_rows)
    # daft-lint: allow(unguarded-global-mutation) -- same bootstrap; the
    # flag flips only after the sanitizer is fully constructed
    _enabled = True


def disable() -> None:
    global _global, _enabled
    if not _enabled:
        return
    # daft-lint: allow(unguarded-global-mutation) -- mirror of enable():
    # teardown runs on the single main thread at session/test end
    _enabled = False
    # daft-lint: allow(unguarded-global-mutation) -- same teardown; the
    # hooks no-op on a None global either way
    _global = None


def is_enabled() -> bool:
    return _enabled


def sanitizer() -> Optional[PlanSanitizer]:
    return _global


def summary() -> dict:
    return _global.summary() if _global is not None else {}


def report() -> str:
    return _global.report() if _global is not None \
        else "plan sanitizer: disabled"


# ------------------------------------------------------------ engine hooks

def check_rule(rule_name: str, before, after) -> None:
    """Optimizer hook: schema equality after one rule application."""
    san = _global
    if _enabled and san is not None:
        san.check_rule(rule_name, before, after)


def wrap_node(node, it):
    """Executor hook: boundary checks on one node execution's output.
    Returns ``it`` unchanged when disarmed — zero overhead."""
    san = _global
    if not _enabled or san is None:
        return it
    return san.wrap(node, it)


def check_grace_pair(bucket: int, num_buckets: int, by, part) -> None:
    """Grace-join hook: a sampled bucket batch must re-hash into its own
    bucket (depth-0 radix split is contractually ``h % n``, bit-identical
    to ``partition_by_hash``)."""
    san = _global
    if not _enabled or san is None or part is None or len(part) == 0:
        return
    try:
        sample = part.head(san.sample_rows or _sample_rows())
        parts = sample.partition_by_hash(list(by), num_buckets)
    except Exception:
        return  # non-expression keys / empty: nothing to judge
    with san._meta:
        san.membership_parts += 1
        san.membership_rows += len(sample)
    stray = {j: len(p) for j, p in enumerate(parts)
             if j != bucket and len(p) > 0}
    if stray:
        san._violate(
            f"grace-join bucket membership violation: "
            f"{sum(stray.values())} of {len(sample)} sampled rows of "
            f"bucket {bucket}/{num_buckets} re-hash into bucket(s) "
            f"{sorted(stray)} — spill round-trip drifted the hash")


# -------------------------------------------- observability integration

def counters_snapshot() -> Dict[str, float]:
    """Monotonic counters for per-query deltas (observability pattern:
    snapshot at query start, diff at finish)."""
    san = _global
    if not _enabled or san is None:
        return {}
    s = san.summary()
    return {"rule_checks": s["rule_checks"],
            "membership_parts": s["membership_parts"],
            "membership_rows": s["membership_rows"],
            "order_parts": s["order_parts"],
            "conservation_checks": s["conservation_checks"],
            "violations": len(s["violations"])}


def counters_delta(before: Dict[str, float],
                   after: Dict[str, float]) -> Dict[str, float]:
    out = {k: round(after.get(k, 0) - before.get(k, 0), 6)
           for k in after}
    # total violations is a level, not a delta — report the absolute too
    san = _global
    if _enabled and san is not None:
        out["total_violations"] = len(san.summary()["violations"])
    return out
