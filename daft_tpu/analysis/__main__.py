"""daft-lint CLI: ``python -m daft_tpu.analysis``.

Exit status 0 = no non-baselined findings; 1 = findings. Also the
knob-docs generator: ``--knob-docs`` prints the generated README tables,
``--knob-docs --write`` rewrites the README's generated blocks in place.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m daft_tpu.analysis",
        description="engine-aware static analysis for daft_tpu")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files/dirs to scan "
                         "(default: daft_tpu tests bench.py)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings (incl. family + "
                         "fix hint)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID",
                    help="only report findings of this rule id "
                         "(repeatable) — burn-down filtering")
    ap.add_argument("--stats", action="store_true",
                    help="print a summary line: files scanned, functions "
                         "analyzed, per-family finding counts")
    ap.add_argument("--no-contracts", action="store_true",
                    help="skip the jaxpr dispatch-contract re-verification "
                         "(no jax import)")
    ap.add_argument("--no-readme", action="store_true",
                    help="skip the README knob-table drift check")
    ap.add_argument("--knob-docs", action="store_true",
                    help="print the generated knob tables and exit")
    ap.add_argument("--write", action="store_true",
                    help="with --knob-docs: rewrite README generated blocks")
    ap.add_argument("--fuzz", action="store_true",
                    help="run the differential plan fuzzer instead of the "
                         "static rules: seeded random queries, every "
                         "engine mode matrix vs the unoptimized reference")
    ap.add_argument("--seeds", type=int, default=None, metavar="N",
                    help="with --fuzz: number of seeds (default "
                         "DAFT_TPU_FUZZ_COUNT)")
    ap.add_argument("--seed", type=int, default=None,
                    help="with --fuzz: base seed (default "
                         "DAFT_TPU_FUZZ_SEED)")
    args = ap.parse_args(argv)

    # the dispatch-contract checks trace jaxprs; never touch a real TPU
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from . import knobs
    from .framework import DEFAULT_SUBDIRS, repo_root, run_analysis

    root = repo_root()

    if args.fuzz:
        from . import plan_fuzzer
        res = plan_fuzzer.run_fuzz(count=args.seeds, seed=args.seed,
                                   log=print)
        s = res.summary()
        if args.json:
            print(json.dumps({**s, "mismatches_detail": [
                {"seed": m.seed, "mode": m.mode, "ops": [list(o) for o in
                 m.ops], "detail": m.detail} for m in res.mismatches]},
                indent=2))
        else:
            for m in res.mismatches:
                print("plan fuzzer MISMATCH\n" + m.repro())
            for e in res.errors:
                print(f"plan fuzzer error: {e}")
            print(f"plan fuzzer: {s['seeds_run']} seeds, "
                  f"{s['cases_compared']} comparisons, "
                  f"{s['mismatches']} mismatches, {s['errors']} errors, "
                  f"{s['sanitizer_violations']} sanitizer violations")
        return 1 if (res.mismatches or res.errors
                     or res.sanitizer_violations) else 0

    if args.knob_docs:
        if args.write:
            changed = knobs.update_readme(os.path.join(root, "README.md"))
            print("README.md updated" if changed else "README.md up to date")
            return 0
        for group in knobs.GROUPS:
            print(f"### {group}\n{knobs.knob_table_markdown(group)}\n")
        return 0

    from .framework import known_rules
    if args.rule:
        rules = known_rules()
        unknown = [r for r in args.rule if r not in rules]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}; known: "
                  f"{', '.join(sorted(rules))}")
            return 2

    subdirs = tuple(args.paths) if args.paths else DEFAULT_SUBDIRS
    stats = {} if args.stats else None
    findings = run_analysis(root, subdirs=subdirs,
                            contracts=not args.no_contracts,
                            readme=not args.no_readme, stats=stats)
    if args.rule:
        findings = [f for f in findings if f.rule in args.rule]
        if stats is not None:
            # the stats line must describe the same (filtered) findings
            # the listing and exit code do
            by_family = {}
            for f in findings:
                by_family[f.family or "?"] = by_family.get(
                    f.family or "?", 0) + 1
            stats["findings_by_family"] = by_family
    if args.json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
            if f.hint:
                print(f"    hint: {f.hint}")
        print(f"daft-lint: {len(findings)} finding(s)")
    if stats is not None:
        fam = ", ".join(f"{k}={v}" for k, v in
                        sorted(stats["findings_by_family"].items())) \
            or "none"
        print(f"daft-lint stats: files={stats['files_scanned']} "
              f"functions={stats['functions_analyzed']} "
              f"rules={len(stats['rules'])} findings_by_family: {fam}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
