"""Rule family 3 — lock discipline.

24 modules across the shuffle/scan/resilience planes hold
``threading.Lock``/``RLock`` instances. Two static hazards recur:

- ``blocking-under-lock`` — a blocking call (sleep, network request,
  ``future.result()``, file/socket I/O, thread join) made while a lock
  is held. Every waiter on that lock now waits on the network/disk too;
  under contention this serializes the plane the lock was supposed to
  only *briefly* guard, and combined with a second lock it is half of a
  deadlock. The check is per-module AST plus a one-level call graph
  (a lock body calling a same-module helper that blocks is flagged at
  the call site).
- ``unguarded-global-mutation`` — a function rebinds module-level state
  (``global X``; ``X = ...``) outside any ``with <lock>:`` scope:
  check-then-set races under the free-threaded pools this engine runs.

Lock recognition is lexical (a context-manager expression whose final
name contains ``lock``) — matching this codebase's uniform naming. The
runtime lock-order sanitizer (``analysis/lock_sanitizer.py``) covers
what static analysis can't: cross-module acquisition cycles and
contention that only shows under load.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .framework import Finding, SourceFile, call_name

_STR_JOIN_PREFIXES = ("os.path", "posixpath", "ntpath")


def _is_lockish(expr: ast.AST) -> bool:
    from .framework import dotted_name
    name = call_name(expr) if isinstance(expr, ast.Call) \
        else dotted_name(expr)
    last = name.rsplit(".", 1)[-1].lower()
    return "lock" in last or "mutex" in last


def _blocking_reason(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if not name:
        return None
    first = name.split(".")[0]
    last = name.rsplit(".", 1)[-1]
    if name in ("time.sleep", "sleep"):
        return "time.sleep()"
    if first == "requests":
        return f"network I/O ({name})"
    if last == "urlopen":
        return "network I/O (urlopen)"
    if first == "subprocess":
        return f"subprocess ({name})"
    if name == "open":
        return "file I/O (open)"
    if first == "socket" and last in ("connect", "recv", "send", "sendall",
                                      "accept", "create_connection"):
        return f"socket I/O ({name})"
    if isinstance(node.func, ast.Attribute):
        recv = node.func.value
        if last == "result":
            return "future .result() wait"
        if last == "wait":
            return ".wait()"
        if last == "join":
            if isinstance(recv, ast.Constant) and isinstance(recv.value, str):
                return None     # ", ".join(...) — string building
            for pref in _STR_JOIN_PREFIXES:
                if name.startswith(pref + "."):
                    return None
            return ".join() wait"
    return None


def _local_defs(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """name → def for module functions AND methods (last-name keyed —
    a lightweight call graph, deliberately one level deep)."""
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    return defs


def _direct_blocking(body_nodes) -> List[Tuple[ast.Call, str]]:
    out = []
    for stmt in body_nodes:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                why = _blocking_reason(sub)
                if why:
                    out.append((sub, why))
    return out


def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if not sf.path.startswith("daft_tpu/"):
            continue
        defs = _local_defs(sf.tree)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.With) and any(
                    _is_lockish(item.context_expr) for item in node.items):
                out.extend(_check_lock_body(sf, node, defs))
        out.extend(_check_global_mutation(sf))
    return out


def _check_lock_body(sf: SourceFile, with_node: ast.With,
                     defs: Dict[str, ast.FunctionDef]) -> List[Finding]:
    out = []
    for call, why in _direct_blocking(with_node.body):
        out.append(Finding(
            "blocking-under-lock", sf.path, call.lineno,
            f"{why} while holding "
            f"{ast.unparse(with_node.items[0].context_expr)} — waiters on "
            f"the lock now wait on this too"))
    # one-level call graph: same-module helpers that block
    for stmt in with_node.body:
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            callee = defs.get(call_name(sub).rsplit(".", 1)[-1])
            if callee is None:
                continue
            inner = _direct_blocking(callee.body)
            if inner:
                _, why = inner[0]
                out.append(Finding(
                    "blocking-under-lock", sf.path, sub.lineno,
                    f"call to {callee.name}() (which does {why} at line "
                    f"{inner[0][0].lineno}) while holding "
                    f"{ast.unparse(with_node.items[0].context_expr)}"))
    return out


def _check_global_mutation(sf: SourceFile) -> List[Finding]:
    out = []
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        globals_declared = set()
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Global):
                globals_declared.update(stmt.names)
        if not globals_declared:
            continue
        hits: List[Tuple[str, int]] = []
        _walk_guarded(fn.body, False, globals_declared, hits)
        for name, line in hits:
            out.append(Finding(
                "unguarded-global-mutation", sf.path, line,
                f"module-level {name!r} rebound outside any `with <lock>:` "
                f"scope in {fn.name}() — check-then-set races under the "
                f"engine's thread pools"))
    return out


def _walk_guarded(stmts, inside_lock: bool, names, hits):
    for s in stmts:
        if isinstance(s, ast.With):
            locked = inside_lock or any(
                _is_lockish(item.context_expr) for item in s.items)
            _walk_guarded(s.body, locked, names, hits)
        elif isinstance(s, (ast.If, ast.For, ast.While)):
            _walk_guarded(s.body, inside_lock, names, hits)
            _walk_guarded(s.orelse, inside_lock, names, hits)
        elif isinstance(s, ast.Try):
            _walk_guarded(s.body, inside_lock, names, hits)
            for h in s.handlers:
                _walk_guarded(h.body, inside_lock, names, hits)
            _walk_guarded(s.orelse, inside_lock, names, hits)
            _walk_guarded(s.finalbody, inside_lock, names, hits)
        elif isinstance(s, (ast.Assign, ast.AugAssign)) and not inside_lock:
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in names:
                    hits.append((t.id, s.lineno))
