"""Rule family 4 — jit hygiene for the device tier.

The whole value of ``daft_tpu/device`` is *statically provable* dispatch
behavior (PR 1): the packed-key argsort compiles to ≤3 ``lax.sort``
operands for ANY key count, and the fused join runs build+probe+expand
as ONE jit program with no host round-trips between phases. Two ways to
silently lose that:

- host side effects inside a jit'd kernel (``print``/``open``/env
  reads) — they fire at trace time, not run time, and mask retracing;
- ``np.*`` math on traced values — numpy silently forces the tracer to
  concretize (a hidden device→host transfer per call), or fails only on
  the real accelerator. Trace-time ``np`` on *static* metadata (dtypes,
  shapes, pack plans) is the kernel idiom and stays allowed; the rule
  taints function parameters and flags value-computing ``np.*`` calls
  whose arguments derive from them.

Static rules: ``host-effect-in-jit``, ``np-in-jit``.

Contract re-verification (``check_dispatch_contracts``): rebuilds the
jaxprs and re-proves PR 1's numbers — ``dispatch-contract`` findings on
violation. PR 7 extends the same discipline to the hash-strategy Pallas
kernels: the hash grouped-agg is exactly ONE ``pallas_call`` (plus a
2-operand slot-compaction sort, within the ≤3-operand budget), the hash
join is exactly TWO ``pallas_call``s (build + probe) with zero
``lax.sort``, both are free of host-callback primitives, and key sets
wider than the 128-bit hash budget keep routing to the sort path. The
jaxpr-walking helpers here (:func:`max_sort_operands`,
:func:`count_primitive`, the ``*_jaxpr`` builders) are the single
source tests use too (``tests/test_device_kernels.py``).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .framework import Finding, SourceFile, call_name

KERNELS_PATH = "daft_tpu/device/kernels.py"

#: np attributes that are trace-time metadata, not value math
_NP_STATIC_OK = {
    "dtype", "iinfo", "finfo", "result_type", "promote_types", "can_cast",
    "issubdtype", "ndim", "shape", "ceil", "floor", "log2",
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bool_",
}

_HOST_EFFECTS = {"print", "open", "input", "breakpoint"}
_HOST_EFFECT_PREFIXES = ("os.environ", "os.getenv", "time.", "sys.std")


def _jit_function_names(tree: ast.Module) -> Set[str]:
    """Names of functions that end up inside ``jax.jit`` — via decorator
    (``@jax.jit`` / ``@partial(jax.jit, …)``) or wrap-site
    (``jax.jit(f, …)`` / ``partial(jax.jit, …)(f)``)."""
    jitted: Set[str] = set()

    def _dotted(node):
        from .framework import dotted_name
        return dotted_name(node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _dotted(dec) in ("jax.jit", "jit"):
                    jitted.add(node.name)
                elif isinstance(dec, ast.Call):
                    name = call_name(dec)
                    if name in ("jax.jit", "jit"):
                        jitted.add(node.name)
                    elif name.endswith("partial") and dec.args \
                            and _dotted(dec.args[0]) in ("jax.jit", "jit"):
                        jitted.add(node.name)
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("jax.jit", "jit"):
                if node.args and isinstance(node.args[0], ast.Name):
                    jitted.add(node.args[0].id)
            elif isinstance(node.func, ast.Call):
                inner = node.func
                if call_name(inner).endswith("partial") and inner.args \
                        and _dotted(inner.args[0]) in ("jax.jit", "jit"):
                    if node.args and isinstance(node.args[0], ast.Name):
                        jitted.add(node.args[0].id)
    return jitted


def _param_names(fn) -> Set[str]:
    names = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                names.add(arg.arg)
            if a.vararg:
                names.add(a.vararg.arg)
            if a.kwarg:
                names.add(a.kwarg.arg)
    return names


def _taint(fn) -> Set[str]:
    """Names (transitively) derived from the function's parameters —
    fixpoint over assignments, order-insensitive."""
    tainted = _param_names(fn)
    for _ in range(6):
        grew = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                value_names = {n.id for n in ast.walk(node.value)
                               if isinstance(n, ast.Name)}
                if value_names & tainted:
                    for tgt in node.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name) \
                                    and n.id not in tainted:
                                tainted.add(n.id)
                                grew = True
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                value_names = {n.id for n in ast.walk(it)
                               if isinstance(n, ast.Name)}
                if value_names & tainted:
                    tgt = node.target
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            grew = True
        if not grew:
            break
    return tainted


def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if not sf.path.startswith("daft_tpu/device/"):
            continue
        jitted = _jit_function_names(sf.tree)
        if not jitted:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in jitted:
                out.extend(_check_jit_body(sf, node))
    return out


def _check_jit_body(sf: SourceFile, fn) -> List[Finding]:
    out = []
    tainted = _taint(fn)
    from .framework import dotted_name
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in _HOST_EFFECTS \
                or any(name.startswith(p) for p in _HOST_EFFECT_PREFIXES):
            out.append(Finding(
                "host-effect-in-jit", sf.path, node.lineno,
                f"{name}() inside jit'd kernel {fn.name}() — fires at "
                f"trace time, not dispatch time"))
            continue
        parts = name.split(".")
        if len(parts) == 2 and parts[0] in ("np", "numpy") \
                and parts[1] not in _NP_STATIC_OK:
            arg_names = set()
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                for n in ast.walk(a):
                    if isinstance(n, ast.Name):
                        arg_names.add(n.id)
            if arg_names & tainted:
                out.append(Finding(
                    "np-in-jit", sf.path, node.lineno,
                    f"{name}() applied to traced value(s) "
                    f"({', '.join(sorted(arg_names & tainted))}) inside "
                    f"jit'd kernel {fn.name}() — forces host concretization; "
                    f"use jnp or mark static"))
    return out


# ---------------------------------------------------- dispatch contracts

#: the committed kernel contracts (PR 1): single source for the lint
#: runner and tests/test_device_kernels.py
ARGSORT_MAX_SORT_OPERANDS = 3
ARGSORT_CASES = ((1, "int64"), (2, "float32"), (3, "int64"),
                 (6, "int32"), (8, "float32"))
FORBIDDEN_IN_FUSED_JOIN = ("pure_callback", "io_callback",
                           "debug_callback", "callback")

PALLAS_PATH = "daft_tpu/device/pallas_kernels.py"
#: PR 7's hash-kernel contracts: the hash grouped-agg is ONE Pallas
#: program (build) plus a tiny 2-operand slot-compaction sort — within
#: the ≤3-operand budget; the hash join is exactly TWO Pallas programs
#: (build + probe) fused into one jit program with ZERO lax.sort. Both
#: contain zero host-callback primitives (same single-dispatch
#: discipline as the fused sort join). The >hash-budget key-width case
#: must keep returning None from ``hash_pack_words`` so dispatch sites
#: route wide key sets to the LSD-radix sort path.
HASH_AGG_PALLAS_CALLS = 1
HASH_JOIN_PALLAS_CALLS = 2
HASH_JOIN_MAX_SORT_OPERANDS = 0  # no sort anywhere in build/probe
#: 3 i64 keys pack to 195 bits — beyond the ≤128-bit hash-key budget
HASH_UNFIT_KEY_DTYPES = ("int64", "int64", "int64")


def max_sort_operands(jaxpr) -> int:
    """Deepest ``lax.sort`` operand count anywhere in a (closed) jaxpr."""
    mx = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "sort":
            mx = max(mx, len(eqn.invars))
        for sub in eqn.params.values():
            if hasattr(sub, "jaxpr"):
                mx = max(mx, max_sort_operands(sub.jaxpr))
    return mx


def count_primitive(jaxpr, name: str) -> int:
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == name:
            n += 1
        for sub in eqn.params.values():
            if hasattr(sub, "jaxpr"):
                n += count_primitive(sub.jaxpr, name)
    return n


def argsort_jaxpr(n_keys: int, dtype: str = "int64"):
    import jax
    import numpy as np
    from ..device import kernels as K
    C = 32
    keys = tuple(np.arange(C, dtype=dtype) for _ in range(n_keys))
    valids = tuple(np.ones(C, bool) for _ in range(n_keys))
    mask = np.ones(C, bool)
    flags = tuple(False for _ in range(n_keys))
    return jax.make_jaxpr(lambda ks, vs, m: K.argsort_kernel(
        ks, vs, m, flags, flags))(keys, valids, mask)


def grouped_agg_jaxpr(n_keys: int = 5):
    import jax
    import numpy as np
    from ..device import kernels as K
    C = 32
    keys = tuple(np.arange(C, dtype=np.int64) for _ in range(n_keys))
    ones = tuple(np.ones(C, bool) for _ in range(n_keys))
    mask = np.ones(C, bool)
    vals = (np.ones(C, np.float32),)
    return jax.make_jaxpr(
        lambda ks, kv, v, vv, m: K.grouped_agg_block_impl(
            ks, kv, v, vv, m, ("sum",), 16))(keys, ones, vals, (mask,), mask)


def join_fused_jaxpr(capacity: int = 64):
    import jax
    import numpy as np
    from ..device import kernels as K
    C = 32
    key = np.arange(C, dtype=np.int64)
    ones = np.ones(C, bool)
    return jax.make_jaxpr(
        lambda lk, lv, lm, rk, rv, rm: K.join_fused_impl(
            lk, lv, lm, rk, rv, rm, capacity))(
        key, ones, ones, key, ones, ones)


def hash_agg_jaxpr(n_keys: int = 2):
    """Jaxpr of the hash grouped-agg (interpret=True so the trace needs
    no silicon; the program structure is identical either way). i32 keys:
    two of them pack to 66 bits — a 2-word hash key within the budget."""
    import jax
    import numpy as np
    from ..device import pallas_kernels as pk
    C = 64
    keys = tuple(np.arange(C, dtype=np.int32) for _ in range(n_keys))
    ones = tuple(np.ones(C, bool) for _ in range(n_keys))
    mask = np.ones(C, bool)
    vals = (np.ones(C, np.float32),)
    return jax.make_jaxpr(
        lambda ks, kv, v, vv, m: pk.hash_grouped_agg_impl(
            ks, kv, v, vv, m, ("sum",), 16, interpret=True, block=16))(
        keys, ones, vals, (mask,), mask)


def hash_join_jaxpr(capacity: int = 128):
    """Jaxpr of the fused hash build/probe join."""
    import jax
    import numpy as np
    from ..device import pallas_kernels as pk
    C = 64
    key = np.arange(C, dtype=np.int64)
    ones = np.ones(C, bool)
    return jax.make_jaxpr(
        lambda lk, lv, lm, rk, rv, rm: pk.hash_join_impl(
            lk, lv, lm, rk, rv, rm, capacity, interpret=True, block=16))(
        key, ones, ones, key, ones, ones)


FRAGMENT_PATH = "daft_tpu/device/fragment.py"
#: round 21's whole-query compilation contract: a fusion region is ONE
#: jit program — its fresh jaxpr carries ZERO host-callback primitives
#: (an in-region callback would be a hidden host round-trip, the exact
#: thing fusion exists to eliminate), every lax.sort inside stays within
#: the ≤3-operand packed-code budget, and each region dispatch site is
#: declared in the registry with a finite per-signature trace budget.
REGION_SITES = ("region.chain", "region.topk", "region.join_agg",
                "region.build")


def _region_chain_jaxpr(topk: bool = False):
    """Fresh jaxpr of a representative chain/topk region program."""
    import jax
    import numpy as np
    from .. import col
    from ..schema import DataType, Field, Schema
    from ..device import fragment as F
    schema = Schema([Field("a", DataType.int64()),
                     Field("b", DataType.float64())])
    exprs = [(col("b") * 2.0).alias("b2"), col("a")]
    pred = col("a") > 10
    if topk:
        prog = F.get_fused_region(exprs, pred, schema,
                                  sort_by=(col("b"),), descending=(True,),
                                  nulls_first=(False,), limit=8,
                                  fused_ops=("Filter", "Project", "TopN"))
    else:
        prog = F.get_fused_region(exprs, pred, schema,
                                  fused_ops=("Filter", "Project"))
    if prog is None:
        raise RuntimeError("representative region program did not lower")
    C = 64
    arrays = {"a": np.arange(C, dtype=np.int64),
              "b": np.ones(C, np.float64)}
    valids = {"a": np.ones(C, bool), "b": np.ones(C, bool)}
    mask = np.ones(C, bool)
    return jax.make_jaxpr(lambda ar, va, m: prog._run_packed(
        ar, va, m, (), out_w=32))(arrays, valids, mask)


def _region_join_agg_jaxpr():
    """Fresh jaxpr of a representative join_agg region program."""
    import jax
    import numpy as np
    from .. import col
    from ..schema import DataType, Field, Schema
    from ..device import fragment as F
    src = Schema([Field("k", DataType.int64()),
                  Field("b", DataType.float64())])
    build = Schema([Field("k2", DataType.int64()),
                    Field("g", DataType.int64()),
                    Field("w", DataType.float64())])
    prog = F.get_fused_join_agg(
        group_exprs=[col("g")],
        child_exprs=[(col("b") * col("w")).alias("__v0__")],
        ops=("sum",), probe_pred=None, post_pred=None,
        lkey="k", rkey="k2", src_schema=src, build_schema=build,
        fused_ops=("HashJoin", "Project", "Aggregate"))
    if prog is None:
        raise RuntimeError("representative join_agg program did not lower")
    C = 64
    p_arrays = {"k": np.arange(C, dtype=np.int64),
                "b": np.ones(C, np.float64)}
    p_valids = {k: np.ones(C, bool) for k in p_arrays}
    b_arrays = {"g": np.arange(C, dtype=np.int64),
                "w": np.ones(C, np.float64)}
    b_valids = {k: np.ones(C, bool) for k in b_arrays}
    mask = np.ones(C, bool)
    b_sorted = np.arange(C, dtype=np.int64)
    b_perm = np.arange(C, dtype=np.int32)
    b_live = np.int32(C)
    return jax.make_jaxpr(
        lambda pa, pv, pm, ba, bv, bs, bp, bl: prog._run_packed(
            pa, pv, pm, (), ba, bv, bs, bp, bl, (), W=128, out_cap=32))(
        p_arrays, p_valids, mask, b_arrays, b_valids,
        b_sorted, b_perm, b_live)


def check_fusion_region_contracts() -> List[Finding]:
    """Round 21's fusion-region contract, re-proved from fresh jaxprs."""
    out: List[Finding] = []
    from . import dispatch_registry as reg
    for sid in REGION_SITES:
        if reg.budget_for(sid) is None:
            out.append(Finding(
                "fusion-region-contract", FRAGMENT_PATH, 1,
                f"region dispatch site {sid!r} is undeclared or exempt in "
                f"the dispatch registry — fusion regions must carry a "
                f"finite per-signature trace budget"))
    jaxprs = (("chain region", _region_chain_jaxpr(False)),
              ("topk region", _region_chain_jaxpr(True)),
              ("join_agg region", _region_join_agg_jaxpr()))
    for label, jx in jaxprs:
        for prim in FORBIDDEN_IN_FUSED_JOIN:
            k = count_primitive(jx.jaxpr, prim)
            if k:
                out.append(Finding(
                    "fusion-region-contract", FRAGMENT_PATH, 1,
                    f"{label} program contains {k} {prim} primitive(s) — "
                    f"whole-query compilation forbids host round-trips "
                    f"inside a fused region"))
        ops = max_sort_operands(jx.jaxpr)
        if ops > ARGSORT_MAX_SORT_OPERANDS:
            out.append(Finding(
                "fusion-region-contract", FRAGMENT_PATH, 1,
                f"{label} program sorts with {ops} operands (contract: "
                f"≤{ARGSORT_MAX_SORT_OPERANDS}) — the packed-code sort "
                f"budget applies inside regions too"))
    return out


def check_dispatch_contracts() -> List[Finding]:
    """Re-prove PR 1's dispatch contracts from freshly-built jaxprs."""
    out: List[Finding] = []
    try:
        for n_keys, dtype in ARGSORT_CASES:
            ops = max_sort_operands(argsort_jaxpr(n_keys, dtype).jaxpr)
            if ops > ARGSORT_MAX_SORT_OPERANDS:
                out.append(Finding(
                    "dispatch-contract", KERNELS_PATH, 1,
                    f"argsort_kernel({n_keys} {dtype} keys) compiles to a "
                    f"{ops}-operand lax.sort (contract: ≤"
                    f"{ARGSORT_MAX_SORT_OPERANDS}) — the operand-count "
                    f"compile cliff is back"))
        ops = max_sort_operands(grouped_agg_jaxpr().jaxpr)
        if ops > ARGSORT_MAX_SORT_OPERANDS:
            out.append(Finding(
                "dispatch-contract", KERNELS_PATH, 1,
                f"grouped_agg_block_impl sorts with {ops} operands "
                f"(contract: ≤{ARGSORT_MAX_SORT_OPERANDS})"))
        jx = join_fused_jaxpr()
        for prim in FORBIDDEN_IN_FUSED_JOIN:
            n = count_primitive(jx.jaxpr, prim)
            if n:
                out.append(Finding(
                    "dispatch-contract", KERNELS_PATH, 1,
                    f"join_fused_impl contains {n} {prim} primitive(s) — "
                    f"the single-dispatch contract forbids host "
                    f"round-trips inside the fused program"))
        if max_sort_operands(jx.jaxpr) > ARGSORT_MAX_SORT_OPERANDS:
            out.append(Finding(
                "dispatch-contract", KERNELS_PATH, 1,
                f"join_fused_impl build-side sort exceeds "
                f"{ARGSORT_MAX_SORT_OPERANDS} operands"))
        out.extend(_check_hash_contracts())
        out.extend(check_fusion_region_contracts())
    except Exception as exc:   # can't verify ⇒ say so, don't pass silently
        out.append(Finding(
            "dispatch-contract", KERNELS_PATH, 1,
            f"could not re-verify dispatch contracts: {exc!r} (run with "
            f"--no-contracts to skip)"))
    return out


def _check_hash_contracts() -> List[Finding]:
    """Re-prove PR 7's hash-kernel contracts from freshly-built jaxprs."""
    out: List[Finding] = []
    ha = hash_agg_jaxpr()
    n = count_primitive(ha.jaxpr, "pallas_call")
    if n != HASH_AGG_PALLAS_CALLS:
        out.append(Finding(
            "dispatch-contract", PALLAS_PATH, 1,
            f"hash_grouped_agg_impl contains {n} pallas_call(s) "
            f"(contract: exactly {HASH_AGG_PALLAS_CALLS} — one table-build "
            f"program, single-dispatch)"))
    ops = max_sort_operands(ha.jaxpr)
    if ops > ARGSORT_MAX_SORT_OPERANDS:
        out.append(Finding(
            "dispatch-contract", PALLAS_PATH, 1,
            f"hash_grouped_agg_impl slot compaction sorts with {ops} "
            f"operands (contract: ≤{ARGSORT_MAX_SORT_OPERANDS})"))
    hj = hash_join_jaxpr()
    n = count_primitive(hj.jaxpr, "pallas_call")
    if n != HASH_JOIN_PALLAS_CALLS:
        out.append(Finding(
            "dispatch-contract", PALLAS_PATH, 1,
            f"hash_join_impl contains {n} pallas_call(s) (contract: "
            f"exactly {HASH_JOIN_PALLAS_CALLS} — build + probe fused in "
            f"one jit program)"))
    if max_sort_operands(hj.jaxpr) > HASH_JOIN_MAX_SORT_OPERANDS:
        out.append(Finding(
            "dispatch-contract", PALLAS_PATH, 1,
            "hash_join_impl contains a lax.sort — the hash build/probe "
            "contract is sort-free (the sort formulation is the OTHER "
            "strategy)"))
    for jx, fn in ((ha, "hash_grouped_agg_impl"), (hj, "hash_join_impl")):
        for prim in FORBIDDEN_IN_FUSED_JOIN:
            k = count_primitive(jx.jaxpr, prim)
            if k:
                out.append(Finding(
                    "dispatch-contract", PALLAS_PATH, 1,
                    f"{fn} contains {k} {prim} primitive(s) — the "
                    f"single-dispatch contract forbids host round-trips "
                    f"inside the fused program"))
    # the width gate: key sets wider than the hash budget must keep
    # falling back (hash_pack_words → None routes dispatch sites to the
    # any-width LSD-radix sort path, itself re-proven above)
    import numpy as np
    from ..device import pallas_kernels as pk
    if pk.hash_pack_words([np.dtype(d) for d in
                           HASH_UNFIT_KEY_DTYPES]) is not None:
        out.append(Finding(
            "dispatch-contract", PALLAS_PATH, 1,
            f"hash_pack_words accepted a "
            f"{len(HASH_UNFIT_KEY_DTYPES)}-wide i64 key set (> the "
            f"128-bit hash-key budget) — wide keys must route to the "
            f"sort path"))
    return out
