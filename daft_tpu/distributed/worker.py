"""Worker abstraction for distributed stage execution.

Reference: the flotilla Worker/WorkerManager traits
(``src/daft-distributed/src/scheduling/worker.rs:13-25``) whose first
implementation is a Ray actor per node; here the first implementation is an
in-process worker (one per mesh device group / CPU slice), and the seam is
identical: ``submit`` returns a future of materialized partitions, so a
multi-host gRPC worker drops in without touching the scheduler.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..micropartition import MicroPartition
from ..physical import plan as pp


@dataclass
class ShuffleOutSpec:
    """Map-side instruction: partition this task's output into the
    worker-local shuffle cache instead of returning rows.

    ``kind``:
    - ``hash``  — hash-partition by ``by`` into ``num_partitions``.
    - ``store`` — store the whole output as partition 0 and (when
      ``sample_k`` > 0) return a key sample for driver-side boundary
      computation: phase 1 of the distributed range/sort protocol.
    - ``range`` — range-partition by ``by`` against ``boundaries_ipc``
      (arrow-IPC boundary rows): phase 2; rows move worker→worker, the
      driver only ever sees samples, boundaries and receipts."""

    num_partitions: int
    by: tuple  # key Expressions
    kind: str = "hash"
    descending: tuple = ()
    boundaries_ipc: Optional[bytes] = None
    sample_k: int = 0


@dataclass
class ShuffleResult:
    """Map-side receipt: where a task's shuffled output is served from
    (flotilla: the shuffle cache registration a reduce task fetches by)."""

    address: str
    shuffle_id: str
    num_partitions: int
    rows: int
    samples_ipc: Optional[bytes] = None


@dataclass
class FetchSpec:
    """Reduce-side stage input: pull partition ``partition`` from every
    listed (address, shuffle_id) map output and concat. ``keys`` are
    stable per-source identities (stage/map-task derived, NOT the
    run-specific shuffle uuid) so fault-injection decisions replay
    bit-identically across runs."""

    sources: List  # [(address, shuffle_id)]
    partition: int
    keys: Optional[List[str]] = None


@dataclass
class StageTask:
    """One dispatchable unit: an exchange-free plan fragment plus its
    stage-input bindings (flotilla's SwordfishTask shape,
    ``scheduling/task.rs:80``). ``stage_inputs`` values are either
    materialized partition lists or a ``FetchSpec`` the worker resolves
    through the shuffle service."""

    stage_id: int
    plan: pp.PhysicalPlan
    stage_inputs: Dict[int, object]
    task_idx: int = 0
    preferred_worker: Optional[str] = None
    shuffle_out: Optional[ShuffleOutSpec] = None
    # resilience plane: stable task identity for fault injection/lineage
    # (minted by the stage planner) and the dispatch attempt number (set
    # by the task supervisor; travels over the remote-worker wire)
    fault_key: str = ""
    attempt: int = 0


def resolve_stage_inputs(stage_inputs: Dict[int, object]
                         ) -> Dict[int, List[MicroPartition]]:
    """Materialize any FetchSpec bindings via the shuffle service."""
    from ..recordbatch import RecordBatch
    from .shuffle_service import fetch_partition
    out: Dict[int, List[MicroPartition]] = {}
    for sid, binding in stage_inputs.items():
        if isinstance(binding, FetchSpec):
            tables = []
            for j, (address, shuffle_id) in enumerate(binding.sources):
                fkey = binding.keys[j] \
                    if binding.keys and j < len(binding.keys) else None
                t = fetch_partition(address, shuffle_id, binding.partition,
                                    fault_key=fkey)
                if t is not None and t.num_rows:
                    tables.append(t)
            if tables:
                import pyarrow as pa
                merged = pa.concat_tables(tables)
                out[sid] = [MicroPartition.from_recordbatch(
                    RecordBatch.from_arrow_table(merged))]
            else:
                out[sid] = []
        else:
            out[sid] = binding
    return out


def run_task(task: StageTask) -> object:
    """Execute one stage task on the local streaming executor. Returns a
    partition list, or a ShuffleResult when the task shuffles out."""
    from ..execution.executor import LocalExecutor
    from .resilience import active_fault_plan
    plan = active_fault_plan()
    if plan is not None:  # injection site 1: task execution
        plan.maybe_fail("task",
                        task.fault_key or f"s{task.stage_id}.t{task.task_idx}",
                        attempt=task.attempt)
    ex = LocalExecutor()
    inputs = resolve_stage_inputs(task.stage_inputs)
    stream = ex.run(task.plan, stage_inputs=inputs)
    if task.shuffle_out is None:
        return list(stream)
    from ..recordbatch import RecordBatch
    from .shuffle_service import ShuffleCache, get_local_shuffle_server
    spec = task.shuffle_out
    by = list(spec.by)
    cache = ShuffleCache()
    rows = 0
    samples_ipc = None
    if spec.kind == "hash":
        for mp in stream:
            rows += len(mp)
            for i, piece in enumerate(
                    mp.partition_by_hash(by, spec.num_partitions)):
                if len(piece):
                    cache.push(i, piece.combined().to_arrow_table())
    elif spec.kind == "store":
        sampled = []
        for mp in stream:
            rows += len(mp)
            if len(mp):
                cache.push(0, mp.combined().to_arrow_table())
                if spec.sample_k > 0:
                    rb = mp.combined()
                    s = rb.sample(size=min(spec.sample_k, len(rb)))
                    sampled.append(s.eval_expression_list(by))
        if sampled:
            merged = RecordBatch.concat(sampled)
            if len(merged) > spec.sample_k:
                merged = merged.sample(size=spec.sample_k)
            samples_ipc = _ipc_bytes(merged.to_arrow_table())
    elif spec.kind == "range":
        boundaries = RecordBatch.from_arrow_table(
            _ipc_table(spec.boundaries_ipc))
        desc = list(spec.descending) or [False] * len(by)
        for mp in stream:
            rows += len(mp)
            for i, piece in enumerate(mp.combined().partition_by_range(
                    by, boundaries, desc)):
                if len(piece):
                    cache.push(i, piece.to_arrow_table())
    else:
        raise ValueError(f"shuffle-out kind {spec.kind!r}")
    server = get_local_shuffle_server()
    server.register(cache)
    return ShuffleResult(server.address, cache.shuffle_id,
                         spec.num_partitions, rows, samples_ipc)


def _ipc_bytes(table) -> bytes:
    import io

    import pyarrow as pa
    buf = io.BytesIO()
    with pa.ipc.new_stream(buf, table.schema) as w:
        w.write_table(table)
    return buf.getvalue()


def _ipc_table(data: bytes):
    import io

    import pyarrow as pa
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return r.read_all()


class Worker:
    """Abstract worker: executes StageTasks, reports capacity."""

    id: str
    num_slots: int

    def submit(self, task: StageTask) -> "cf.Future":
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InProcessWorker(Worker):
    """Runs stage fragments on a local streaming executor (per-host worker
    in a pod deployment; the only worker type on a single host)."""

    def __init__(self, worker_id: str, num_slots: int = 2):
        self.id = worker_id
        self.num_slots = num_slots
        self._pool = cf.ThreadPoolExecutor(
            max_workers=num_slots, thread_name_prefix=f"daft-tpu-{worker_id}")

    def submit(self, task: StageTask) -> "cf.Future":
        return self._pool.submit(run_task, task)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


@dataclass
class WorkerState:
    worker: Worker
    active: int = 0


class WorkerManager:
    """Tracks workers and in-flight load; routes submissions through a
    scheduling policy (reference: ``scheduling/worker.rs`` WorkerManager +
    dispatcher)."""

    def __init__(self, workers: List[Worker]):
        self._lock = threading.Lock()
        self.states: Dict[str, WorkerState] = {
            w.id: WorkerState(w) for w in workers}

    @property
    def worker_ids(self) -> List[str]:
        return list(self.states)

    def snapshot(self) -> List[WorkerState]:
        with self._lock:
            return list(self.states.values())

    def dispatch(self, task: StageTask, worker_id: str
                 ) -> "cf.Future[List[MicroPartition]]":
        with self._lock:
            st = self.states[worker_id]
            st.active += 1
        fut = st.worker.submit(task)

        def _done(_):
            with self._lock:
                st.active -= 1

        fut.add_done_callback(_done)
        return fut

    def shutdown(self) -> None:
        for st in self.snapshot():
            st.worker.shutdown()
