"""Worker abstraction for distributed stage execution.

Reference: the flotilla Worker/WorkerManager traits
(``src/daft-distributed/src/scheduling/worker.rs:13-25``) whose first
implementation is a Ray actor per node; here the first implementation is an
in-process worker (one per mesh device group / CPU slice), and the seam is
identical: ``submit`` returns a future of materialized partitions, so a
multi-host gRPC worker drops in without touching the scheduler.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..micropartition import MicroPartition
from ..physical import plan as pp


@dataclass
class StageTask:
    """One dispatchable unit: an exchange-free plan fragment plus its
    stage-input bindings (flotilla's SwordfishTask shape,
    ``scheduling/task.rs:80``)."""

    stage_id: int
    plan: pp.PhysicalPlan
    stage_inputs: Dict[int, List[MicroPartition]]
    task_idx: int = 0
    preferred_worker: Optional[str] = None


class Worker:
    """Abstract worker: executes StageTasks, reports capacity."""

    id: str
    num_slots: int

    def submit(self, task: StageTask) -> "cf.Future[List[MicroPartition]]":
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InProcessWorker(Worker):
    """Runs stage fragments on a local streaming executor (per-host worker
    in a pod deployment; the only worker type on a single host)."""

    def __init__(self, worker_id: str, num_slots: int = 2):
        self.id = worker_id
        self.num_slots = num_slots
        self._pool = cf.ThreadPoolExecutor(
            max_workers=num_slots, thread_name_prefix=f"daft-tpu-{worker_id}")

    def submit(self, task: StageTask) -> "cf.Future[List[MicroPartition]]":
        return self._pool.submit(self._run, task)

    @staticmethod
    def _run(task: StageTask) -> List[MicroPartition]:
        from ..execution.executor import LocalExecutor
        ex = LocalExecutor()
        return list(ex.run(task.plan, stage_inputs=task.stage_inputs))

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


@dataclass
class WorkerState:
    worker: Worker
    active: int = 0


class WorkerManager:
    """Tracks workers and in-flight load; routes submissions through a
    scheduling policy (reference: ``scheduling/worker.rs`` WorkerManager +
    dispatcher)."""

    def __init__(self, workers: List[Worker]):
        self._lock = threading.Lock()
        self.states: Dict[str, WorkerState] = {
            w.id: WorkerState(w) for w in workers}

    @property
    def worker_ids(self) -> List[str]:
        return list(self.states)

    def snapshot(self) -> List[WorkerState]:
        with self._lock:
            return list(self.states.values())

    def dispatch(self, task: StageTask, worker_id: str
                 ) -> "cf.Future[List[MicroPartition]]":
        with self._lock:
            st = self.states[worker_id]
            st.active += 1
        fut = st.worker.submit(task)

        def _done(_):
            with self._lock:
                st.active -= 1

        fut.add_done_callback(_done)
        return fut

    def shutdown(self) -> None:
        for st in self.snapshot():
            st.worker.shutdown()
